"""Shared run-provenance stamp for benchmark artifacts (MICROBENCH /
RLBENCH): this box is load-sensitive ±30%, so cross-run comparisons need
commit/time context attached to every artifact."""

from __future__ import annotations

import os
import subprocess
import time


def run_metadata() -> dict:
    def _git(*args):
        try:
            out = subprocess.run(
                ["git", *args],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, text=True, timeout=10)
            return out.stdout.strip()
        except Exception:
            return ""

    return {
        "commit": _git("rev-parse", "--short", "HEAD"),
        "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
        "hostname": os.uname().nodename,
        "cpus": os.cpu_count(),
    }
