"""Persistent AOT compile cache: jitted executables serialized across
process lifetimes (ROADMAP item 5, second half).

PR 13's compile observability showed where gang restarts and elastic
resizes stall: every new process re-traces the same jitted functions —
the `_DeviceOps` collective bodies, the paged-KV donated update, the
Trainer fused/grad/apply steps — for shape classes an identical process
compiled minutes earlier. This module closes the loop: the FIRST process
to compile a (seam, shape-class) pair exports the jitted function via
`jax.export` (StableHLO + calling convention, the only serialization
the runtime can rely on across jax minor versions) and stores the blob
in an on-disk session cache; every later process — a restarted gang
rank, an elastic-resize joiner, a fresh serve replica — deserializes
and skips the trace+compile entirely.

Key schema (sha256 over a JSON list, hex-truncated):

    [seam, *parts, runtime_fingerprint()]

* ``seam`` names the call site class ("collective", "serve.kv_update",
  "train.step") — the same names the compile spans carry.
* ``parts`` is the seam's own cache key: op kind, dtype, shape-class,
  axis name, world size — every compile-relevant input, nothing else.
* ``runtime_fingerprint()`` folds in jax/jaxlib/libtpu versions, the
  backend, the device kinds, and the process count: any of these
  changing invalidates EVERY entry (an executable compiled for another
  runtime must never load — fingerprint mismatch means a different
  key, which means a clean miss, never a wrong executable).

Failure semantics: the cache can only make things faster, never break
them. A load/deserialize failure counts `jax.compile_cache_errors_total`
and falls back to the normal trace+compile path; a store failure counts
the same and the op proceeds on the freshly-jitted function. The
`compile_cache.load` / `compile_cache.store` failpoints inject exactly
these faults in chaos tests. Writes are temp-file + os.replace so a
crashed writer leaves either a whole blob or a ``.ctmp-*`` stray (which
the test-suite leak check names), never a torn file.

The local JSON index (entry key -> seam/parts/size/created/hits) is
mirrored to the GCS KV under ``ray_tpu:compile_cache/index`` so the CLI
(`ray-tpu compile-cache`) and the doctor can see cache state without
touching the cache host's disk.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
import threading
import time

from ray_tpu._private import stats as _stats

# entries created before this moment predate the process: the doctor's
# compile_cache_cold finding keys off entries_preexisting, never off
# blobs this very process stored on its own first-ever misses (store()
# lives in this module, so any self-stored entry is created after this
# import ran)
_PROCESS_START = time.time()

M_HITS = _stats.Count(
    "jax.compile_cache_hits_total",
    "persistent compile-cache hits: a jitted executable deserialized "
    "from the on-disk AOT cache instead of re-tracing")
M_MISSES = _stats.Count(
    "jax.compile_cache_misses_total",
    "persistent compile-cache misses: no entry for the (seam, "
    "shape-class, runtime-fingerprint) key — the caller traced, "
    "compiled, and (best-effort) populated the cache")
M_ERRORS = _stats.Count(
    "jax.compile_cache_errors_total",
    "persistent compile-cache load/deserialize/store failures — every "
    "one degraded to a normal re-trace, never a user-visible error")
M_LOAD_S = _stats.Histogram(
    "jax.compile_cache_load_s", _stats.LATENCY_BOUNDARIES_S,
    "wall seconds to load + deserialize one cached executable (the "
    "re-trace time this hit avoided is jax.compile_s)")

# stray temp files carry this prefix so the conftest leak check can
# name them (a crashed writer is the only way one survives)
TMP_PREFIX = ".ctmp-"
INDEX_NAME = "index.json"
KV_INDEX_KEY = "ray_tpu:compile_cache/index"

_lock = threading.Lock()


def enabled() -> bool:
    """RAY_TPU_COMPILE_CACHE=0 turns the plane off (every call is a
    plain re-trace and nothing touches disk)."""
    return os.environ.get("RAY_TPU_COMPILE_CACHE", "1") not in (
        "0", "false", "no")


def cache_dir() -> str:
    d = os.environ.get("RAY_TPU_COMPILE_CACHE_DIR")
    if not d:
        d = os.path.join(tempfile.gettempdir(), "ray_tpu_compile_cache")
    return d


def runtime_fingerprint() -> str:
    """Every runtime fact a serialized executable depends on. Computed
    lazily (jax may not be imported in pure-host processes) and cached
    per process — but ONLY once the backend facts resolved: a key built
    before jax initialization must not pin 'uninit'/'nojax' for the
    process's whole life, or differently-topologized processes collide
    on keys after their backends come up."""
    global _fingerprint
    if _fingerprint is not None:
        return _fingerprint
    parts = []
    complete = True
    try:
        import jax

        parts.append(jax.__version__)
        try:
            import jaxlib

            parts.append(getattr(jaxlib, "__version__", "?"))
        except Exception:
            parts.append("?")
        try:
            parts.append(jax.default_backend())
            parts.append(",".join(sorted(
                {d.device_kind for d in jax.devices()})))
            parts.append(str(jax.process_count()))
        except Exception:
            parts.append("uninit")
            complete = False
        try:  # TPU boxes: the libtpu build changes lowering
            import libtpu  # type: ignore

            parts.append(getattr(libtpu, "__version__", "?"))
        except Exception:
            pass
    except Exception:
        parts.append("nojax")
        complete = False
    fp = "|".join(parts)
    if complete:
        _fingerprint = fp
    return fp


_fingerprint: str | None = None


def make_key(seam: str, parts) -> str:
    blob = json.dumps([seam, list(map(str, parts)),
                       runtime_fingerprint()], sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


# ---------------------------------------------------------------------------
# blob + index storage
# ---------------------------------------------------------------------------


def _blob_path(key: str) -> str:
    return os.path.join(cache_dir(), key + ".jaxexp")


def _index_path() -> str:
    return os.path.join(cache_dir(), INDEX_NAME)


def _read_index() -> dict:
    try:
        with open(_index_path(), "r", encoding="utf-8") as f:
            out = json.load(f)
        return out if isinstance(out, dict) else {}
    except Exception:
        return {}


def _write_index(index: dict) -> None:
    """Atomic local write, then best-effort GCS KV mirror (the CLI and
    doctor read the mirror; the cache itself only trusts the disk)."""
    d = cache_dir()
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=TMP_PREFIX, dir=d)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(index, f)
        os.replace(tmp, _index_path())
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        from ray_tpu.experimental import internal_kv

        internal_kv._kv_put(KV_INDEX_KEY,
                            json.dumps(index).encode())
    except Exception:
        pass  # no GCS (unit test / pure-local): disk is authoritative


@contextlib.contextmanager
def _index_lock():
    """Thread lock + OS file lock around the index read-modify-write:
    the cache dir is shared by every rank on the host (the normal
    multi-rank-per-host case), so an in-process lock alone loses index
    entries and hit counts to last-writer-wins races across processes.
    Degrades to thread-only locking where flock is unavailable."""
    with _lock:
        lockf = None
        try:
            import fcntl

            d = cache_dir()
            os.makedirs(d, exist_ok=True)
            lockf = open(os.path.join(d, INDEX_NAME + ".lock"), "a")
            fcntl.flock(lockf, fcntl.LOCK_EX)
        except Exception:
            if lockf is not None:
                lockf.close()
                lockf = None
        try:
            yield
        finally:
            if lockf is not None:
                try:
                    import fcntl

                    fcntl.flock(lockf, fcntl.LOCK_UN)
                except Exception:
                    pass
                lockf.close()


def _index_update(key: str, **fields) -> None:
    with _index_lock():
        index = _read_index()
        entry = index.setdefault(key, {"hits": 0})
        entry.update(fields)
        _write_index(index)


def read_index(prefer_kv: bool = False) -> dict:
    """The CLI entry point: the KV mirror when reachable (cluster-wide
    view), else the local disk index."""
    if prefer_kv:
        try:
            from ray_tpu.experimental import internal_kv

            raw = internal_kv._kv_get(KV_INDEX_KEY)
            if raw:
                out = json.loads(raw.decode())
                if isinstance(out, dict):
                    return out
        except Exception:
            pass
    return _read_index()


def lookup(key: str) -> bytes | None:
    """The serialized executable for `key`, or None (absent OR load
    failure — the caller re-traces either way; only the counter
    differs)."""
    if not enabled():
        return None
    from ray_tpu._private import failpoints as _fp

    path = _blob_path(key)
    try:
        if _fp.ARMED:
            _fp.fire_strict("compile_cache.load")
        with open(path, "rb") as f:
            return f.read()
    except FileNotFoundError:
        return None
    except Exception:
        M_ERRORS.inc()
        return None


def store(key: str, blob: bytes, seam: str = "", parts=()) -> bool:
    """Best-effort atomic store + index update. False (and an error
    count) on any failure — the caller's freshly-jitted function is
    already the fallback."""
    if not enabled():
        return False
    from ray_tpu._private import failpoints as _fp

    d = cache_dir()
    try:
        if _fp.ARMED:
            _fp.fire_strict("compile_cache.store")
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=TMP_PREFIX, dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, _blob_path(key))
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _index_update(key, seam=seam,
                      parts=[str(p) for p in parts],
                      size=len(blob), created=time.time())
        return True
    except Exception:
        M_ERRORS.inc()
        return False


def record_hit(key: str) -> None:
    try:
        with _index_lock():
            index = _read_index()
            if key in index:
                index[key]["hits"] = int(index[key].get("hits", 0)) + 1
                _write_index(index)
    except Exception:
        pass


def clear() -> int:
    """Remove every blob + the index (local and KV mirror); returns the
    number of entries removed. The CLI's --clear."""
    d = cache_dir()
    n = 0
    with _index_lock():
        try:
            for name in os.listdir(d):
                if name.endswith(".jaxexp") or name == INDEX_NAME \
                        or name.startswith(TMP_PREFIX):
                    if name.endswith(".jaxexp"):
                        n += 1
                    try:
                        os.unlink(os.path.join(d, name))
                    except OSError:
                        pass
        except FileNotFoundError:
            pass
        try:
            from ray_tpu.experimental import internal_kv

            internal_kv._kv_del(KV_INDEX_KEY)
        except Exception:
            pass
    return n


def state() -> dict:
    """Cache-plane summary for debug_state snapshots and the doctor's
    cold-restart finding. `entries_preexisting` counts only entries
    created BEFORE this process started — the index also holds blobs
    this very process stored on its own misses, and a first-ever cold
    process (misses>0, hits==0, entries>0) must not read as 'restart
    re-traced despite a warm cache'."""
    index = _read_index()
    preexisting = sum(
        1 for e in index.values()
        if isinstance(e, dict)
        and float(e.get("created") or 0.0) > 0.0
        and float(e["created"]) < _PROCESS_START)
    return {
        "enabled": enabled(),
        "dir": cache_dir(),
        "entries": len(index),
        "entries_preexisting": preexisting,
        "hits": int(M_HITS.snapshot()["value"]),
        "misses": int(M_MISSES.snapshot()["value"]),
        "errors": int(M_ERRORS.snapshot()["value"]),
    }


# ---------------------------------------------------------------------------
# the seam wrapper
# ---------------------------------------------------------------------------


class CachedFunction:
    """One jitted callable behind the persistent cache.

    Resolution happens on the FIRST call (the args fix the trace):

    * hit  — deserialize the stored `jax.export` blob, re-wrap with
      `jax.jit(exported.call, donate_argnums=...)` (donation is a
      call-site property the serialized module does not carry), count a
      hit + load seconds, and DO NOT record a compile — the whole point
      is that `jax.compiles_total` stays flat on a warm restart.
      Donating seams AOT-compile the deserialized module BEFORE the
      first dispatch: executing a donated jit consumes its input
      buffers, so a stale/incompatible blob must fail while re-trace
      is still possible, not after the inputs are gone.
    * miss — export + store FIRST (executing a donated jit consumes its
      input buffers; exporting only traces), then dispatch the normal
      jitted function and record the compile exactly as the seam did
      before this cache existed.

    Either way later calls go through one resolved function attribute —
    the wrapper adds a single `is None` check to the steady state."""

    def __init__(self, seam: str, parts, jitted, donate_argnums=(),
                 record_key: str | None = None,
                 fingerprint_computation: bool = False):
        self.seam = seam
        self.parts = tuple(parts)
        self.donate_argnums = tuple(donate_argnums)
        self._jitted = jitted
        self._record_key = record_key or (
            seam + ":" + ":".join(map(str, parts)))
        # seams whose computation is USER code (Trainer steps: loss_fn,
        # optimizer) fold a jaxpr hash into the key — two models with
        # identical shapes must never share an executable. One extra
        # trace (no compile) per resolution; runtime-owned seams whose
        # key already pins the computation (op kind) skip it.
        self._fp_computation = fingerprint_computation
        self._fn = None
        self._lock = threading.Lock()
        self.resolved: str | None = None  # "hit" | "miss" | "disabled"

    def __call__(self, *args):
        fn = self._fn
        if fn is not None:
            return fn(*args)
        with self._lock:
            if self._fn is not None:
                return self._fn(*args)
            return self._resolve(args)

    def _resolve(self, args):
        if not enabled():
            self.resolved = "disabled"
            return self._first_dispatch(args, record=True)
        parts = self.parts
        if self._fp_computation:
            try:
                import jax

                jaxpr = jax.make_jaxpr(self._jitted)(*args)
                parts = parts + (hashlib.sha256(
                    str(jaxpr).encode()).hexdigest()[:16],)
            except Exception:
                # can't prove computation identity -> never share
                M_ERRORS.inc()
                self.resolved = "disabled"
                return self._first_dispatch(args, record=True)
        key = make_key(self.seam, parts)
        blob = lookup(key)
        if blob is not None:
            t0 = time.time()
            fn = None
            try:
                import jax
                from jax import export as _export

                exported = _export.deserialize(bytearray(blob))
                fn = jax.jit(exported.call,
                             donate_argnums=self.donate_argnums)
                if self.donate_argnums:
                    # dispatching a donated jit consumes the input
                    # buffers — AOT-compile the deserialized module
                    # first so a stale/corrupt/incompatible blob fails
                    # HERE, with the inputs intact and the re-trace
                    # fallback below still possible
                    fn = fn.lower(*args).compile()
            except Exception:
                # a stale/corrupt/incompatible blob: typed error count,
                # then the normal trace path — never user-visible
                M_ERRORS.inc()
                fn = None
            if fn is not None:
                try:
                    out = fn(*args)
                except Exception:
                    M_ERRORS.inc()
                    if self.donate_argnums:
                        # the executable compiled but failed at RUN
                        # time with the inputs already donated; the
                        # fallback would dispatch on deleted buffers —
                        # surface the real execution error instead
                        raise
                    fn = None
                else:
                    self._fn = fn
                    self.resolved = "hit"
                    M_HITS.inc()
                    M_LOAD_S.observe(time.time() - t0)
                    record_hit(key)
                    return out
        M_MISSES.inc()
        self.resolved = "miss"
        try:
            from jax import export as _export

            blob = _export.export(self._jitted)(*args).serialize()
            store(key, blob, seam=self.seam, parts=parts)
        except Exception:
            M_ERRORS.inc()
        return self._first_dispatch(args, record=True)

    def _first_dispatch(self, args, record: bool):
        from ray_tpu._private import profiling as _profiling

        t0 = time.time()
        out = self._jitted(*args)
        if record:
            _profiling.record_compile(self._record_key, t0, time.time())
        self._fn = self._jitted
        return out
