"""Live cluster state introspection + stall doctor (flight recorder).

Every runtime process class (driver/worker core worker, raylet, GCS
director + store shards, serve controller/proxy/replica actors,
collective groups) exposes a cheap `debug_state()` snapshot of its
in-flight work — per-task stage with age, lease tables, transfer
streams/pins, collective ops with phase, rpc conn depth, event-loop lag
— plus a `debug_stacks()` all-thread Python stack dump (via
`sys._current_frames`, the `py-spy dump` analog with no ptrace).
Snapshots aggregate over the existing rpc/GCS plane into
`api.cluster_state()`, the dashboard `/api/state` endpoint, and the
`ray-tpu state|stack|doctor` CLI (reference analog: the reference
raylet's DebugString() dumps + the Ray state API,
python/ray/util/state).

The **stall doctor** (`diagnose`) cross-references live state against
the per-hop latency histograms the cluster already records (PR 6):
anything whose age exceeds max(floor, K×p99) for its stage is flagged
with its trace id and owning process, so a wedged cluster answers
"which in-flight thing is stuck, where, and on what stack" without a
reproduction run. Findings also flow as deduped WARNING events through
_private/events.py so `/api/events` surfaces stalls without polling.

Wire discipline: snapshots travel over the msgpack rpc layer — only
str/int/float/bool/bytes/list/dict, ids hex-encoded, never sets.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

from ray_tpu._private import stats as _stats

# Satellite gauges (ARCHITECTURE.md metrics-registry table; tier-1 drift
# gate): sampled event-loop responsiveness per process, and the cost of
# the last debug_state collection — the doctor's own overhead must be
# observable through the same plane it reads.
M_LOOP_LAG = _stats.Gauge(
    "proc.event_loop_lag_s",
    "sampled event-loop lag: scheduled-wakeup overshoot of the process's "
    "main asyncio loop (a wedged/overloaded loop reads as a rising lag)")
M_STATE_COLLECT = _stats.Gauge(
    "debug.state_collect_s",
    "wall time of this process's last debug_state() collection")

# Default doctor knobs (api.doctor accepts overrides; env for the CLI).
DOCTOR_FLOOR_S = float(os.environ.get("RAY_TPU_DOCTOR_FLOOR_S", "1.0"))
DOCTOR_P99_FACTOR = float(os.environ.get("RAY_TPU_DOCTOR_P99_K", "3.0"))
# compile-storm finding: >= this many jit compiles within the last 60s
# (with >= floor_s of wall time behind them) flags the process
COMPILE_STORM_MIN = int(os.environ.get("RAY_TPU_DOCTOR_COMPILE_STORM_MIN",
                                       "4"))
# prefix_cold finding: an engine whose prefix tree has nodes and at
# least this many lookups but ZERO hits flags mis-aligned page hashing
PREFIX_COLD_MIN_LOOKUPS = int(os.environ.get(
    "RAY_TPU_DOCTOR_PREFIX_COLD_MIN", "32"))

# stage -> latency histogram whose p99 scales the stall threshold (the
# PR 6 per-hop histograms; stages with no histogram gate on the floor)
STAGE_HISTOGRAMS = {
    "lease_wait": "core.task_lease_wait_s",
    "queued": "core.task_queue_wait_s",
    "executing": "core.task_e2e_s",
    "exec": "core.task_exec_s",
    "raylet_queue": "raylet.lease_grant_s",
    "router_queue": "serve.router_queue_s",
    "decode_step": "serve.decode_step_s",
}


# ---------------------------------------------------------------------------
# per-process primitives
# ---------------------------------------------------------------------------


def start_loop_lag_monitor(interval: float = 0.5):
    """Start the sampled event-loop lag gauge on the CURRENT running
    loop (idempotent per loop). Schedules a callback `interval` ahead
    and records how late it actually ran — a busy or wedged loop shows
    up as lag without any per-callback instrumentation."""
    import asyncio

    loop = asyncio.get_running_loop()
    if getattr(loop, "_ray_tpu_lag_monitor", False):
        return
    loop._ray_tpu_lag_monitor = True

    def _tick(expected: float):
        M_LOOP_LAG.set(max(0.0, loop.time() - expected))
        if not loop.is_closed():
            loop.call_later(interval, _tick, loop.time() + interval)

    loop.call_later(interval, _tick, loop.time() + interval)


def collect_stacks() -> dict:
    """All-thread Python stacks of THIS process (sys._current_frames).
    Cheap and lock-free; the returned dict is msgpack-safe."""
    frames = sys._current_frames()
    names = {t.ident: t for t in threading.enumerate()}
    threads = []
    for tid, frame in frames.items():
        t = names.get(tid)
        threads.append({
            "thread_id": tid,
            "name": t.name if t is not None else f"tid-{tid}",
            "daemon": bool(t.daemon) if t is not None else False,
            "stack": "".join(traceback.format_stack(frame)),
        })
    threads.sort(key=lambda r: r["name"])
    return {"pid": os.getpid(), "threads": threads,
            "collected_at": time.time()}


def finish_snapshot(snap: dict, t_start: float) -> dict:
    """Stamp shared trailer fields + the collection-latency gauge."""
    dt = time.monotonic() - t_start
    M_STATE_COLLECT.set(dt)
    snap["pid"] = os.getpid()
    snap["collected_at"] = time.time()
    snap["collect_s"] = dt
    snap["event_loop_lag_s"] = M_LOOP_LAG.snapshot()["value"]
    return snap


def conn_depth(conn) -> int:
    """In-flight request count on one rpc.Connection (0 for anything
    else — ReconnectingConnection exposes its live conn)."""
    inner = getattr(conn, "_conn", conn)
    pending = getattr(inner, "_pending", None)
    return len(pending) if pending is not None else 0


def bounded(obj, max_items: int = 40, max_str: int = 4000, depth: int = 6):
    """Truncate a snapshot for attachment to a raised error: hangs must
    become self-describing without shipping megabytes inside exceptions."""
    if depth <= 0:
        return "..."
    if isinstance(obj, dict):
        out = {}
        for i, (k, v) in enumerate(obj.items()):
            if i >= max_items:
                out["..."] = f"(+{len(obj) - max_items} more)"
                break
            out[k] = bounded(v, max_items, max_str, depth - 1)
        return out
    if isinstance(obj, (list, tuple)):
        out = [bounded(v, max_items, max_str, depth - 1)
               for v in obj[:max_items]]
        if len(obj) > max_items:
            out.append(f"(+{len(obj) - max_items} more)")
        return out
    if isinstance(obj, str) and len(obj) > max_str:
        return obj[:max_str] + "...(truncated)"
    if isinstance(obj, bytes):
        return obj[:32].hex() + ("..." if len(obj) > 32 else "")
    return obj


# ---------------------------------------------------------------------------
# cluster-wide collection (shared by the driver API and the CLI)
# ---------------------------------------------------------------------------


async def collect_cluster_state_async(gcs_call, peer_dial, *,
                                      include_workers: bool = True,
                                      timeout: float = 5.0) -> dict:
    """Aggregate debug_state across the cluster over the existing rpc
    plane. `gcs_call(method, data)` awaits a GCS director call;
    `peer_dial(address)` awaits a connected rpc.Connection to a raylet.
    Unreachable components degrade to an {"error": ...} entry — a
    snapshot of a sick cluster must never hang on the sick part."""
    import asyncio

    out = {"collected_at": time.time(), "nodes": {}}
    try:
        out["gcs"] = await asyncio.wait_for(
            gcs_call("debug_state", {}), timeout)
    except Exception as e:
        out["gcs"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        nodes = await asyncio.wait_for(gcs_call("get_all_nodes", {}),
                                       timeout)
    except Exception as e:
        out["nodes_error"] = f"{type(e).__name__}: {e}"
        return out

    async def one(n):
        nid = n["node_id"].hex()[:8]
        try:
            conn = await asyncio.wait_for(peer_dial(n["address"]), timeout)
            state = await asyncio.wait_for(
                conn.call("debug_state",
                          {"include_workers": include_workers}), timeout)
            return nid, state
        except Exception as e:
            return nid, {"error": f"{type(e).__name__}: {e}",
                         "address": n["address"]}

    got = await asyncio.gather(*(one(n) for n in nodes))
    out["nodes"] = dict(got)
    return out


def collect_via_rpc(gcs_address: str, *, include_workers: bool = True,
                    timeout: float = 5.0) -> dict:
    """Blocking cluster_state collection for out-of-process callers (the
    CLI): dials the GCS directly, no driver runtime required."""
    import asyncio

    from ray_tpu._private import rpc

    async def _go():
        gcs = await rpc.connect(gcs_address, name="state-cli", timeout=5)
        peers = {}
        try:
            async def gcs_call(method, data):
                return await gcs.call(method, data, timeout=timeout)

            async def peer_dial(address):
                conn = peers.get(address)
                if conn is None or conn.closed:
                    conn = peers[address] = await rpc.connect(
                        address, name="state-cli")
                return conn

            return await collect_cluster_state_async(
                gcs_call, peer_dial, include_workers=include_workers,
                timeout=timeout)
        finally:
            for conn in peers.values():
                await conn.close()
            await gcs.close()

    return asyncio.run(_go())


# ---------------------------------------------------------------------------
# flattening (the `ray-tpu state <component>` tables)
# ---------------------------------------------------------------------------

COMPONENTS = ("serve", "placement", "tasks", "actors", "objects",
              "leases", "transfers",
              "collectives")


def iter_processes(snapshot: dict):
    """Yield (component_label, process_state) for every process-level
    snapshot inside a cluster_state() result."""
    if isinstance(snapshot.get("driver"), dict):
        yield "driver", snapshot["driver"]
    gcs = snapshot.get("gcs")
    if isinstance(gcs, dict):
        yield "gcs", gcs
        for idx, shard in enumerate(gcs.get("shards") or []):
            if isinstance(shard, dict):
                yield f"gcs-shard{idx}", shard
    for nid, node in (snapshot.get("nodes") or {}).items():
        if not isinstance(node, dict):
            continue
        yield f"{nid}/raylet", node
        for wid, w in (node.get("workers") or {}).items():
            if isinstance(w, dict):
                yield f"{nid}/worker-{w.get('pid', wid)}", w
        for did, d in (node.get("drivers") or {}).items():
            if isinstance(d, dict):
                yield f"{nid}/driver-{d.get('pid', did)}", d


def flatten(snapshot: dict, component: str) -> list[dict]:
    """Flat per-item rows for one component class across every process
    in a cluster_state() snapshot."""
    if component not in COMPONENTS:
        raise ValueError(f"unknown component {component!r} "
                         f"(expected one of {COMPONENTS})")
    rows: list[dict] = []
    for label, proc in iter_processes(snapshot):
        if component == "tasks":
            for t in proc.get("tasks") or []:
                rows.append({"process": label, **t})
            for t in proc.get("executing") or []:
                rows.append({"process": label, "stage": "exec", **t})
        elif component == "actors":
            for a in proc.get("actors") or []:
                rows.append({"process": label, **a})
        elif component == "objects":
            om = proc.get("objects")
            if om:
                rows.append({"process": label, **om})
        elif component == "leases":
            for l in proc.get("leases") or []:
                rows.append({"process": label, **l})
            for l in proc.get("pending_leases") or []:
                rows.append({"process": label, "stage": "raylet_queue",
                             **l})
        elif component == "transfers":
            tr = proc.get("transfers")
            for kind in ("pulls", "serves"):
                for t in (tr or {}).get(kind) or []:
                    rows.append({"process": label, "kind": kind[:-1], **t})
            if tr and tr.get("pins"):
                rows.append({"process": label, "kind": "pins",
                             "pins": tr["pins"]})
        elif component == "collectives":
            for g in proc.get("collectives") or []:
                rows.append({"process": label, **g})
        elif component == "placement":
            # per-pg bundle->node rows with topology coords and the
            # chosen strategy/cost-model (GCS placement_table)
            for row in proc.get("placement_table") or []:
                rows.append({"process": label, **row})
        elif component == "serve":
            # per-router admission rows: queue depth vs bound, shed and
            # admitted totals (shed RATE comes from the metrics history;
            # these are the live instantaneous truth)
            for r in proc.get("routers") or []:
                rows.append({
                    "process": label, "kind": "router",
                    "endpoint": r.get("endpoint"),
                    "queued": r.get("queued"),
                    "max_queued": r.get("max_queued"),
                    "shed_total": r.get("shed_total"),
                    "admitted_total": r.get("admitted_total"),
                    "streams_open": r.get("streams_open"),
                    "sessions": r.get("sessions"),
                    "age_s": r.get("oldest_age_s"),
                    "inflight": r.get("inflight_batches"),
                })
            comp = proc.get("component")
            if isinstance(comp, dict) and comp.get("kind", "").startswith(
                    "serve-"):
                row = {"process": label, "kind": comp.get("kind"),
                       **{k: v for k, v in comp.items()
                          if k not in ("kind", "engine")}}
                eng = comp.get("engine")
                if isinstance(eng, dict):
                    # decode-engine occupancy: batch fill, stream
                    # backlog, per-session page counts, leak report —
                    # the `ray-tpu state serve` streaming-tier rows
                    row.update({
                        "decode_batch": f"{eng.get('decode_batch')}"
                                        f"/{eng.get('max_decode_batch')}",
                        "waiting": eng.get("waiting"),
                        "steps": eng.get("steps"),
                        "open_streams": eng.get("open_streams"),
                        "stream_backlog": eng.get("stream_backlog"),
                        "kv_pages": f"{(eng.get('kv') or {}).get('pages_in_use')}"
                                    f"/{(eng.get('kv') or {}).get('pages_total')}",
                        "sessions": eng.get("sessions"),
                        "age_s": eng.get("stall_age_s"),
                        "kv_leaked": eng.get("kv_leaked") or "",
                        "engine_dead": eng.get("dead") or "",
                    })
                    pref = (eng.get("kv") or {}).get("prefix") or {}
                    if pref.get("enabled"):
                        # prefix-tree occupancy: node fill, pages held
                        # by >1 owner, and the adoption hit-rate — the
                        # KV-economy health row
                        kv = eng.get("kv") or {}
                        row.update({
                            "prefix_nodes": f"{pref.get('nodes')}"
                                            f"/{pref.get('max_nodes')}",
                            "kv_shared": kv.get("pages_shared"),
                            "kv_cached": kv.get("pages_cached"),
                            "prefix_hit_rate": pref.get("hit_rate"),
                        })
                rows.append(row)
    rows.sort(key=lambda r: -float(r.get("age_s") or 0.0))
    return rows


# ---------------------------------------------------------------------------
# the stall doctor
# ---------------------------------------------------------------------------


def _merged_p99(metrics: dict,
                exemplars: dict | None = None) -> dict[str, float]:
    """p99 per histogram name, merged across every process snapshot in a
    cluster_metrics() result (raylets already fold worker snapshots in).
    With `exemplars` (a dict to fill), also merges each histogram's
    best p99 exemplar — the trace id a finding can print when the live
    item itself is untraced."""
    merged: dict[str, dict] = {}

    def fold(snap):
        for name, m in (snap or {}).items():
            if not isinstance(m, dict) or m.get("type") != "histogram":
                continue
            if exemplars is not None and m.get("exemplars"):
                ex = _stats.quantile_exemplar(m, 0.99)
                cur_ex = exemplars.get(name)
                if ex is not None and (cur_ex is None
                                       or ex["value"] >= cur_ex["value"]):
                    exemplars[name] = ex
            cur = merged.get(name)
            if cur is None:
                merged[name] = {"boundaries": m.get("boundaries") or [],
                                "counts": list(m.get("counts") or []),
                                "count": m.get("count", 0)}
            elif cur["boundaries"] == (m.get("boundaries") or []):
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], m.get("counts") or [])]
                cur["count"] += m.get("count", 0)

    fold(metrics.get("gcs"))
    # "driver": the calling process's own registry (api.doctor adds it —
    # the submit-side task histograms live in the OWNER process, so
    # without this fold the lease_wait/queued/executing thresholds would
    # never see their stage's p99). Raylet snapshots already fold their
    # workers' and connected drivers' registries in.
    fold(metrics.get("driver"))
    for snap in (metrics.get("raylets") or {}).values():
        fold(snap)
    return {name: _stats.percentile(m, 0.99) for name, m in merged.items()}


def _threshold(stage: str, p99s: dict, floor_s: float, k: float) -> float:
    hist = STAGE_HISTOGRAMS.get(stage)
    p99 = p99s.get(hist, 0.0) if hist else 0.0
    return max(floor_s, k * p99)


def diagnose(snapshot: dict, metrics: dict | None = None, *,
             floor_s: float = None, p99_factor: float = None) -> list[dict]:
    """Cross-reference a cluster_state() snapshot against the per-hop
    latency histograms: every in-flight item whose age exceeds
    max(floor, K×p99-of-its-stage) becomes a finding naming its stage,
    age, owning process and (when traced) trace id. Pure function — no
    IO, so it runs identically in the driver, the CLI, and tests."""
    floor_s = DOCTOR_FLOOR_S if floor_s is None else float(floor_s)
    k = DOCTOR_P99_FACTOR if p99_factor is None else float(p99_factor)
    exemplars: dict[str, dict] = {}
    p99s = _merged_p99(metrics or {}, exemplars)
    findings: list[dict] = []

    def flag(kind, proc, stage, age, item, detail=""):
        if age is None:
            return
        limit = _threshold(stage, p99s, floor_s, k)
        if age <= limit:
            return
        trace_id = item.get("trace_id") or ""
        trace_source = "item" if trace_id else ""
        if not trace_id:
            # untraced item: fall back to the stage histogram's p99
            # EXEMPLAR — one real outlier of the same stage whose span
            # tree `ray-tpu trace --trace-id` resolves
            hist = STAGE_HISTOGRAMS.get(stage)
            ex = exemplars.get(hist) if hist else None
            if ex is not None:
                trace_id, trace_source = ex["trace_id"], "exemplar"
        findings.append({
            "kind": kind,
            "process": proc,
            "stage": stage,
            "age_s": round(float(age), 3),
            "threshold_s": round(limit, 3),
            "trace_id": trace_id,
            "trace_source": trace_source,
            "id": item.get("task_id") or item.get("object_id")
                  or item.get("group") or item.get("lease_id") or "",
            "name": (item.get("name") or item.get("op")
                     or item.get("endpoint") or ""),
            "detail": detail,
        })

    for label, proc in iter_processes(snapshot):
        for t in proc.get("tasks") or []:
            flag("task", label, t.get("stage", "executing"),
                 t.get("age_s"), t,
                 detail=f"lease={t.get('lease_worker', '')}")
        for t in proc.get("executing") or []:
            flag("task", label, "exec", t.get("age_s"), t,
                 detail=f"thread={t.get('thread', '')}")
        for l in proc.get("pending_leases") or []:
            flag("lease", label, "raylet_queue", l.get("age_s"), l)
        for q in proc.get("router_queues") or []:
            flag("query", label, "router_queue", q.get("age_s"), q,
                 detail=f"endpoint={q.get('endpoint', '')}")
        tr = proc.get("transfers") or {}
        for kind in ("pulls", "serves"):
            for t in tr.get(kind) or []:
                flag("transfer", label, "transfer", t.get("age_s"), t,
                     detail=f"{kind[:-1]} {t.get('progress', '')}")
        for g in proc.get("collectives") or []:
            if g.get("op"):
                flag("collective", label, "collective", g.get("age_s"), g,
                     detail=f"phase={g.get('phase', '')} "
                            f"rank={g.get('rank')}")
        comp = proc.get("component")
        eng = comp.get("engine") if isinstance(comp, dict) else None
        if isinstance(eng, dict) and eng.get("stall_age_s") is not None \
                and not eng.get("dead"):
            # a decode engine with running sequences whose last step
            # age exceeds the decode-stage threshold is a WEDGED decode
            # loop (stuck allreduce, dead follower the leader hasn't
            # typed yet) — the stall doctor's streaming-tier finding
            flag("decode", label, "decode_step", eng.get("stall_age_s"),
                 {"name": eng.get("backend")},
                 detail=f"batch={eng.get('decode_batch')} "
                        f"open_streams={eng.get('open_streams')} "
                        f"steps={eng.get('steps')}")
        pref = ((eng.get("kv") or {}).get("prefix") or {}) \
            if isinstance(eng, dict) else {}
        if (pref.get("enabled") and pref.get("nodes", 0) > 0
                and pref.get("lookups", 0) >= PREFIX_COLD_MIN_LOOKUPS
                and pref.get("hits", 0) == 0):
            # prefix_cold: the tree holds indexed pages and plenty of
            # admissions walked it, yet NOTHING ever matched — the
            # classic symptom of mis-aligned page hashing (router and
            # engine disagree on kv_page_size, or prompts are tokenized
            # differently per session so no page boundary ever lines
            # up). A hot shared prefix is paying full prefill N times.
            # Age-less (a property of the workload, not a stall).
            findings.append({
                "kind": "prefix_cold",
                "process": label,
                "stage": "kv_prefix",
                "age_s": 0.0,
                "threshold_s": 0.0,
                "trace_id": "",
                "trace_source": "",
                "id": "",
                "name": (eng.get("backend", "")
                         if isinstance(eng, dict) else ""),
                "detail": (f"{pref['lookups']} prefix lookups with 0 "
                           f"hits despite {pref['nodes']} indexed "
                           f"nodes: likely mis-aligned page hashing "
                           f"(page-size mismatch or non-page-aligned "
                           f"shared prefix)"),
            })
        compiles = proc.get("jax_compiles")
        if (isinstance(compiles, dict)
                and compiles.get("recent_60s", 0) >= COMPILE_STORM_MIN
                and compiles.get("recent_s", 0.0) >= floor_s):
            # recompile storm: many compile events in the last minute
            # with real wall time behind them — a shape-churning loader
            # or a cache-thrashing collective, not a wedged item
            findings.append({
                "kind": "compile_storm",
                "process": label,
                "stage": "compile",
                "age_s": round(float(compiles["recent_s"]), 3),
                "threshold_s": round(floor_s, 3),
                "trace_id": "",
                "trace_source": "",
                "id": "",
                "name": compiles.get("last_key", ""),
                "detail": (f"{compiles['recent_60s']} compiles in 60s "
                           f"({compiles['recent_s']:.1f}s wall, "
                           f"{compiles.get('total', 0)} total)"),
            })
        cache = proc.get("compile_cache")
        if (isinstance(cache, dict) and cache.get("enabled")
                and cache.get("entries_preexisting", 0) > 0
                and cache.get("misses", 0) > 0
                and cache.get("hits", 0) == 0):
            # compile_cache_cold: this process re-traced even though a
            # warm on-disk cache PREDATING the process exists — a
            # fingerprint drift (jax upgrade, topology change) or a
            # key-schema mismatch; the restart paid the re-trace storm
            # the cache exists to prevent. Gating on preexisting
            # entries (not total: the index also holds blobs this very
            # process just stored on its own misses) keeps a first-ever
            # cold process from false-positiving. Age-less (a property
            # of the process, not a stall).
            findings.append({
                "kind": "compile_cache_cold",
                "process": label,
                "stage": "compile",
                "age_s": 0.0,
                "threshold_s": 0.0,
                "trace_id": "",
                "trace_source": "",
                "id": "",
                "name": cache.get("dir", ""),
                "detail": (f"{cache['misses']} cache misses with 0 hits "
                           f"despite {cache['entries_preexisting']} "
                           f"stored executables predating the process "
                           f"(errors={cache.get('errors', 0)}): "
                           f"restart re-traced despite a warm cache"),
            })
        # topology_mismatch: a CREATED gang whose members span ICI
        # slices — its collectives pay DCN on every op even though a
        # same-slice placement may exist; age-less (a property of the
        # placement, not a stall)
        for pg, rows in _pgs_by_id(proc.get("placement_table")).items():
            slices = {r.get("slice") for r in rows if r.get("slice")}
            if len(slices) > 1:
                findings.append({
                    "kind": "placement_group",
                    "process": label,
                    "stage": "topology_mismatch",
                    "age_s": 0.0,
                    "threshold_s": 0.0,
                    "trace_id": "",
                    "trace_source": "",
                    "id": pg,
                    "name": rows[0].get("name", ""),
                    "detail": (f"gang spans slices "
                               f"{sorted(slices)} "
                               f"(strategy={rows[0].get('strategy')}): "
                               f"collective ops cross DCN"),
                })
    findings.sort(key=lambda f: -f["age_s"])
    return findings


def _pgs_by_id(table) -> dict[str, list[dict]]:
    """Group GCS placement_table bundle rows by pg id (CREATED rows
    only — pending/infeasible rows carry no bundle geometry)."""
    out: dict[str, list[dict]] = {}
    for row in table or []:
        if row.get("state") == "CREATED" and "bundle" in row:
            out.setdefault(row.get("pg", "?"), []).append(row)
    return out


# Doctor findings dedup (satellite: one WARNING event per stalled trace,
# not one per 1s doctor tick). Keyed by trace id when present, else by
# (process, kind, id, name, stage) — name matters because untraced
# pending-lease/router rows carry no id, and collapsing every such row
# on a process into one forever-entry would swallow distinct stalls.
# Entries EXPIRE (STALL_EVENT_TTL_S): a stall still live after the TTL
# re-announces rather than staying silent for the process lifetime.
STALL_EVENT_TTL_S = float(os.environ.get("RAY_TPU_STALL_EVENT_TTL_S",
                                         "300"))
_stall_events_seen: dict = {}  # key -> monotonic ts of last emit
_stall_seen_lock = threading.Lock()


def stall_event_key(finding: dict) -> tuple:
    tid = finding.get("trace_id")
    if tid:
        return ("trace", tid)
    return (finding.get("process"), finding.get("kind"),
            finding.get("id"), finding.get("name"),
            finding.get("stage"))


def novel_findings(findings: list[dict]) -> list[dict]:
    """Filter findings to those not recently reported (dedup + TTL)."""
    out = []
    now = time.monotonic()
    with _stall_seen_lock:
        if len(_stall_events_seen) > 10_000:
            _stall_events_seen.clear()
        for f in findings:
            key = stall_event_key(f)
            last = _stall_events_seen.get(key)
            if last is not None and now - last < STALL_EVENT_TTL_S:
                continue
            _stall_events_seen[key] = now
            out.append(f)
    return out


def reset_stall_dedup():
    with _stall_seen_lock:
        _stall_events_seen.clear()


def make_stall_event(finding: dict) -> dict:
    """Structured WARNING event payload for one doctor finding (ships to
    the GCS events ring via report_event)."""
    from ray_tpu._private import events

    msg = (f"{finding['kind']} {finding.get('name') or finding.get('id')} "
           f"stalled in {finding['stage']} for {finding['age_s']:.1f}s "
           f"(threshold {finding['threshold_s']:.1f}s) on "
           f"{finding['process']}")
    return {
        "timestamp": time.time(),
        "severity": events.WARNING,
        "label": "STALL_DETECTED",
        "message": msg,
        "source_type": "doctor",
        "source_id": finding["process"],
        "source_pid": os.getpid(),
        "custom_fields": {k: v for k, v in finding.items()},
    }


# ---------------------------------------------------------------------------
# final-snapshot hook (conftest leak-check naming) + artifact dumps
# ---------------------------------------------------------------------------

# The most recent cluster snapshot captured at driver shutdown: the
# leak check names orphan processes / leaked pins / unreturned leases
# from it instead of reporting bare pids and paths.
FINAL_SNAPSHOT: dict | None = None


def note_final_snapshot(snap: dict) -> None:
    global FINAL_SNAPSHOT
    FINAL_SNAPSHOT = snap


def dump_artifact(path: str, snapshot: dict, stacks: dict | None = None,
                  reason: str = "") -> str:
    """Write a cluster snapshot (+ local stacks) as a JSON artifact —
    the chaos sweeps call this on deadline overrun so seeded-hang triage
    starts from the flight recording, not a reproduction run."""
    import json

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    doc = {"reason": reason, "dumped_at": time.time(),
           "snapshot": snapshot, "stacks": stacks or collect_stacks()}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=_json_default)
    return path


def _json_default(obj):
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, set):
        return sorted(obj)
    return repr(obj)
