"""Deterministic fault injection: named failpoints at every cross-process seam.

The runtime's fault-tolerance story (lineage reconstruction, actor
restarts, a GCS that survives crashes — reference: Ray paper §4.2.3 and
test_gcs_fault_tolerance.py) is only as good as the seams it was proven
at.  The old `RAY_TPU_CHAOS` knob could randomize frame timing, but could
not say "kill THIS worker at its third dispatched task" — so the crash
behavior of the fast paths (coalesced loop queues, direct task channels,
shm collective segments, deferred replies) was never exercised
deterministically.  This registry fixes that: every seam evaluates a
*named* point, and tests arm exactly the failure they want, where they
want it, reproducibly from a seed.

A failpoint is `name = action(predicates)`:

    actions      raise      raise FailpointError at the seam
                 delay      sleep `ms` milliseconds (async-safe at async seams)
                 drop_conn  returned to the site, which drops its connection
                            (or, at dataless seams like gcs.publish, drops
                            the message)
                 exit       hard process kill (os._exit) — SIGKILL-equivalent
                 off        disarmed (catalog entry only)
    predicates   p=F        fire with probability F per hit (seeded RNG)
                 nth=N      fire only on exactly the Nth hit of this point
                 once       disarm after the first firing
                 ms=F       delay duration (action=delay)
                 role=R     only fire in processes whose role is R
                            (driver|worker|raylet|gcs)

Config sources, later ones overriding earlier:

  1. `RAY_TPU_FAILPOINTS` env at process spawn, e.g.
     ``RAY_TPU_FAILPOINTS="worker.exec=exit(nth=3,role=worker);rpc.send=delay(p=0.1,ms=20)"``
     (inherited by every spawned runtime process).
  2. The internal KV: writing the key ``ray_tpu:failpoints`` makes the GCS
     apply the spec locally and publish it on the ``failpoints`` pubsub
     channel, which every raylet/worker/driver subscribes to — so tests
     can arm a point mid-run (`arm_cluster`).

Randomness is seeded from `RAY_TPU_CHAOS_SEED` (mixed with the process
role so co-located processes decorrelate deterministically); any chaos
failure replays from the logged seed.

The legacy ``RAY_TPU_CHAOS`` delay/kill knobs are rebuilt as two
predefined points on this registry — ``rpc.send.delay`` and
``rpc.send.drop_conn`` (see `send_fault`); their firings show up in the
same hit counters and stats.

The catalog of threaded points lives in ARCHITECTURE.md ("Failure
model").  Naming convention: `<layer>.<seam>[.<variant>]`, all lowercase.

Sites guard with the module-level `ARMED` flag so an unarmed registry
costs one attribute load on the hot paths:

    from ray_tpu._private import failpoints as _fp
    ...
    if _fp.ARMED:
        _fp.fire("lease.grant")
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import threading
import time

logger = logging.getLogger("ray_tpu.failpoints")

ENV_VAR = "RAY_TPU_FAILPOINTS"
SEED_ENV = "RAY_TPU_CHAOS_SEED"
KV_KEY = "ray_tpu:failpoints"
CHANNEL = "failpoints"
EXIT_CODE = 113  # distinctive rc: "this process was killed by a failpoint"

ACTIONS = ("raise", "delay", "drop_conn", "exit", "off")
ROLES = ("driver", "worker", "raylet", "gcs")

# True iff any point is armed — the one-word fast guard every site checks.
ARMED = False


class FailpointError(RuntimeError):
    """Raised at a seam by an armed `raise` action."""

    def __init__(self, name: str):
        self.failpoint = name
        super().__init__(f"injected failure at failpoint {name!r}")


@dataclasses.dataclass
class Failpoint:
    name: str
    action: str
    p: float = 1.0
    nth: int = 0          # 0 = every hit; N>0 = only the Nth hit
    once: bool = False
    ms: float = 0.0       # delay duration
    role: str = ""        # "" = every process role
    hits: int = 0         # times the site was reached (post role filter)
    fired: int = 0        # times the action actually applied

    def spec_text(self) -> str:
        args = []
        if self.p != 1.0:
            args.append(f"p={self.p:g}")
        if self.nth:
            args.append(f"nth={self.nth}")
        if self.once:
            args.append("once")
        if self.ms:
            args.append(f"ms={self.ms:g}")
        if self.role:
            args.append(f"role={self.role}")
        return (f"{self.name}={self.action}({','.join(args)})" if args
                else f"{self.name}={self.action}")


_lock = threading.Lock()
_registry: dict[str, Failpoint] = {}
_role = os.environ.get("RAY_TPU_PROCESS_ROLE", "")
_seed = os.environ.get(SEED_ENV)
_rng = random.Random()


def _reseed():
    """Deterministic when RAY_TPU_CHAOS_SEED is set: mixed with the role
    so co-located processes make different (but replayable) draws."""
    if _seed is not None:
        _rng.seed(f"{_seed}:{_role}")


_reseed()


def set_role(role: str, only_if_unset: bool = False) -> None:
    """Declare this process's role (driver|worker|raylet|gcs) for the
    `role=` predicate. Called once at process bootstrap."""
    global _role
    if only_if_unset and _role:
        return
    _role = role
    _reseed()


def get_role() -> str:
    return _role


def _parse_one(text: str) -> Failpoint:
    name, sep, rhs = text.partition("=")
    name = name.strip()
    if not sep or not name:
        raise ValueError(f"malformed failpoint spec {text!r} "
                         f"(expected 'name=action(args)')")
    rhs = rhs.strip()
    action, _, argstr = rhs.partition("(")
    action = action.strip()
    if action not in ACTIONS:
        raise ValueError(f"unknown failpoint action {action!r} in {text!r} "
                         f"(expected one of {ACTIONS})")
    fp = Failpoint(name=name, action=action)
    argstr = argstr.rstrip(")").strip()
    if argstr:
        for part in argstr.split(","):
            part = part.strip()
            if not part:
                continue
            k, ksep, v = part.partition("=")
            k = k.strip()
            v = v.strip()
            if k == "once" and not ksep:
                fp.once = True
            elif k == "p":
                fp.p = float(v)
            elif k == "nth":
                fp.nth = int(v)
            elif k == "once":
                fp.once = v.lower() not in ("0", "false", "")
            elif k == "ms":
                fp.ms = float(v)
            elif k == "role":
                fp.role = v
            else:
                raise ValueError(
                    f"unknown failpoint predicate {k!r} in {text!r}")
    return fp


def parse(text: str) -> dict[str, Failpoint]:
    """Parse a config string: ';'-separated `name=action(args)` entries."""
    out: dict[str, Failpoint] = {}
    for chunk in (text or "").split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        fp = _parse_one(chunk)
        out[fp.name] = fp
    return out


def _recompute_armed():
    global ARMED
    ARMED = any(fp.action != "off" for fp in _registry.values())


def configure(text: str, replace: bool = True) -> None:
    """(Re)arm the registry from a config string. `replace=True` (the KV
    broadcast semantics) makes the string the complete new registry, so
    an empty string disarms everything."""
    specs = parse(text)
    with _lock:
        if replace:
            _registry.clear()
        _registry.update(specs)
        _recompute_armed()
    if specs:
        logger.info("failpoints configured (%s): %s", _role or "?",
                    "; ".join(fp.spec_text() for fp in specs.values()))


def arm(name: str, action: str, **kw) -> None:
    """Arm one point programmatically (local process only)."""
    if action not in ACTIONS:
        raise ValueError(f"unknown failpoint action {action!r}")
    with _lock:
        _registry[name] = Failpoint(name=name, action=action, **kw)
        _recompute_armed()


def disarm(name: str) -> None:
    with _lock:
        _registry.pop(name, None)
        _recompute_armed()


def reset() -> None:
    """Disarm everything and clear counters (test isolation)."""
    with _lock:
        _registry.clear()
        _recompute_armed()


def armed(name: str) -> bool:
    fp = _registry.get(name)
    return fp is not None and fp.action != "off"


def hits(name: str) -> int:
    fp = _registry.get(name)
    return fp.hits if fp is not None else 0


def snapshot() -> dict[str, dict]:
    with _lock:
        return {name: {"action": fp.action, "hits": fp.hits,
                       "fired": fp.fired}
                for name, fp in _registry.items()}


# fired-counters surface in the per-process stats snapshot, so tests can
# observe remote firings through cluster_metrics() (raylets aggregate
# worker snapshots into get_metrics)
_counters: dict[str, object] = {}


def _count_fired(name: str):
    counter = _counters.get(name)
    if counter is None:
        from ray_tpu._private import stats

        counter = _counters[name] = stats.Count(
            f"failpoints.{name}.fired_total",
            f"failpoint {name} injected-action firings")
    counter.inc()


def check(name: str) -> tuple[str, float] | None:
    """Evaluate point `name`: count the hit, apply predicates, and return
    (action, delay_seconds) when armed-and-firing — WITHOUT applying the
    action. Sites that need custom handling (async delay, connection
    drop) use this; everything else uses fire()/fire_async()."""
    fp = _registry.get(name)
    if fp is None or fp.action == "off":
        return None
    if fp.role and fp.role != _role:
        return None
    with _lock:
        fp.hits += 1
        if fp.nth and fp.hits != fp.nth:
            return None
        if fp.once and fp.fired:
            return None
        if fp.p < 1.0 and _rng.random() >= fp.p:
            return None
        fp.fired += 1
    _count_fired(name)
    logger.warning("failpoint %s firing: %s (hit %d, role %s, pid %d)",
                   name, fp.action, fp.hits, _role or "?", os.getpid())
    return fp.action, fp.ms / 1000.0


def _hard_exit(name: str):
    logger.error("failpoint %s: hard-killing pid %d", name, os.getpid())
    os._exit(EXIT_CODE)


def fire(name: str) -> str | None:
    """Apply point `name` at a synchronous seam. Sleeps for `delay`,
    raises FailpointError for `raise`, kills the process for `exit`;
    returns "drop_conn" (the site handles it) or None."""
    act = check(name)
    if act is None:
        return None
    kind, delay = act
    if kind == "delay":
        time.sleep(delay)
        return None
    if kind == "raise":
        raise FailpointError(name)
    if kind == "exit":
        _hard_exit(name)
    return kind


def fire_strict(name: str) -> None:
    """fire() for seams with NO connection to drop: an armed action must
    never be a silent no-op (a chaos schedule would read as exercised-
    and-passing with nothing injected), so `drop_conn` degrades to
    `raise` here."""
    if fire(name) == "drop_conn":
        raise FailpointError(name)


async def fire_async(name: str) -> str | None:
    """fire() for asyncio seams: `delay` awaits instead of blocking the
    event loop."""
    act = check(name)
    if act is None:
        return None
    kind, delay = act
    if kind == "delay":
        import asyncio

        await asyncio.sleep(delay)
        return None
    if kind == "raise":
        raise FailpointError(name)
    if kind == "exit":
        _hard_exit(name)
    return kind


async def fire_async_strict(name: str) -> None:
    """fire_async() with the fire_strict() no-silent-drop_conn rule."""
    if await fire_async(name) == "drop_conn":
        raise FailpointError(name)


# ---------------------------------------------------------------------------
# predefined rpc.send points (the rebuilt RAY_TPU_CHAOS knobs)
# ---------------------------------------------------------------------------

def send_fault(legacy: dict | None) -> tuple[str, float] | None:
    """Evaluate the outbound-frame fault points for one send.

    The legacy ``RAY_TPU_CHAOS`` dict (delay_p/delay_ms/kill_conn_p) is a
    per-call predicate source for the two predefined points
    ``rpc.send.drop_conn`` and ``rpc.send.delay`` — same counters, same
    seeded RNG, same observability as registry-armed points. On top of
    those, a registry-armed ``rpc.send`` point supports every action.
    Returns (action, delay_seconds) or None.
    """
    if legacy is not None:
        kp = legacy.get("kill_conn_p") or 0.0
        if kp and _rng.random() < kp:
            _legacy_hit("rpc.send.drop_conn", "drop_conn")
            return "drop_conn", 0.0
        dp = legacy.get("delay_p") or 0.0
        if dp and _rng.random() < dp:
            _legacy_hit("rpc.send.delay", "delay")
            return "delay", _rng.random() * (legacy.get("delay_ms", 10.0)
                                             / 1000.0)
    if ARMED:
        return check("rpc.send")
    return None


def _legacy_hit(name: str, action: str):
    with _lock:
        fp = _registry.get(name)
        if fp is None:
            fp = _registry[name] = Failpoint(name=name, action=action)
            # catalog entry only — evaluation stays with the legacy dict,
            # so arming it does not flip the global ARMED fast path
            fp.action = "off"
        fp.hits += 1
        fp.fired += 1
    _count_fired(name)


# ---------------------------------------------------------------------------
# cluster-wide live arming (through the internal KV + pubsub)
# ---------------------------------------------------------------------------

def arm_cluster(text: str) -> None:
    """Arm/replace failpoints across every live runtime process: writes
    the spec to the internal KV; the GCS applies it and broadcasts on the
    `failpoints` channel (raylets/workers/drivers are subscribed).
    Requires a connected driver. An empty string disarms everywhere."""
    from ray_tpu._private import global_state

    parse(text)  # validate before shipping a typo cluster-wide
    cw = global_state.require_core_worker()
    cw.kv_put(KV_KEY, text.encode())
    configure(text)  # local process applies immediately (push also lands)


def disarm_cluster() -> None:
    arm_cluster("")


def apply_kv_value(value) -> None:
    """Apply a spec arriving via KV/pubsub (bytes or str)."""
    if isinstance(value, (bytes, bytearray)):
        value = bytes(value).decode(errors="replace")
    try:
        configure(value or "")
    except ValueError:
        logger.exception("invalid failpoint spec from KV; ignored")


# arm from the environment at import (spawned runtime processes inherit
# RAY_TPU_FAILPOINTS from their parent)
if os.environ.get(ENV_VAR):
    try:
        configure(os.environ[ENV_VAR])
    except ValueError:
        logger.exception("invalid %s; starting with no failpoints armed",
                         ENV_VAR)
