"""Streaming fault tolerance: checkpoint barriers + replay (reference:
streaming/src/reliability/barrier_helper.cc + barrier coordination in
streaming/src/data_writer.cc — at-least-once/exactly-once via barriers).

Mechanism (Chandy–Lamport style, as in the reference's aligned barriers):
sources inject a barrier marker every `checkpoint_interval` batches,
tagged with a checkpoint id and the source's replay offset. A stage that
has seen the barrier from SOME upstream instance buffers further batches
from that upstream until the barrier has arrived from ALL of them
(alignment), then snapshots its operator state (reduce aggregates, sink
buffer, round-robin cursor) to the cluster KV and forwards the barrier.
Because alignment prevents post-barrier records from leaking into the
snapshot, restored state is consistent: re-driving sources from their
recorded offsets reprocesses exactly the post-checkpoint suffix.

Snapshot keys: stream:{job}:{ckpt}:{stage}:{instance} → pickled state,
plus stream:{job}:{ckpt}:manifest once the driver confirms completeness.
Sink *state* is exactly-once (it's in the snapshot); user sink side
effects replay at-least-once, same caveat as the reference."""

from __future__ import annotations

import cloudpickle

BARRIER = "__ray_tpu_stream_barrier__"


def kv_key(job_id: str, ckpt_id: int, stage: int, inst: int) -> str:
    return f"stream:{job_id}:{ckpt_id}:{stage}:{inst}"


def save_snapshot(job_id: str, ckpt_id: int, stage: int, inst: int,
                  state: dict):
    from ray_tpu.experimental.internal_kv import _kv_put

    _kv_put(kv_key(job_id, ckpt_id, stage, inst),
            cloudpickle.dumps(state))


def load_snapshot(job_id: str, ckpt_id: int, stage: int,
                  inst: int) -> dict | None:
    from ray_tpu.experimental.internal_kv import _kv_get

    raw = _kv_get(kv_key(job_id, ckpt_id, stage, inst))
    return None if raw is None else cloudpickle.loads(raw)


def bump_max_checkpoint(job_id: str, ckpt_id: int):
    from ray_tpu.experimental.internal_kv import _kv_get, _kv_put

    key = f"stream:{job_id}:max_ckpt"
    cur = _kv_get(key)
    if cur is None or int(cur) < ckpt_id:
        _kv_put(key, str(ckpt_id).encode())


def find_complete_checkpoint(job_id: str, plan: list[int]) -> int | None:
    """Latest ckpt id for which every stage instance snapshotted.
    `plan` = instances per stage."""
    from ray_tpu.experimental.internal_kv import _kv_get

    raw = _kv_get(f"stream:{job_id}:max_ckpt")
    if raw is None:
        return None
    for ckpt in range(int(raw), 0, -1):
        if all(_kv_get(kv_key(job_id, ckpt, s, i)) is not None
               for s, n in enumerate(plan) for i in range(n)):
            return ckpt
    return None
