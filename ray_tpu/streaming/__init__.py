"""ray_tpu.streaming — dataflow pipelines over actor stages (the
streaming-engine capability the reference ships as ray/streaming:
StreamingContext -> DataStream.map/flat_map/filter/key_by/reduce/sink
compiled to parallel stage actors with hash partitioning, credit-based
backpressure, and EOS-propagated completion)."""

from ray_tpu.streaming.streaming import DataStream, StreamingContext

__all__ = ["DataStream", "StreamingContext"]
