"""Streaming dataflow on actors.

Role parity with the reference's streaming engine (reference:
streaming/python — StreamingContext, DataStream, KeyDataStream,
word-count e2e in its tests; reliability: streaming/src/reliability/
barrier_helper.cc), redesigned for this runtime instead of the
reference's C++ DataWriter/DataReader channels:

- logical graph: chained operators, each with its own parallelism;
- physical graph: one actor per operator instance; records flow as
  BATCHES through direct actor calls (the object plane IS the channel);
- partitioning: round-robin for stateless edges, hash-of-key after
  key_by (so each reducer instance owns a key shard);
- backpressure: each pusher keeps at most `max_inflight` unacked batch
  calls per downstream instance (credit window over ray_tpu.wait);
- completion: sources emit EOS; every stage forwards EOS downstream
  once ALL of its upstream instances finished; reducers flush their
  per-key state on EOS (so finite pipelines behave like batch jobs);
- results: sink() collects into sink actors the driver drains at the
  end of run();
- fault tolerance (checkpoint_interval=N): aligned checkpoint barriers
  snapshot operator state to the cluster KV; on a stage-actor death the
  driver rebuilds the DAG from the last complete checkpoint and re-drives
  sources from their recorded offsets (streaming/reliability.py).
"""

from __future__ import annotations

import uuid

import cloudpickle

import ray_tpu
from ray_tpu.streaming.reliability import (BARRIER, bump_max_checkpoint,
                                           find_complete_checkpoint,
                                           load_snapshot, save_snapshot)

_EOS = "__ray_tpu_stream_eos__"


def _stable_hash(key) -> int:
    """Partitioning hash that is stable ACROSS PROCESSES (python's hash()
    is per-process randomized for strings — stage actors are separate
    workers, so it must never be used for routing)."""
    import pickle
    import zlib

    if isinstance(key, int):
        return key & 0x7FFFFFFF
    return zlib.crc32(pickle.dumps(key, protocol=4))


def _sliced_source(src, index: int, parallelism: int):
    """Parallel generator sources: each instance re-evaluates the source
    callable and reads its stride, so gen_fn MUST be deterministic and
    repeatable (one-shot sources — queues, sockets — need parallelism 1;
    collections are sliced driver-side instead)."""
    def gen():
        import itertools

        return itertools.islice(src(), index, None, parallelism)

    return gen


class _StageActor:
    """One parallel instance of one operator."""

    def __init__(self, op_pickled: bytes, index: int, num_upstream: int,
                 stall_timeout: float = 300.0, job_id: str = "",
                 stage_index: int = 0, restore_ckpt: int = 0):
        kind, fn = cloudpickle.loads(op_pickled)
        self._kind = kind
        self._fn = fn
        self._index = index
        self._stage = stage_index
        self._job = job_id
        self._num_upstream = num_upstream
        self._eos_left = num_upstream
        self._downstream = None          # list[handle] | None
        self._partitioned = False
        self._max_inflight = 8
        self._stall_timeout = stall_timeout
        self._inflight = {}              # id(handle) -> [refs]
        self._state = {}                 # reduce: key -> aggregate
        self._out = []                   # sink: collected records
        self._rr = -1
        # barrier alignment (reliability.py)
        self._barrier_from: set[int] = set()
        self._eos_from: set[int] = set()
        self._aligned_buffer: list[tuple[int, list]] = []
        self._barrier_offsets: dict = {}
        self._pending_ckpt: int = 0
        if restore_ckpt and job_id:
            snap = load_snapshot(job_id, restore_ckpt, stage_index, index)
            if snap is not None:
                self._state = snap["state"]
                self._out = snap["out"]
                self._rr = snap["rr"]

    def connect(self, downstream, partitioned: bool):
        self._downstream = list(downstream)
        self._partitioned = partitioned
        return True

    # -- pushing with credit-based backpressure --------------------------

    def _push(self, target, batch):
        key = id(target)
        refs = self._inflight.setdefault(key, [])
        while len(refs) >= self._max_inflight:
            ready, rest = ray_tpu.wait(refs, num_returns=1,
                                       timeout=self._stall_timeout)
            if not ready:
                raise TimeoutError("downstream stage stalled")
            # Surface downstream failures NOW: an errored ack raises here
            # and the exception cascades back through the chain to run()
            # instead of silently dropping data.
            ray_tpu.get(ready)
            self._inflight[key] = refs = rest
        refs.append(target.process.remote(batch, self._index))

    def _emit(self, records):
        if not records or self._downstream is None:
            return
        if self._partitioned:
            buckets: dict[int, list] = {}
            n = len(self._downstream)
            for rec in records:
                buckets.setdefault(_stable_hash(rec[0]) % n, []).append(rec)
            for i, batch in buckets.items():
                self._push(self._downstream[i], batch)
        else:
            # round-robin by batch
            self._rr = (self._rr + 1) % len(self._downstream)
            self._push(self._downstream[self._rr], records)

    def _broadcast(self, marker):
        if self._downstream is None:
            return
        for target in self._downstream:
            # markers must arrive AFTER the data already in flight: the
            # per-target call order guarantees it.
            self._push(target, marker)

    def _flush_and_forward_eos(self):
        if self._kind == "reduce" and self._downstream is not None:
            items = list(self._state.items())
            for i in range(0, len(items), 256):
                self._emit(items[i:i + 256])
            self._state = {}
        if self._downstream is not None:
            self._broadcast(_EOS)
            for refs in self._inflight.values():
                ray_tpu.get(refs, timeout=self._stall_timeout)
            self._inflight = {}

    # -- checkpoint barriers (reliability.py) ----------------------------

    def _snapshot(self, ckpt_id: int):
        save_snapshot(self._job, ckpt_id, self._stage, self._index, {
            "state": self._state,
            "out": self._out,
            "rr": self._rr,
        })

    def _on_barrier(self, marker: dict, from_idx: int):
        if from_idx in self._barrier_from:
            # this upstream raced ahead into its NEXT checkpoint while we
            # still await others for the current one — hold its barrier in
            # the alignment buffer with its data (replayed in order)
            self._aligned_buffer.append((from_idx, marker))
            return True
        self._barrier_from.add(from_idx)
        self._barrier_offsets.update(marker.get("offsets", {}))
        self._pending_ckpt = marker["ckpt"]
        self._maybe_complete_barrier()
        return True

    def _maybe_complete_barrier(self):
        """Aligned once every upstream has either sent the barrier or
        finished (EOS — it will never send one; an upstream with a
        shorter input must not deadlock the alignment)."""
        if not self._barrier_from:
            return
        if len(self._barrier_from | self._eos_from) < self._num_upstream:
            return
        ckpt_id = self._pending_ckpt
        self._snapshot(ckpt_id)
        self._broadcast({BARRIER: True, "ckpt": ckpt_id,
                         "offsets": self._barrier_offsets})
        self._barrier_from = set()
        self._barrier_offsets = {}
        buffered, self._aligned_buffer = self._aligned_buffer, []
        for from_i, batch in buffered:
            self.process(batch, from_i)

    # -- operator semantics ----------------------------------------------

    def process(self, batch, from_idx: int = 0):
        if isinstance(batch, str) and batch == _EOS:
            self._eos_left -= 1
            self._eos_from.add(from_idx)
            # a finished upstream can no longer send barriers: re-check
            # alignment so live upstreams' checkpoints still complete
            self._maybe_complete_barrier()
            if self._eos_left == 0:
                # release anything still held for an alignment that can
                # no longer complete, then flush
                buffered, self._aligned_buffer = self._aligned_buffer, []
                self._barrier_from = set()
                for from_i, b in buffered:
                    self.process(b, from_i)
                self._flush_and_forward_eos()
            return True
        if isinstance(batch, dict) and batch.get(BARRIER):
            return self._on_barrier(batch, from_idx)
        if from_idx in self._barrier_from:
            # alignment: this upstream already passed the barrier; hold
            # its post-barrier data out of the pre-barrier snapshot
            self._aligned_buffer.append((from_idx, batch))
            return True
        kind, fn = self._kind, self._fn
        if kind == "map":
            out = [fn(x) for x in batch]
        elif kind == "flat_map":
            out = [y for x in batch for y in fn(x)]
        elif kind == "filter":
            out = [x for x in batch if fn(x)]
        elif kind == "key_by":
            out = [(fn(x), x) for x in batch]
        elif kind == "reduce":
            for key, value in batch:
                if key in self._state:
                    self._state[key] = fn(self._state[key], value)
                else:
                    self._state[key] = value
            return True  # emits on EOS flush
        elif kind == "sink":
            for x in batch:
                self._out.append(fn(x) if fn is not None else x)
            return True
        else:
            raise ValueError(f"unknown operator kind {kind!r}")
        self._emit(out)
        return True

    def drain_source(self, batch_size: int = 128,
                     checkpoint_interval: int = 0,
                     resume_offset: int = 0, resume_ckpt: int = 0):
        """Source instances: pull from the user iterable and push.
        With checkpointing on, a barrier follows every
        `checkpoint_interval` batches, carrying this instance's record
        offset; `resume_offset` skips records already covered by the
        checkpoint being restored and `resume_ckpt` continues its
        numbering (deterministic sources make snapshots from different
        run attempts interchangeable at the same ckpt id)."""
        import itertools

        it = self._fn() if callable(self._fn) else iter(self._fn)
        if resume_offset:
            it = itertools.islice(it, resume_offset, None)
        offset = resume_offset
        batches_since = 0
        ckpt_id = resume_ckpt
        buf = []
        for item in it:
            buf.append(item)
            if len(buf) >= batch_size:
                self._emit(buf)
                offset += len(buf)
                buf = []
                batches_since += 1
                if (checkpoint_interval
                        and batches_since >= checkpoint_interval):
                    batches_since = 0
                    ckpt_id += 1
                    self._snapshot_source(ckpt_id, offset)
        if buf:
            self._emit(buf)
            offset += len(buf)
        self._flush_and_forward_eos()
        return True

    def _snapshot_source(self, ckpt_id: int, offset: int):
        save_snapshot(self._job, ckpt_id, self._stage, self._index,
                      {"state": {}, "out": [], "rr": self._rr,
                       "offset": offset})
        bump_max_checkpoint(self._job, ckpt_id)
        self._broadcast({BARRIER: True, "ckpt": ckpt_id,
                         "offsets": {self._index: offset}})

    def collect(self):
        out, self._out = self._out, []
        return out


class _Op:
    def __init__(self, kind: str, fn, parallelism: int = 1):
        self.kind = kind
        self.fn = fn
        self.parallelism = parallelism


class DataStream:
    """Lazy operator chain (reference: streaming DataStream /
    KeyDataStream surface)."""

    def __init__(self, ctx: "StreamingContext", ops: list[_Op]):
        self._ctx = ctx
        self._ops = ops

    def _chain(self, op: _Op) -> "DataStream":
        return DataStream(self._ctx, self._ops + [op])

    def set_parallelism(self, n: int) -> "DataStream":
        self._ops[-1].parallelism = n
        return self

    def map(self, fn) -> "DataStream":
        return self._chain(_Op("map", fn))

    def flat_map(self, fn) -> "DataStream":
        return self._chain(_Op("flat_map", fn))

    def filter(self, fn) -> "DataStream":
        return self._chain(_Op("filter", fn))

    def key_by(self, fn) -> "DataStream":
        return self._chain(_Op("key_by", fn))

    def reduce(self, fn) -> "DataStream":
        return self._chain(_Op("reduce", fn))

    def sink(self, fn=None) -> "StreamingContext":
        self._ctx._pipelines.append(self._ops + [_Op("sink", fn)])
        return self._ctx


class StreamingContext:
    def __init__(self, batch_size: int = 128,
                 stall_timeout: float = 300.0,
                 checkpoint_interval: int = 0,
                 max_restarts: int = 0):
        """stall_timeout bounds every intra-pipeline wait (backpressure,
        EOS flush) inside the stage actors; run(timeout=...) bounds the
        driver-side end-to-end drive. checkpoint_interval > 0 turns on
        barrier checkpointing every N source batches; max_restarts is how
        many times run() rebuilds a failed DAG from the last complete
        checkpoint before giving up."""
        self._pipelines: list[list[_Op]] = []
        self._batch_size = batch_size
        self._stall_timeout = stall_timeout
        self._checkpoint_interval = checkpoint_interval
        self._max_restarts = max_restarts

    # -- sources ---------------------------------------------------------

    def from_collection(self, items) -> DataStream:
        return DataStream(self, [_Op("source", list(items))])

    def source(self, gen_fn) -> DataStream:
        """gen_fn() -> iterable (evaluated inside the source actor)."""
        return DataStream(self, [_Op("source", gen_fn)])

    # -- execution -------------------------------------------------------

    def run(self, timeout: float = 300.0) -> list:
        """Build the actor DAG, run every pipeline to completion, and
        return the concatenated sink outputs."""
        results = []
        for ops in self._pipelines:
            results.extend(self._run_with_recovery(ops, timeout))
        return results

    def _run_with_recovery(self, ops: list[_Op], timeout: float) -> list:
        job_id = uuid.uuid4().hex[:12]
        attempts = self._max_restarts + 1
        last_err = None
        for attempt in range(attempts):
            restore = 0
            if attempt and self._checkpoint_interval:
                plan = [op.parallelism for op in ops]
                restore = find_complete_checkpoint(job_id, plan) or 0
            try:
                return self._run_one(ops, timeout, job_id, restore)
            except Exception as e:
                last_err = e
                if attempt + 1 >= attempts:
                    raise
        raise last_err  # unreachable

    def _build_stages(self, ops: list[_Op], job_id: str, restore: int):
        stage_cls = ray_tpu.remote(_StageActor)
        stages: list[list] = []
        for i, op in enumerate(ops):
            num_up = 1 if i == 0 else ops[i - 1].parallelism
            row = []
            for j in range(op.parallelism):
                fn = op.fn
                if op.kind == "source" and op.parallelism > 1:
                    if callable(fn):
                        fn = _sliced_source(fn, j, op.parallelism)
                    else:  # collection: slice driver-side, ship the slice
                        fn = list(fn)[j::op.parallelism]
                pickled = cloudpickle.dumps((op.kind, fn))
                row.append(stage_cls.remote(
                    pickled, j, num_up, self._stall_timeout, job_id, i,
                    restore))
            stages.append(row)
        return stages

    def _run_one(self, ops: list[_Op], timeout: float, job_id: str = "",
                 restore: int = 0) -> list:
        stages = self._build_stages(ops, job_id, restore)
        # wire edges; the edge INTO the op after key_by is hash-partitioned
        wiring = []
        for i in range(len(ops) - 1):
            partitioned = ops[i].kind == "key_by"
            for inst in stages[i]:
                wiring.append(inst.connect.remote(stages[i + 1],
                                                  partitioned))
        try:
            ray_tpu.get(wiring, timeout=min(60.0, timeout))
            # drive sources to completion (EOS cascades through the
            # chain); restored runs resume from the checkpoint offsets
            drains = []
            for j, s in enumerate(stages[0]):
                offset = 0
                if restore:
                    snap = load_snapshot(job_id, restore, 0, j)
                    offset = (snap or {}).get("offset", 0)
                drains.append(s.drain_source.remote(
                    self._batch_size, self._checkpoint_interval, offset,
                    restore))
            ray_tpu.get(drains, timeout=timeout)
            # EOS has reached the sinks only after every intermediate
            # actor acked; collect sink outputs
            out = []
            for sink in stages[-1]:
                out.extend(ray_tpu.get(sink.collect.remote(),
                                       timeout=min(60.0, timeout)))
            return out
        finally:
            # Failed runs must not leak the actor DAG (worker processes
            # plus buffered reduce/sink state).
            for row in stages:
                for inst in row:
                    try:
                        ray_tpu.kill(inst)
                    except Exception:
                        pass
