"""ActorClass / ActorHandle / ActorMethod (reference: python/ray/actor.py:
ActorClass :297, ._remote :477, ActorHandle :723, ActorMethod :62,
exit_actor :1035)."""

from __future__ import annotations

import cloudpickle

from ray_tpu._private import global_state
from ray_tpu._private.ids import ActorID


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        # static spec prefix cached per (handle, method, core worker) —
        # see CoreWorker.make_actor_task_template
        self._template = None
        self._template_cw = None

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly; "
            f"use .{self._method_name}.remote()."
        )

    def __getstate__(self):
        # ActorMethods can be captured in closures shipped to other
        # processes; the template cache references this process's
        # CoreWorker and must not travel.
        state = self.__dict__.copy()
        state["_template"] = None
        state["_template_cw"] = None
        return state

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, {})

    def options(self, **opts):
        parent = self

        class _Wrapped:
            def remote(self, *args, **kwargs):
                return parent._remote(args, kwargs, opts)

        return _Wrapped()

    def _remote(self, args, kwargs, opts):
        cw = global_state.require_core_worker()
        num_returns = opts.get("num_returns", self._num_returns)
        if not opts and not getattr(cw, "_legacy", False):
            if self._template is None or self._template_cw is not cw:
                self._template = cw.make_actor_task_template(
                    self._handle._actor_id.binary(),
                    fn_id=self._handle._cls_id,
                    name=f"{self._handle._class_name}.{self._method_name}",
                    method_name=self._method_name,
                    num_returns=num_returns,
                )
                self._template_cw = cw
            refs = cw.submit_actor_task(
                self._handle._actor_id.binary(), args=args, kwargs=kwargs,
                template=self._template)
        else:
            refs = cw.submit_actor_task(
                self._handle._actor_id.binary(),
                fn_id=self._handle._cls_id,
                name=f"{self._handle._class_name}.{self._method_name}",
                method_name=self._method_name,
                args=args,
                kwargs=kwargs,
                num_returns=num_returns,
            )
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs


class ActorHandle:
    def __init__(self, actor_id: ActorID, cls_id: bytes, class_name: str,
                 method_num_returns: dict[str, int] | None = None):
        self._actor_id = actor_id
        self._cls_id = cls_id
        self._class_name = class_name
        self._method_num_returns = method_num_returns or {}

    def __getattr__(self, name):
        # __ray_*__ system methods (terminate, collective init) are callable
        # remotely; other underscore names are not exposed as actor methods.
        if name.startswith("_") and not name.startswith("__ray_"):
            raise AttributeError(name)
        method = ActorMethod(self, name, self._method_num_returns.get(name, 1))
        # Cache on the instance so repeated `handle.method` lookups skip
        # __getattr__ (and keep the method's cached spec template alive);
        # __reduce__ serializes explicit state only, so the cache never
        # travels.
        self.__dict__[name] = method
        return method

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        state = {
            "actor_id": self._actor_id.binary(),
            "cls_id": self._cls_id,
            "class_name": self._class_name,
            "method_num_returns": self._method_num_returns,
        }
        return (_rehydrate_handle, (state,))

    def __ray_terminate__(self):
        """Gracefully stop this actor (queued behind pending tasks)."""
        return ActorMethod(self, "__ray_terminate__", 0).remote()


def _rehydrate_handle(state) -> ActorHandle:
    return ActorHandle(
        ActorID(state["actor_id"]),
        state["cls_id"],
        state["class_name"],
        state.get("method_num_returns"),
    )


class ActorClass:
    def __init__(self, cls, *, num_cpus=None, num_tpus=None, resources=None,
                 max_restarts=0, max_concurrency=1, accelerator_type=None):
        self._cls = cls
        self._class_name = cls.__name__
        self._num_cpus = num_cpus
        self._num_tpus = num_tpus
        self._resources = resources or {}
        self._max_restarts = max_restarts
        self._max_concurrency = max_concurrency
        self._accelerator_type = accelerator_type
        self._pickled = None
        self._cls_id = None

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._class_name} cannot be instantiated directly;"
            f" use {self._class_name}.remote()."
        )

    def options(self, **opts):
        parent = self

        class _Wrapped:
            def remote(self, *args, **kwargs):
                return parent._remote(args, kwargs, opts)

        return _Wrapped()

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, {})

    def _remote(self, args, kwargs, opts) -> ActorHandle:
        cw = global_state.require_core_worker()
        if self._cls_id is None:
            cls = _prepare_actor_class(self._cls)
            self._pickled = cloudpickle.dumps(cls)
        cls_id = cw.export_function(self._pickled, kind="cls")
        self._cls_id = cls_id
        resources = dict(self._resources)
        resources.update(opts.get("resources") or {})
        num_cpus = opts.get("num_cpus", self._num_cpus)
        num_tpus = opts.get("num_tpus", self._num_tpus)
        # Reference semantics: actors without an explicit request hold no
        # CPU while alive (so long-lived actors don't starve task
        # scheduling); an explicit num_cpus — or an explicit "CPU" key in
        # resources= — is held for the actor's lifetime.
        if num_cpus is not None:
            resources["CPU"] = num_cpus
        elif "CPU" not in resources:
            resources["CPU"] = 0
        if num_tpus:
            resources["TPU"] = num_tpus
        accel = opts.get("accelerator_type", self._accelerator_type)
        if accel:
            from ray_tpu.util.accelerators import accelerator_resource

            resources.setdefault(accelerator_resource(accel), 0.001)
        pg = opts.get("placement_group")
        actor_id = cw.create_actor(
            cls_id=cls_id,
            name=self._class_name,
            args=args,
            kwargs=kwargs,
            resources=resources,
            max_restarts=opts.get("max_restarts", self._max_restarts),
            max_concurrency=opts.get("max_concurrency",
                                     self._max_concurrency),
            actor_name=opts.get("name", ""),
            namespace=opts.get("namespace", ""),
            lifetime=opts.get("lifetime", ""),
            placement_group=pg.id.binary() if pg is not None else None,
            bundle_index=opts.get("placement_group_bundle_index", -1),
        )
        return ActorHandle(ActorID(actor_id), cls_id, self._class_name)


def _prepare_actor_class(cls):
    """Add framework methods to the user's class before export."""

    class Prepared(cls):  # type: ignore[misc,valid-type]
        def __ray_terminate__(self):
            import os
            import threading
            import time

            from ray_tpu._private import global_state

            cw = global_state.get_core_worker()
            if cw is not None:
                cw.notify_actor_exiting()

            def _die():
                time.sleep(0.2)
                os._exit(0)

            threading.Thread(target=_die, daemon=True).start()

        def __ray_ping__(self):
            return "pong"

    Prepared.__name__ = cls.__name__
    Prepared.__qualname__ = getattr(cls, "__qualname__", cls.__name__)
    Prepared.__module__ = cls.__module__
    return Prepared


def exit_actor():
    """Terminate the current actor from inside one of its methods
    (reference: python/ray/actor.py:1035)."""
    import os
    import threading
    import time

    from ray_tpu._private import global_state

    cw = global_state.get_core_worker()
    if cw is None or cw._actor_instance is None:
        raise RuntimeError("exit_actor() called outside an actor")
    cw.notify_actor_exiting()

    def _die():
        time.sleep(0.2)
        os._exit(0)

    threading.Thread(target=_die, daemon=True).start()
    raise SystemExit(0)
