"""Build native components with the system compiler, cached by source
hash (no pip/pybind11: plain g++ -shared + ctypes)."""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))


def build_library(name: str, sources: list[str],
                  extra_flags: list[str] | None = None) -> str | None:
    """Compile `sources` (relative to native/) into lib<name>.so; returns
    the path, or None when no compiler is available."""
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        return None
    src_paths = [os.path.join(_DIR, s) for s in sources]
    tag = hashlib.sha256()
    for p in src_paths:
        with open(p, "rb") as f:
            tag.update(f.read())
    build_dir = os.path.join(_DIR, "build")
    os.makedirs(build_dir, exist_ok=True)
    out = os.path.join(build_dir, f"lib{name}-{tag.hexdigest()[:12]}.so")
    if os.path.exists(out):
        return out
    # per-pid tmp: concurrent cold-starting processes (raylet + workers)
    # each compile privately, then atomically publish — a shared tmp
    # path would interleave two g++ runs into one corrupt .so
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = [cxx, "-O2", "-g", "-fPIC", "-shared", "-std=c++17",
           "-o", tmp, *src_paths, "-lpthread",
           *(extra_flags or [])]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.rename(tmp, out)
    return out
