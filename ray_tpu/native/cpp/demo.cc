// End-to-end exercise of the ray_tpu C++ API against a live cluster
// (role parity: the reference's cpp/src/ray example/test flow —
// Init → Put/Get → Task(...).Remote() → Get). Driven by
// tests/test_cpp_api.py, which compiles this file with g++ and runs it
// against a cluster + client server it starts.
//
// usage: demo <host:port-of-client-server>

#include <cstdio>
#include <string>

#include "ray_api.hpp"

namespace mp = msgpack_lite;

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s host:port\n", argv[0]);
    return 2;
  }
  try {
    ray::Init(argv[1]);

    // objects: put/get round-trips for scalars, strings, lists, maps
    ray::ObjectRef a = ray::Put(mp::Value(int64_t{41}));
    if (ray::Get(a).as_int() != 41) throw std::runtime_error("int rt");

    mp::Array list;
    list.emplace_back(int64_t{1});
    list.emplace_back(2.5);
    list.emplace_back("three");
    ray::ObjectRef b = ray::Put(mp::Value(list));
    const mp::Array& got = ray::Get(b).as_array();
    if (got.size() != 3 || got[2].as_str() != "three")
      throw std::runtime_error("list rt");

    mp::Map m;
    m.emplace("k", mp::Value(int64_t{7}));
    ray::ObjectRef c = ray::Put(mp::Value(m));
    if (ray::Get(c)["k"].as_int() != 7) throw std::runtime_error("map rt");

    // tasks by descriptor, executed by the cluster's Python workers
    ray::ObjectRef sum =
        ray::Task("tests.cpp_demo_funcs:add").Remote(int64_t{2},
                                                     int64_t{3});
    if (ray::Get(sum).as_int() != 5) throw std::runtime_error("task");

    // chaining: ObjectRef args resolve to their values server-side
    ray::ObjectRef doubled =
        ray::Task("tests.cpp_demo_funcs:double_it").Remote(sum);
    if (ray::Get(doubled).as_int() != 10) throw std::runtime_error("chain");

    // batched get preserves order
    std::vector<mp::Value> vals = ray::Get({a, sum, doubled});
    if (vals[0].as_int() != 41 || vals[1].as_int() != 5 ||
        vals[2].as_int() != 10)
      throw std::runtime_error("batched get");

    // cluster introspection
    mp::Value res = ray::ClusterResources();
    if (res.as_map().empty()) throw std::runtime_error("resources");

    // server-side errors surface as exceptions with the remote message
    bool raised = false;
    try {
      ray::Get(ray::Task("tests.cpp_demo_funcs:boom").Remote());
    } catch (const std::exception& e) {
      raised = std::string(e.what()).find("deliberate") !=
               std::string::npos;
    }
    if (!raised) throw std::runtime_error("error propagation");

    ray::Shutdown();
    std::printf("CPP_DEMO_OK\n");
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "CPP_DEMO_FAIL: %s\n", e.what());
    return 1;
  }
}
