// Minimal msgpack codec for the ray_tpu C++ client (role parity:
// the reference's C++/Java workers serialize cross-language payloads as
// msgpack — src/ray/common/... msgpack dependency; here a dependency-free
// subset: nil/bool/int/float64/str/bin/array/map).
//
// Not a general-purpose library: covers exactly the wire shapes the
// ray_tpu client-server protocol uses (rpc.py: length-prefixed
// msgpack([msgtype, msgid, method, data])).
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace msgpack_lite {

class Value;
using Array = std::vector<Value>;
using Map = std::map<std::string, Value>;

class Value {
 public:
  enum class Type { Nil, Bool, Int, Float, Str, Bin, Arr, MapT };

  Value() : type_(Type::Nil) {}
  Value(std::nullptr_t) : type_(Type::Nil) {}
  Value(bool b) : type_(Type::Bool), b_(b) {}
  Value(int i) : type_(Type::Int), i_(i) {}
  Value(int64_t i) : type_(Type::Int), i_(i) {}
  Value(uint64_t i) : type_(Type::Int), i_(static_cast<int64_t>(i)) {}
  Value(double d) : type_(Type::Float), d_(d) {}
  Value(const char* s) : type_(Type::Str), s_(s) {}
  Value(std::string s) : type_(Type::Str), s_(std::move(s)) {}
  static Value Bin(std::string data) {
    Value v;
    v.type_ = Type::Bin;
    v.s_ = std::move(data);
    return v;
  }
  Value(Array a) : type_(Type::Arr), arr_(std::move(a)) {}
  Value(Map m) : type_(Type::MapT), map_(std::move(m)) {}

  Type type() const { return type_; }
  bool is_nil() const { return type_ == Type::Nil; }
  bool as_bool() const { check(Type::Bool); return b_; }
  int64_t as_int() const { check(Type::Int); return i_; }
  double as_float() const {
    if (type_ == Type::Int) return static_cast<double>(i_);
    check(Type::Float);
    return d_;
  }
  const std::string& as_str() const {
    if (type_ != Type::Str && type_ != Type::Bin)
      throw std::runtime_error("msgpack: not a str/bin");
    return s_;
  }
  const Array& as_array() const { check(Type::Arr); return arr_; }
  const Map& as_map() const { check(Type::MapT); return map_; }

  // map convenience: v["key"]
  const Value& operator[](const std::string& k) const {
    static Value nil;
    check(Type::MapT);
    auto it = map_.find(k);
    return it == map_.end() ? nil : it->second;
  }

 private:
  void check(Type t) const {
    if (type_ != t) throw std::runtime_error("msgpack: type mismatch");
  }
  Type type_;
  bool b_ = false;
  int64_t i_ = 0;
  double d_ = 0;
  std::string s_;
  Array arr_;
  Map map_;
};

// ---------------------------------------------------------------- pack

inline void pack_into(const Value& v, std::string& out);

inline void put_be(std::string& out, uint64_t x, int bytes) {
  for (int i = bytes - 1; i >= 0; --i)
    out.push_back(static_cast<char>((x >> (8 * i)) & 0xff));
}

inline void pack_into(const Value& v, std::string& out) {
  using T = Value::Type;
  switch (v.type()) {
    case T::Nil:
      out.push_back(static_cast<char>(0xc0));
      break;
    case T::Bool:
      out.push_back(static_cast<char>(v.as_bool() ? 0xc3 : 0xc2));
      break;
    case T::Int: {
      int64_t i = v.as_int();
      if (i >= 0 && i < 128) {
        out.push_back(static_cast<char>(i));
      } else if (i < 0 && i >= -32) {
        out.push_back(static_cast<char>(0xe0 | (i + 32)));
      } else {
        out.push_back(static_cast<char>(0xd3));  // int64
        put_be(out, static_cast<uint64_t>(i), 8);
      }
      break;
    }
    case T::Float: {
      out.push_back(static_cast<char>(0xcb));
      double d = v.as_float();
      uint64_t bits;
      std::memcpy(&bits, &d, 8);
      put_be(out, bits, 8);
      break;
    }
    case T::Str: {
      const std::string& s = v.as_str();
      if (s.size() < 32) {
        out.push_back(static_cast<char>(0xa0 | s.size()));
      } else if (s.size() < 256) {
        out.push_back(static_cast<char>(0xd9));
        put_be(out, s.size(), 1);
      } else {
        out.push_back(static_cast<char>(0xda));
        put_be(out, s.size(), 2);
      }
      out += s;
      break;
    }
    case T::Bin: {
      const std::string& s = v.as_str();
      if (s.size() < 256) {
        out.push_back(static_cast<char>(0xc4));
        put_be(out, s.size(), 1);
      } else if (s.size() < (1u << 16)) {
        out.push_back(static_cast<char>(0xc5));
        put_be(out, s.size(), 2);
      } else {
        out.push_back(static_cast<char>(0xc6));
        put_be(out, s.size(), 4);
      }
      out += s;
      break;
    }
    case T::Arr: {
      const Array& a = v.as_array();
      if (a.size() < 16) {
        out.push_back(static_cast<char>(0x90 | a.size()));
      } else {
        out.push_back(static_cast<char>(0xdc));
        put_be(out, a.size(), 2);
      }
      for (const auto& e : a) pack_into(e, out);
      break;
    }
    case T::MapT: {
      const Map& m = v.as_map();
      if (m.size() < 16) {
        out.push_back(static_cast<char>(0x80 | m.size()));
      } else {
        out.push_back(static_cast<char>(0xde));
        put_be(out, m.size(), 2);
      }
      for (const auto& kv : m) {
        pack_into(Value(kv.first), out);
        pack_into(kv.second, out);
      }
      break;
    }
  }
}

inline std::string pack(const Value& v) {
  std::string out;
  pack_into(v, out);
  return out;
}

// -------------------------------------------------------------- unpack

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  uint8_t u8() {
    if (p >= end) throw std::runtime_error("msgpack: truncated");
    return *p++;
  }
  uint64_t be(int bytes) {
    uint64_t x = 0;
    for (int i = 0; i < bytes; ++i) x = (x << 8) | u8();
    return x;
  }
  std::string bytes(size_t n) {
    if (p + n > end) throw std::runtime_error("msgpack: truncated");
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

inline Value unpack_one(Cursor& c) {
  uint8_t t = c.u8();
  if (t < 0x80) return Value(static_cast<int64_t>(t));          // posfixint
  if (t >= 0xe0) return Value(static_cast<int64_t>(static_cast<int8_t>(t)));
  if (t >= 0xa0 && t <= 0xbf) return Value(c.bytes(t & 0x1f));  // fixstr
  if (t >= 0x90 && t <= 0x9f) {                                 // fixarray
    Array a;
    for (int i = 0; i < (t & 0x0f); ++i) a.push_back(unpack_one(c));
    return Value(std::move(a));
  }
  if (t >= 0x80 && t <= 0x8f) {                                 // fixmap
    Map m;
    for (int i = 0; i < (t & 0x0f); ++i) {
      std::string k = unpack_one(c).as_str();
      m.emplace(std::move(k), unpack_one(c));
    }
    return Value(std::move(m));
  }
  switch (t) {
    case 0xc0: return Value();
    case 0xc2: return Value(false);
    case 0xc3: return Value(true);
    case 0xc4: return Value::Bin(c.bytes(c.be(1)));
    case 0xc5: return Value::Bin(c.bytes(c.be(2)));
    case 0xc6: return Value::Bin(c.bytes(c.be(4)));
    case 0xca: {  // float32
      uint32_t bits = static_cast<uint32_t>(c.be(4));
      float f;
      std::memcpy(&f, &bits, 4);
      return Value(static_cast<double>(f));
    }
    case 0xcb: {  // float64
      uint64_t bits = c.be(8);
      double d;
      std::memcpy(&d, &bits, 8);
      return Value(d);
    }
    case 0xcc: return Value(static_cast<int64_t>(c.be(1)));
    case 0xcd: return Value(static_cast<int64_t>(c.be(2)));
    case 0xce: return Value(static_cast<int64_t>(c.be(4)));
    case 0xcf: return Value(static_cast<int64_t>(c.be(8)));
    case 0xd0: return Value(static_cast<int64_t>(static_cast<int8_t>(c.be(1))));
    case 0xd1: return Value(static_cast<int64_t>(static_cast<int16_t>(c.be(2))));
    case 0xd2: return Value(static_cast<int64_t>(static_cast<int32_t>(c.be(4))));
    case 0xd3: return Value(static_cast<int64_t>(c.be(8)));
    case 0xd9: return Value(c.bytes(c.be(1)));
    case 0xda: return Value(c.bytes(c.be(2)));
    case 0xdb: return Value(c.bytes(c.be(4)));
    case 0xdc: {
      size_t n = c.be(2);
      Array a;
      for (size_t i = 0; i < n; ++i) a.push_back(unpack_one(c));
      return Value(std::move(a));
    }
    case 0xdd: {
      size_t n = c.be(4);
      Array a;
      for (size_t i = 0; i < n; ++i) a.push_back(unpack_one(c));
      return Value(std::move(a));
    }
    case 0xde: {
      size_t n = c.be(2);
      Map m;
      for (size_t i = 0; i < n; ++i) {
        std::string k = unpack_one(c).as_str();
        m.emplace(std::move(k), unpack_one(c));
      }
      return Value(std::move(m));
    }
    case 0xdf: {
      size_t n = c.be(4);
      Map m;
      for (size_t i = 0; i < n; ++i) {
        std::string k = unpack_one(c).as_str();
        m.emplace(std::move(k), unpack_one(c));
      }
      return Value(std::move(m));
    }
  }
  throw std::runtime_error("msgpack: unsupported tag");
}

inline Value unpack(const std::string& data) {
  Cursor c{reinterpret_cast<const uint8_t*>(data.data()),
           reinterpret_cast<const uint8_t*>(data.data() + data.size())};
  return unpack_one(c);
}

}  // namespace msgpack_lite
