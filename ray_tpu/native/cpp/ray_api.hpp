// ray_tpu C++ worker API (role parity with the reference's C++ API:
// cpp/src/ray/api.cc ray::Init / ray::Put / ray::Get /
// ray::Task(...).Remote()).
//
// Architecture: unlike the reference (whose C++ worker links the whole
// core-worker runtime), this client speaks the ray_tpu client-server
// protocol (ray_tpu/util/client/server.py) over one TCP connection —
// the idiomatic integration for this runtime, where remote drivers hold
// no local runtime and values cross languages as msgpack (the same
// cross-language data plane the reference uses for Java/C++ calls).
// Tasks are addressed by "module:function" descriptors executed by the
// cluster's Python workers (reference cross_language.py py_function).
#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <stdexcept>
#include <string>
#include <type_traits>
#include <vector>

#include "msgpack_lite.hpp"

namespace ray {

namespace mp = msgpack_lite;

// wire constants (ray_tpu/_private/rpc.py)
constexpr int kRequest = 0;
constexpr int kReplyOk = 1;
constexpr int kReplyErr = 2;
constexpr int kPush = 4;

class ObjectRef {
 public:
  ObjectRef() = default;
  explicit ObjectRef(std::string id) : id_(std::move(id)) {}
  const std::string& id() const { return id_; }
  bool valid() const { return !id_.empty(); }

 private:
  std::string id_;
};

class RayClient {
 public:
  void Connect(const std::string& host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("socket() failed");
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    hostent* he = ::gethostbyname(host.c_str());
    if (!he) throw std::runtime_error("unknown host " + host);
    std::memcpy(&addr.sin_addr, he->h_addr, he->h_length);
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)))
      throw std::runtime_error("connect to " + host + " failed");
  }

  void Close() {
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
  }

  bool connected() const { return fd_ >= 0; }

  // one correlated request/reply (pushes are skipped)
  mp::Value Call(const std::string& method, mp::Map data) {
    int64_t msgid = next_id_++;
    mp::Array frame;
    frame.emplace_back(static_cast<int64_t>(kRequest));
    frame.emplace_back(msgid);
    frame.emplace_back(method);
    frame.emplace_back(mp::Map(std::move(data)));
    SendFrame(mp::pack(mp::Value(std::move(frame))));
    for (;;) {
      mp::Value reply = mp::unpack(RecvFrame());
      const mp::Array& arr = reply.as_array();
      int64_t kind = arr[0].as_int();
      if (kind == kPush) continue;  // pubsub pushes are not our reply
      if (arr[1].as_int() != msgid) continue;  // stale (shouldn't happen)
      if (kind == kReplyErr) {
        // data = [pickled_exc (bin), traceback (str)]
        std::string detail = "remote error";
        if (arr[3].type() == mp::Value::Type::Arr &&
            arr[3].as_array().size() > 1)
          detail = arr[3].as_array()[1].as_str();
        throw std::runtime_error("ray_tpu server error:\n" + detail);
      }
      return arr[3];
    }
  }

 private:
  void SendAll(const char* p, size_t n) {
    while (n) {
      ssize_t w = ::send(fd_, p, n, 0);
      if (w <= 0) throw std::runtime_error("send failed");
      p += w;
      n -= static_cast<size_t>(w);
    }
  }
  void RecvAll(char* p, size_t n) {
    while (n) {
      ssize_t r = ::recv(fd_, p, n, 0);
      if (r <= 0) throw std::runtime_error("connection closed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }
  void SendFrame(const std::string& body) {
    uint32_t len = htonl(static_cast<uint32_t>(body.size()));
    SendAll(reinterpret_cast<const char*>(&len), 4);
    SendAll(body.data(), body.size());
  }
  std::string RecvFrame() {
    uint32_t len_be;
    RecvAll(reinterpret_cast<char*>(&len_be), 4);
    uint32_t len = ntohl(len_be);
    std::string body(len, '\0');
    RecvAll(body.data(), len);
    return body;
  }

  int fd_ = -1;
  std::atomic<int64_t> next_id_{1};
};

// ------------------------------------------------------------ ray:: API

inline RayClient& Client() {
  static RayClient client;
  return client;
}

// ray::Init("host:port") — address of a ray-tpu client server
// (`python -m ray_tpu.util.client.server --address <gcs>`)
inline void Init(const std::string& address) {
  auto colon = address.rfind(':');
  if (colon == std::string::npos)
    throw std::invalid_argument("address must be host:port");
  Client().Connect(address.substr(0, colon),
                   std::stoi(address.substr(colon + 1)));
  Client().Call("ping", {});
}

inline void Shutdown() { Client().Close(); }

inline ObjectRef Put(const mp::Value& value) {
  mp::Map req;
  req.emplace("data", value);
  req.emplace("codec", mp::Value("msgpack"));
  mp::Value reply = Client().Call("put", std::move(req));
  return ObjectRef(reply["ref"].as_str());
}

inline std::vector<mp::Value> Get(const std::vector<ObjectRef>& refs,
                                  double timeout = 120.0) {
  mp::Array ids;
  for (const auto& r : refs) ids.push_back(mp::Value::Bin(r.id()));
  mp::Map req;
  req.emplace("refs", mp::Value(std::move(ids)));
  req.emplace("codec", mp::Value("msgpack"));
  req.emplace("timeout", mp::Value(timeout));
  mp::Value reply = Client().Call("get", std::move(req));
  if (!reply["error_msg"].is_nil())
    throw std::runtime_error(reply["error_msg"].as_str());
  return reply["raw_values"].as_array();
}

inline mp::Value Get(const ObjectRef& ref, double timeout = 120.0) {
  return Get(std::vector<ObjectRef>{ref}, timeout)[0];
}

// ray::Task("module:function").Remote(args...) — submit to the cluster
class TaskCaller {
 public:
  explicit TaskCaller(std::string descriptor)
      : descriptor_(std::move(descriptor)) {}

  TaskCaller& SetResource(const std::string& name, double amount) {
    resources_.emplace(name, mp::Value(amount));
    return *this;
  }

  template <typename... Args>
  ObjectRef Remote(Args&&... args) {
    mp::Array packed;
    (AppendArg(packed, std::forward<Args>(args)), ...);
    mp::Map req;
    req.emplace("name", mp::Value(descriptor_));
    req.emplace("args", mp::Value(std::move(packed)));
    if (!resources_.empty()) {
      mp::Map opts;
      opts.emplace("resources", mp::Value(resources_));
      req.emplace("options", mp::Value(std::move(opts)));
    }
    mp::Value reply = Client().Call("task_by_name", std::move(req));
    return ObjectRef(reply["refs"].as_array()[0].as_str());
  }

 private:
  template <typename T>
  static void AppendArg(mp::Array& out, T&& v) {
    if constexpr (std::is_same_v<std::decay_t<T>, ObjectRef>) {
      // refs travel as {"__ref__": id} placeholders the server
      // rehydrates to its pinned ObjectRef
      mp::Map placeholder;
      placeholder.emplace("__ref__", mp::Value::Bin(v.id()));
      out.emplace_back(std::move(placeholder));
    } else {
      out.emplace_back(mp::Value(std::forward<T>(v)));
    }
  }

  std::string descriptor_;
  mp::Map resources_;
};

inline TaskCaller Task(const std::string& descriptor) {
  return TaskCaller(descriptor);
}

inline mp::Value ClusterResources() {
  return Client().Call("cluster_resources", {});
}

}  // namespace ray
