"""Native (C++) runtime components, built on demand with the system
toolchain (reference split: src/ray/* C++ runtime under the python API)."""
