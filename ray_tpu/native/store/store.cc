// Native shared-memory object store: one mmap'd arena per node, a slab
// (first-fit free-list) allocator and an open-addressed object index, both
// living INSIDE the shared mapping so every process on the node sees one
// coherent store with zero-copy reads and no store-server process.
//
// Plays the role of the reference's plasma store + eviction bookkeeping
// (reference: src/ray/object_manager/plasma/store.h:53, dlmalloc arena in
// plasma/malloc.cc) redesigned for the TPU host: no fd passing, no IPC —
// creation is allocate+memcpy, sealing is one atomic flag store, lookup is
// a lock-free-read hash probe. Cross-process mutual exclusion for
// allocation/deletion uses a robust pthread mutex in the arena header so a
// crashed worker can never deadlock the node.
//
// C ABI (driven from Python via ctypes — see native_store.py):
//   rts_open / rts_close
//   rts_create -> offset   (writable region; caller memcpys then seals)
//   rts_seal
//   rts_get    -> offset,size   (sealed objects only)
//   rts_delete
//   rts_stats

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055464f5255ULL;  // "RTPUFORU" (v3: refs)
constexpr uint32_t kIdBytes = 24;  // ObjectID size (ids.py: TaskID16+tag4+rand4)
constexpr uint32_t kAlign = 64;  // cacheline; also keeps numpy views aligned

enum SlotState : uint32_t {
  kFree = 0,
  kCreating = 1,
  kSealed = 2,
  // Deleted while readers still hold pins: invisible to lookups, block
  // freed when the last pin releases (plasma-style deferred deletion,
  // reference: plasma clients hold objects in use until Release).
  kZombie = 3,
};

struct Slot {
  uint8_t id[kIdBytes];
  uint64_t offset;
  uint64_t size;
  uint32_t state;
  uint32_t probe_live;  // 1 while this slot participates in probe chains
  uint32_t refs;        // outstanding reader pins (rts_get/rts_release)
};

struct Block {  // free-list node, stored at block start inside the arena
  uint64_t size;      // payload capacity of this block
  uint64_t next_off;  // next free block offset (0 = none)
};

struct Header {
  uint64_t magic;
  uint64_t capacity;       // arena bytes after the header/index
  uint64_t data_start;     // file offset where allocatable data begins
  uint64_t free_head;      // offset of first free block (0 = none)
  uint64_t used_bytes;
  uint64_t num_objects;
  uint64_t tombstones;     // kFree slots still holding probe chains open
  uint32_t num_slots;
  pthread_mutex_t mu;      // robust, pshared
};

struct Handle {
  uint8_t* base;
  uint64_t map_len;
  Header* hdr;
  Slot* slots;
};

uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~uint64_t(kAlign - 1); }

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 16-byte id
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdBytes; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

class Lock {
 public:
  explicit Lock(Header* hdr) : hdr_(hdr) {
    int rc = pthread_mutex_lock(&hdr_->mu);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock; state is still consistent for our
      // operations (each op completes its bookkeeping before unlock), so
      // mark it recovered and continue.
      pthread_mutex_consistent(&hdr_->mu);
    }
  }
  ~Lock() { pthread_mutex_unlock(&hdr_->mu); }

 private:
  Header* hdr_;
};

Slot* find_slot(Handle* h, const uint8_t* id, bool want_sealed) {
  uint32_t n = h->hdr->num_slots;
  uint64_t idx = hash_id(id) % n;
  for (uint32_t probes = 0; probes < n; probes++) {
    Slot* s = &h->slots[(idx + probes) % n];
    if (s->state == kFree && !s->probe_live) return nullptr;
    if (s->state != kFree && memcmp(s->id, id, kIdBytes) == 0) {
      if (s->state == kZombie) continue;  // invisible; a fresh slot with
                                          // the same id may live further
                                          // down the chain
      if (want_sealed && s->state != kSealed) return nullptr;
      return s;
    }
  }
  return nullptr;
}

Slot* claim_slot(Handle* h, const uint8_t* id) {
  uint32_t n = h->hdr->num_slots;
  uint64_t idx = hash_id(id) % n;
  for (uint32_t probes = 0; probes < n; probes++) {
    Slot* s = &h->slots[(idx + probes) % n];
    if (s->state == kFree) {
      if (s->probe_live) h->hdr->tombstones--;  // recycling a tombstone
      memcpy(s->id, id, kIdBytes);
      s->probe_live = 1;
      return s;
    }
    if (memcmp(s->id, id, kIdBytes) == 0 && s->state != kZombie)
      return nullptr;  // duplicate (zombies of the id may coexist)
  }
  return nullptr;  // index full
}

// Rebuild the index in place, dropping tombstones (amortized: runs when
// tombstones exceed half the table; keeps miss-lookups O(cluster) instead
// of degrading to full-table scans over the node's lifetime).
void maybe_rehash(Handle* h) {
  Header* hdr = h->hdr;
  if (hdr->tombstones <= hdr->num_slots / 2) return;
  uint32_t n = hdr->num_slots;
  // Collect live slots — count first: num_objects excludes zombies,
  // which must survive a rehash (their pins are still outstanding).
  uint64_t live_n = 0;
  for (uint32_t i = 0; i < n; i++)
    if (h->slots[i].state != kFree) live_n++;
  Slot* live = new Slot[live_n ? live_n : 1];
  uint64_t m = 0;
  for (uint32_t i = 0; i < n; i++) {
    if (h->slots[i].state != kFree) live[m++] = h->slots[i];
    h->slots[i].state = kFree;
    h->slots[i].probe_live = 0;
  }
  hdr->tombstones = 0;
  for (uint64_t j = 0; j < m; j++) {
    uint64_t idx = hash_id(live[j].id) % n;
    for (uint32_t probes = 0; probes < n; probes++) {
      Slot* s = &h->slots[(idx + probes) % n];
      if (s->state == kFree) {
        *s = live[j];
        s->probe_live = 1;
        break;
      }
    }
  }
  delete[] live;
}

// First-fit allocation from the in-arena free list. Returns 0 on failure.
uint64_t alloc_block(Handle* h, uint64_t want) {
  want = align_up(want);
  Header* hdr = h->hdr;
  uint64_t prev_off = 0;
  uint64_t off = hdr->free_head;
  while (off) {
    Block* b = reinterpret_cast<Block*>(h->base + off);
    if (b->size >= want) {
      uint64_t remainder = b->size - want;
      if (remainder >= sizeof(Block) + kAlign) {
        // split: tail remains free
        uint64_t tail_off = off + sizeof(Block) + want;
        Block* tail = reinterpret_cast<Block*>(h->base + tail_off);
        tail->size = remainder - sizeof(Block);
        tail->next_off = b->next_off;
        b->size = want;
        if (prev_off) {
          reinterpret_cast<Block*>(h->base + prev_off)->next_off = tail_off;
        } else {
          hdr->free_head = tail_off;
        }
      } else {
        if (prev_off) {
          reinterpret_cast<Block*>(h->base + prev_off)->next_off = b->next_off;
        } else {
          hdr->free_head = b->next_off;
        }
      }
      hdr->used_bytes += b->size;
      return off + sizeof(Block);  // payload offset
    }
    prev_off = off;
    off = b->next_off;
  }
  return 0;
}

void free_block(Handle* h, uint64_t payload_off) {
  Header* hdr = h->hdr;
  uint64_t off = payload_off - sizeof(Block);
  Block* b = reinterpret_cast<Block*>(h->base + off);
  hdr->used_bytes -= b->size;
  // address-ordered insert + coalesce with neighbours
  uint64_t prev_off = 0;
  uint64_t cur = hdr->free_head;
  while (cur && cur < off) {
    prev_off = cur;
    cur = reinterpret_cast<Block*>(h->base + cur)->next_off;
  }
  b->next_off = cur;
  if (prev_off) {
    Block* prev = reinterpret_cast<Block*>(h->base + prev_off);
    prev->next_off = off;
    // coalesce prev+b
    if (prev_off + sizeof(Block) + prev->size == off) {
      prev->size += sizeof(Block) + b->size;
      prev->next_off = b->next_off;
      b = prev;
      off = prev_off;
    }
  } else {
    hdr->free_head = off;
  }
  // coalesce b+next
  if (b->next_off && off + sizeof(Block) + b->size == b->next_off) {
    Block* next = reinterpret_cast<Block*>(h->base + b->next_off);
    b->size += sizeof(Block) + next->size;
    b->next_off = next->next_off;
  }
}

}  // namespace

extern "C" {

// Open (creating if needed) an arena file with `capacity` data bytes and
// an index sized for `max_objects`. Returns an opaque handle or null.
void* rts_open(const char* path, uint64_t capacity, uint32_t max_objects) {
  if (capacity == 0 || max_objects == 0) return nullptr;
  uint64_t index_bytes = align_up(sizeof(Slot) * uint64_t(max_objects));
  uint64_t data_start = align_up(sizeof(Header)) + index_bytes;
  uint64_t total = data_start + capacity;

  int fd = open(path, O_RDWR | O_CREAT, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  bool fresh = st.st_size == 0;
  if (fresh && ftruncate(fd, int64_t(total)) != 0) {
    close(fd);
    return nullptr;
  }
  if (!fresh) total = uint64_t(st.st_size);

  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;

  Handle* h = new Handle();
  h->base = static_cast<uint8_t*>(mem);
  h->map_len = total;
  h->hdr = reinterpret_cast<Header*>(h->base);
  h->slots = reinterpret_cast<Slot*>(h->base + align_up(sizeof(Header)));

  if (fresh) {
    Header* hdr = h->hdr;
    hdr->capacity = capacity;
    hdr->data_start = data_start;
    hdr->num_slots = max_objects;
    hdr->used_bytes = 0;
    hdr->num_objects = 0;
    // one big free block
    Block* b = reinterpret_cast<Block*>(h->base + data_start);
    b->size = capacity - sizeof(Block);
    b->next_off = 0;
    hdr->free_head = data_start;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->mu, &attr);
    pthread_mutexattr_destroy(&attr);
    __atomic_store_n(&hdr->magic, kMagic, __ATOMIC_RELEASE);
  } else {
    // wait for another opener's initialization to become visible
    for (int i = 0; i < 1000000; i++) {
      if (__atomic_load_n(&h->hdr->magic, __ATOMIC_ACQUIRE) == kMagic) break;
    }
    if (h->hdr->magic != kMagic) {
      munmap(mem, total);
      delete h;
      return nullptr;
    }
  }
  return h;
}

void rts_close(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  if (!h) return;
  munmap(h->base, h->map_len);
  delete h;
}

// Allocate space for an object; returns the arena OFFSET of the writable
// payload, or 0 on failure (exists / out of space / index full).
uint64_t rts_create(void* handle, const uint8_t* id, uint64_t size) {
  Handle* h = static_cast<Handle*>(handle);
  Lock lock(h->hdr);
  if (find_slot(h, id, /*want_sealed=*/false)) return 0;
  Slot* s = claim_slot(h, id);
  if (!s) return 0;
  uint64_t payload = alloc_block(h, size ? size : 1);
  if (!payload) {
    s->state = kFree;  // probe_live stays 1: keeps chains intact
    h->hdr->tombstones++;
    return 0;
  }
  s->offset = payload;
  s->size = size;
  s->refs = 0;
  __atomic_store_n(&s->state, kCreating, __ATOMIC_RELEASE);
  h->hdr->num_objects++;
  return payload;
}

int rts_seal(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  Lock lock(h->hdr);
  Slot* s = find_slot(h, id, false);
  if (!s || s->state != kCreating) return -1;
  __atomic_store_n(&s->state, kSealed, __ATOMIC_RELEASE);
  return 0;
}

// Look up a sealed object; fills offset+size and takes a reader PIN
// (caller must balance with rts_release). Returns 0 on hit, -1 miss.
int rts_get(void* handle, const uint8_t* id, uint64_t* offset,
            uint64_t* size) {
  Handle* h = static_cast<Handle*>(handle);
  Lock lock(h->hdr);
  Slot* s = find_slot(h, id, /*want_sealed=*/true);
  if (!s) return -1;
  s->refs++;
  *offset = s->offset;
  *size = s->size;
  return 0;
}

// Drop one reader pin. `offset` (from the matching rts_get) names the
// exact BLOCK: an id alone is ambiguous once an object is overwritten
// while pinned (old zombie generation + new sealed generation share the
// id, and freeing the wrong one would corrupt the other's readers).
// The last release of a zombie frees its block. Returns 0, or -1 if no
// pinned slot matches.
int rts_release(void* handle, const uint8_t* id, uint64_t offset) {
  Handle* h = static_cast<Handle*>(handle);
  Lock lock(h->hdr);
  uint32_t n = h->hdr->num_slots;
  uint64_t idx = hash_id(id) % n;
  for (uint32_t probes = 0; probes < n; probes++) {
    Slot* s = &h->slots[(idx + probes) % n];
    if (s->state == kFree && !s->probe_live) break;
    if (s->state != kFree && s->offset == offset && s->refs > 0 &&
        memcmp(s->id, id, kIdBytes) == 0) {
      s->refs--;
      if (s->state == kZombie && s->refs == 0) {
        free_block(h, s->offset);
        s->state = kFree;
        h->hdr->tombstones++;
        maybe_rehash(h);
      }
      return 0;
    }
  }
  return -1;
}

int rts_contains(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  Lock lock(h->hdr);
  return find_slot(h, id, true) ? 1 : 0;
}

// Delete (sealed or aborted) object. Unpinned: frees the block now.
// Pinned: becomes a zombie — invisible immediately, block freed by the
// last rts_release. Returns the object's (logical) size either way.
uint64_t rts_delete(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  Lock lock(h->hdr);
  Slot* s = find_slot(h, id, false);
  if (!s || s->state == kFree) return 0;
  uint64_t freed = s->size;
  h->hdr->num_objects--;
  if (s->refs > 0) {
    __atomic_store_n(&s->state, kZombie, __ATOMIC_RELEASE);
    return freed;
  }
  free_block(h, s->offset);
  s->state = kFree;  // probe_live stays 1 so longer chains keep working
  h->hdr->tombstones++;
  maybe_rehash(h);
  return freed;
}

void rts_stats(void* handle, uint64_t* capacity, uint64_t* used,
               uint64_t* num_objects) {
  Handle* h = static_cast<Handle*>(handle);
  Lock lock(h->hdr);
  *capacity = h->hdr->capacity;
  *used = h->hdr->used_bytes;
  *num_objects = h->hdr->num_objects;
}

// Base pointer of the mapping (Python builds zero-copy memoryviews from
// base+offset).
uint8_t* rts_base(void* handle) {
  return static_cast<Handle*>(handle)->base;
}

uint64_t rts_map_len(void* handle) {
  return static_cast<Handle*>(handle)->map_len;
}

}  // extern "C"
