"""ctypes front for the C++ shared-arena object store (store.cc).

Drop-in for LocalObjectStore (object_store.py): same create/seal/get/
delete/contains surface, but objects live inside ONE mmap'd arena managed
by the native slab allocator instead of a file per object — small-object
churn costs an allocation + memcpy, not create/unlink syscalls, and every
process on the node shares one coherent index (reference role: the plasma
store process + its dlmalloc arena, src/ray/object_manager/plasma/).
Measured on this image: 10MB put+get 3.3 -> 4.7 GB/s, 200KB objects
885 -> 1206/s vs the files backend.

Reader safety (why this can be the DEFAULT backend): every `get` takes a
native pin held by the returned buffer's exporter (_PinnedBlock); a
delete while pins are outstanding turns the slot into a zombie — gone
from lookups, block freed by the last release — so zero-copy views can
never read reused memory (the per-client Get/Release bookkeeping plasma
does in the reference, plasma/client.h). A crashed process leaks its
pins (bounded by what it had mapped); the arena is per-session, so the
leak dies with the session. The same pin/zombie mechanism is what makes
raylet spill-to-disk safe here (raylet.py _maybe_spill): spill deletes
after copying, and a delete under outstanding pins only zombifies."""

from __future__ import annotations

import ctypes
import mmap
import os
import sys

from ray_tpu._private.ids import ObjectID

_lib = None
_lib_err: str | None = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        from ray_tpu.native.build import build_library

        path = build_library("rts_store", ["store/store.cc"])
        if path is None:
            _lib_err = "no C++ compiler available"
            return None
        lib = ctypes.CDLL(path)
        lib.rts_open.restype = ctypes.c_void_p
        lib.rts_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_uint32]
        lib.rts_close.argtypes = [ctypes.c_void_p]
        lib.rts_create.restype = ctypes.c_uint64
        lib.rts_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
        lib.rts_seal.restype = ctypes.c_int
        lib.rts_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_get.restype = ctypes.c_int
        lib.rts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_uint64),
                                ctypes.POINTER(ctypes.c_uint64)]
        lib.rts_release.restype = ctypes.c_int
        lib.rts_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64]
        lib.rts_contains.restype = ctypes.c_int
        lib.rts_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_delete.restype = ctypes.c_uint64
        lib.rts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_stats.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_uint64)] * 3
        lib.rts_map_len.restype = ctypes.c_uint64
        lib.rts_map_len.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception as e:  # pragma: no cover - toolchain problems
        _lib_err = str(e)
        return None
    return _lib


def native_store_available() -> bool:
    return _load() is not None


class _ArenaBuffer:
    """Writable/readable zero-copy view into the arena mapping."""

    def __init__(self, view: memoryview, size: int):
        self.view = view[:size]
        self.size = size

    def close(self):
        try:
            self.view.release()
        except (BufferError, ValueError):
            pass


class _PinnedBlock:
    """Zero-copy reader view that holds an arena PIN for its lifetime.

    Buffer-protocol exporter (PEP 688): `memoryview(block)` and every
    slice of it share one export; when the LAST view is released —
    including numpy arrays deserialized zero-copy out of the payload —
    __release_buffer__ fires and drops the native pin, letting a
    deleted-while-read block (zombie) actually free. This is the
    per-client Release bookkeeping plasma does in the reference
    (plasma/client.h Get/Release)."""

    __slots__ = ("_store", "_oid", "_offset", "_view")

    def __init__(self, store: "NativeObjectStore", oid: bytes,
                 offset: int, view: memoryview):
        self._store = store
        self._oid = oid
        self._offset = offset  # names the exact block generation
        self._view = view

    def __buffer__(self, flags):
        return self._view

    def __release_buffer__(self, view):
        try:
            self._store._release(self._oid, self._offset)
        finally:
            try:
                self._view.release()
            except (BufferError, ValueError):
                pass


class _RawBuffer:
    """Arena view whose pin is released by an explicit close() (see
    NativeObjectStore.get_raw). Double-close safe."""

    __slots__ = ("view", "size", "_store", "_oid", "_offset", "_closed")

    def __init__(self, store, oid: bytes, offset: int, view: memoryview,
                 size: int):
        self.view = view
        self.size = size
        self._store = store
        self._oid = oid
        self._offset = offset
        self._closed = False

    def close(self):
        if self._closed:
            return
        self._closed = True
        try:
            self.view.release()
        except (BufferError, ValueError):
            pass
        self._store._release(self._oid, self._offset)


class NativeObjectStore:
    """LocalObjectStore-compatible backend over the C++ arena."""

    def __init__(self, root: str, capacity: int = 1 << 30,
                 max_objects: int = 1 << 16):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native store unavailable: {_lib_err}")
        os.makedirs(root, exist_ok=True)
        self.root = root
        self._path = os.path.join(root, "arena.rts")
        self._lib = lib
        self._h = lib.rts_open(self._path.encode(), capacity, max_objects)
        if not self._h:
            raise RuntimeError(f"rts_open failed for {self._path}")
        # One python-side mmap of the same file for memoryview access
        # (ctypes base pointers can't become memoryviews safely).
        fd = os.open(self._path, os.O_RDWR)
        try:
            self._map = mmap.mmap(fd, lib.rts_map_len(self._h))
        finally:
            os.close(fd)
        self._mv = memoryview(self._map)

    # -- LocalObjectStore surface ---------------------------------------

    def create(self, object_id: ObjectID, size: int) -> _ArenaBuffer:
        oid = object_id.binary()
        assert len(oid) == 24, f"ObjectID must be 24 bytes, got {len(oid)}"
        off = self._lib.rts_create(self._h, oid, size)
        if not off:
            # Files-backend semantics: a re-put of an existing (or
            # half-created) object overwrites it — e.g. a reconstructed
            # task re-producing its return. Drop the old entry and retry;
            # if the id wasn't present this is a no-op and the retry
            # distinguishes true OOM.
            self._lib.rts_delete(self._h, oid)
            off = self._lib.rts_create(self._h, oid, size)
        if not off:
            raise MemoryError(
                f"native store: cannot allocate {size} bytes for "
                f"{object_id.hex()[:12]} — the arena is full (the raylet "
                f"spills above object_spilling_threshold, but zombie "
                f"blocks pinned by live readers hold bytes until "
                f"released; raise object_store_memory for headroom)")
        return _ArenaBuffer(self._mv[off:off + size], size)

    def seal(self, object_id: ObjectID) -> None:
        if self._lib.rts_seal(self._h, object_id.binary()) != 0:
            raise KeyError(f"seal of unknown object {object_id.hex()[:12]}")

    def abort(self, object_id: ObjectID) -> None:
        self._lib.rts_delete(self._h, object_id.binary())

    def contains(self, object_id: ObjectID) -> bool:
        return bool(self._lib.rts_contains(self._h, object_id.binary()))

    def _release(self, oid: bytes, offset: int):
        if self._h:
            self._lib.rts_release(self._h, oid, offset)

    def get(self, object_id: ObjectID) -> _ArenaBuffer | None:
        """Pinned zero-copy read: the returned buffer (and anything
        deserialized out of it) holds a native pin until every view
        dies, so owner-driven deletes can never corrupt live readers
        (they defer via zombie blocks)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        oid = object_id.binary()
        rc = self._lib.rts_get(self._h, oid,
                               ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        raw = self._mv[off.value:off.value + size.value]
        if sys.version_info >= (3, 12):
            pinned = _PinnedBlock(self, oid, off.value, raw)
            return _ArenaBuffer(memoryview(pinned), size.value)
        # Python < 3.12 cannot export the buffer protocol from pure
        # Python (PEP 688), so the pinned zero-copy path is unavailable:
        # copy the payload out and drop the pin immediately. One memcpy
        # slower than 3.12, but views can never see reused arena memory.
        try:
            data = bytes(raw)
        finally:
            raw.release()
            self._release(oid, off.value)
        return _ArenaBuffer(memoryview(data), size.value)

    def get_raw(self, object_id: ObjectID) -> "_RawBuffer | None":
        """Pinned zero-copy read with EXPLICIT lifetime: the returned
        buffer's view aliases the arena directly and close() drops the
        native pin by hand. For runtime-internal readers (the bulk
        transfer server) that own the buffer for a bounded scope — the
        view MUST NOT be touched after close(). Unlike get(), this is
        zero-copy on every Python version: release is explicit, so no
        PEP-688 buffer-protocol export is needed."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        oid = object_id.binary()
        rc = self._lib.rts_get(self._h, oid,
                               ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        raw = self._mv[off.value:off.value + size.value]
        return _RawBuffer(self, oid, off.value, raw, size.value)

    def size_of(self, object_id: ObjectID) -> int:
        # size-only: rts_get already returns it — don't materialize the
        # payload (on <3.12 get() copies the whole object out)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        oid = object_id.binary()
        rc = self._lib.rts_get(self._h, oid,
                               ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            raise FileNotFoundError(object_id.hex())
        self._release(oid, off.value)
        return size.value

    def delete(self, object_id: ObjectID) -> int:
        return int(self._lib.rts_delete(self._h, object_id.binary()))

    def put_serialized(self, object_id: ObjectID, header: bytes,
                       buffers: list[memoryview]) -> int:
        total = len(header) + sum(b.nbytes for b in buffers)
        buf = self.create(object_id, total)
        try:
            view = buf.view
            view[:len(header)] = header
            off = len(header)
            for b in buffers:
                flat = b.cast("B") if (b.ndim != 1 or b.format != "B") else b
                view[off:off + flat.nbytes] = flat
                off += flat.nbytes
            buf.close()
            self.seal(object_id)
        except BaseException:
            buf.close()
            self.abort(object_id)
            raise
        return total

    def put_bytes(self, object_id: ObjectID, data) -> int:
        return self.put_serialized(object_id, b"",
                                   [memoryview(data).cast("B")])

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        n = ctypes.c_uint64()
        self._lib.rts_stats(self._h, ctypes.byref(cap), ctypes.byref(used),
                            ctypes.byref(n))
        return {"capacity": cap.value, "used": used.value,
                "num_objects": n.value}

    def list_objects(self) -> list[ObjectID]:  # not tracked natively
        return []

    def close(self):
        try:
            self._mv.release()
            self._map.close()
        except (BufferError, ValueError):
            pass
        if self._h:
            self._lib.rts_close(self._h)
            self._h = None
