from ray_tpu.native.store.native_store import (NativeObjectStore,
                                               native_store_available)

__all__ = ["NativeObjectStore", "native_store_available"]
