from ray_tpu.native.store.native_store import (NativeObjectStore,
                                               native_store_available)
from ray_tpu.native.store.segment import (SharedSegment, create_segment,
                                          is_shared_memory_path,
                                          open_segment, segment_dir)

__all__ = ["NativeObjectStore", "SharedSegment", "create_segment",
           "is_shared_memory_path", "native_store_available",
           "open_segment", "segment_dir"]
