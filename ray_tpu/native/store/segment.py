"""Shared-memory scratch segments for the collective data plane.

Extends the native arena's placement — the same /dev/shm session
directory whose tmpfs pages make the object store do multi-GB/s — with a
segment-allocation API that skips the object-id/pin machinery entirely:
a collective segment is group-private scratch with its own lifecycle
(created by rank 0, mapped by every rank on the node, unlinked on group
destroy), not an object anyone else can look up. When no runtime store
is up (bare HostGroup in tests) the segment falls back to a plain mmap
file under /dev/shm, or the tempdir as a last resort.

The returned mapping is MAP_SHARED on one tmpfs file, so every process
that opens it sees one coherent set of physical pages — stores by one
rank are loads for the others with zero syscalls in between. That
coherence claim only holds for node-local filesystems; callers gate on
node identity (and /dev/shm placement) before trusting it.
"""

from __future__ import annotations

import mmap
import os
import tempfile


def segment_dir() -> str:
    """Directory for collective segments: beside the session's store
    arena when a runtime is up (same tmpfs, same lifecycle), else a
    process-independent /dev/shm path, else the tempdir."""
    try:
        from ray_tpu._private import global_state

        cw = global_state.get_core_worker()
        root = getattr(getattr(cw, "store", None), "root", None)
        if root:
            return os.path.join(
                os.path.dirname(os.path.abspath(root)), "colseg")
    except Exception:
        pass
    shm = "/dev/shm"
    if os.path.isdir(shm) and os.access(shm, os.W_OK):
        return os.path.join(shm, "ray_tpu_colseg")
    return os.path.join(tempfile.gettempdir(), "ray_tpu_colseg")


def is_shared_memory_path(path: str) -> bool:
    """True when `path` lives on a filesystem we trust to be node-local
    shared memory (tmpfs under /dev/shm)."""
    return os.path.abspath(path).startswith("/dev/shm/")


class SharedSegment:
    """One mmap'd scratch file shared by every rank on a node."""

    def __init__(self, path: str, size: int, create: bool):
        self.path = path
        self.size = size
        self.owner = create
        if create:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        else:
            fd = os.open(path, os.O_RDWR)
        try:
            if create:
                # Reserve capacity NOW: a sparse ftruncate on a tmpfs
                # near its limit would mmap fine and then SIGBUS (an
                # uncatchable rank death) on the first write past the
                # fs limit; fallocate turns that into a clean ENOSPC
                # the caller converts into a transport fallback.
                try:
                    os.posix_fallocate(fd, 0, size)
                except OSError:
                    os.unlink(path)  # enclosing finally closes fd
                    raise
            elif os.fstat(fd).st_size < size:
                raise ValueError(
                    f"segment {path} is {os.fstat(fd).st_size} bytes, "
                    f"need {size}")
            self._map = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.view = memoryview(self._map)

    def close(self, unlink: bool | None = None):
        """Release the mapping; the creator also unlinks the file by
        default (tmpfs bytes are freed when the last mapping dies)."""
        try:
            self.view.release()
            self._map.close()
        except (BufferError, ValueError):
            pass  # outstanding numpy views keep the mapping alive
        if unlink is None:
            unlink = self.owner
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


def create_segment(name: str, size: int) -> SharedSegment:
    return SharedSegment(os.path.join(segment_dir(), name), size,
                         create=True)


def open_segment(path: str, size: int) -> SharedSegment:
    return SharedSegment(path, size, create=False)
