"""Device mesh construction for 5-axis parallelism.

The TPU-native resource model the reference lacks (SURVEY §2.4: TP/PP/SP/EP
absent upstream): one jax Mesh with named axes

    dp — data parallel (gradient allreduce; DCN-friendly outer axis)
    pp — pipeline stages (ppermute microbatch schedule)
    sp — sequence/context parallel (ring attention)
    tp — tensor parallel (heads/mlp sharding; highest-bandwidth ICI axis)
    ep — expert parallel (MoE all_to_all)

Axis order puts dp outermost and tp innermost so tp collectives ride the
fastest ICI links on real slices (the "How to Scale Your Model" recipe:
mesh axes ordered by communication intensity).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "pp", "sp", "tp", "ep")

# Device-count -> (data, fsdp) 2D mesh shapes (SNIPPETS [2]: the
# auto-sharder's predefined optimal shapes for TPU pod slices).
# ROADMAP item 3's FSDP ('data','fsdp') mode consumes this, and the
# ICI_RING placement strategy records it with each gang so rank
# ordering and the derived mesh agree on the same factorization. The
# implementation lives jax-free in _private/topology.py because the
# GCS placement scorer (a control-plane process that never imports
# jax) shares it; this is its public home.
from ray_tpu._private.topology import (  # noqa: E402  (re-export)
    MESH_SHAPES as _MESH_SHAPES,
    mesh_shape_for,
)


def axis_size(axis_name: str) -> int:
    """Version-portable mapped-axis size (call INSIDE shard_map):
    jax.lax.axis_size is newer API; on older jax the classic
    `psum(1, axis)` idiom folds to the same static int."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(fn, mesh, in_specs, out_specs):
    """Version-portable shard_map (the ONE shim every sharded kernel and
    the collective device tier use): jax >= 0.6 exposes `jax.shard_map`
    with `check_vma`; older releases only have
    jax.experimental.shard_map with `check_rep`."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.sp * self.tp * self.ep

    def axis_sizes(self) -> tuple[int, ...]:
        return (self.dp, self.pp, self.sp, self.tp, self.ep)

    @classmethod
    def auto(cls, n_devices: int, *, tp: int = 1, pp: int = 1, sp: int = 1,
             ep: int = 1) -> "MeshSpec":
        """Fill dp with whatever devices remain after the model axes."""
        model = tp * pp * sp * ep
        if n_devices % model:
            raise ValueError(
                f"{n_devices} devices not divisible by tp*pp*sp*ep={model}")
        return cls(dp=n_devices // model, pp=pp, sp=sp, tp=tp, ep=ep)

    def build(self, devices=None) -> Mesh:
        devices = list(devices) if devices is not None else jax.devices()
        if len(devices) < self.size:
            raise ValueError(
                f"mesh needs {self.size} devices, have {len(devices)}")
        devices = devices[: self.size]
        arr = np.array(devices).reshape(self.axis_sizes())
        return Mesh(arr, AXES)

    @classmethod
    def from_placement_group(cls, pg, *, tp: int | None = None, pp: int = 1,
                             sp: int = 1, ep: int = 1) -> "MeshSpec":
        """Derive the mesh from an actual TPU reservation, so shardings
        follow placement instead of convention (closing SURVEY §7 step 4:
        "STRICT_PACK = one ICI host" used to be a docstring).

        Each bundle is one slice host contributing its TPU chips. tp
        defaults to chips-per-host — tp is the innermost mesh axis, so
        tensor-parallel collectives ride the within-host ICI island; dp
        fills the remaining (cross-host) factor.
        """
        bundles = pg.bundle_specs if hasattr(pg, "bundle_specs") else pg
        chips = [int(b.get("TPU", 0)) for b in bundles]
        if not chips or any(c <= 0 for c in chips):
            raise ValueError(
                "placement group has bundles without TPU chips; "
                f"bundle resources: {bundles}")
        if len(set(chips)) != 1:
            raise ValueError(
                f"heterogeneous chips per bundle {chips}: a mesh needs "
                "equal chips per host")
        total = sum(chips)
        if tp is None:
            tp = chips[0]
        return cls.auto(total, tp=tp, pp=pp, sp=sp, ep=ep)


def fsdp_mesh(devices=None) -> Mesh:
    """The topology-derived ('data', 'fsdp') mesh for
    Trainer(mesh_mode="fsdp"): device count -> mesh_shape_for's
    predefined (data, fsdp) factorization — the SAME table the ICI_RING
    placement record carries, so gang rank order and mesh layout agree.
    Batch shards over 'data', params/optimizer state over 'fsdp'."""
    devices = list(devices) if devices is not None else jax.devices()
    shape = mesh_shape_for(len(devices))
    n = shape[0] * shape[1]
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, ("data", "fsdp"))


def fsdp_param_specs(params, mesh: Mesh):
    """Per-leaf PartitionSpecs sharding each param over the 'fsdp' axis
    along its leading dimension when that divides evenly; small or
    indivisible leaves (biases, scalars) stay replicated — the standard
    FSDP layout compromise."""
    fsdp = mesh.shape["fsdp"]

    def spec(p):
        shape = getattr(p, "shape", ())
        if shape and shape[0] % fsdp == 0 and shape[0] >= fsdp > 1:
            return P("fsdp", *([None] * (len(shape) - 1)))
        return P()

    return jax.tree.map(spec, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Inputs: batch over dp, sequence over sp."""
    return NamedSharding(mesh, P("dp", "sp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_mesh_spec(*, tp: int = 1, pp: int = 1, sp: int = 1,
                    ep: int = 1) -> MeshSpec:
    return MeshSpec.auto(len(jax.devices()), tp=tp, pp=pp, sp=sp, ep=ep)
