"""Ulysses sequence parallelism: all-to-all head/sequence transposition
(DeepSpeed-Ulysses; capability absent from the reference, SURVEY §2.4 —
supplied as the second SP primitive next to ring attention).

Each device on the `sp` axis holds a sequence shard [B, S/sp, H, D]. One
all_to_all re-partitions to [B, S, H/sp, D] — full sequence, head shard —
so every device runs ordinary (flash-able) attention for its heads with
NO inner communication; a second all_to_all transposes back. Total
traffic is 2 all-to-alls of the activation (vs ring attention's sp-step
ppermute pipeline): cheaper on all-to-all-friendly fabrics and for short
rings, while ring attention wins when S is huge and overlap matters —
that trade-off is why both exist.

Constraint: num_heads % sp == 0 (heads are the second shard axis)."""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.ring_attention import reference_attention


def ulysses_attention(q, k, v, *, axis_name: str = "sp",
                      causal: bool = True, scale: float | None = None):
    """Call INSIDE shard_map: q,k,v local [B, S_local, H, D], sequence
    sharded over `axis_name`. Returns the local output shard."""
    from ray_tpu.parallel.mesh import axis_size

    sp = axis_size(axis_name)
    b, s_local, h, d = q.shape
    if h % sp:
        raise ValueError(
            f"ulysses needs num_heads divisible by the sp axis "
            f"({h} % {sp} != 0); use ring_attention instead")
    if sp == 1:
        return reference_attention(q, k, v, causal=causal, scale=scale)

    def seq_to_head(x):
        # [B, S/sp, H, D] -> [B, S, H/sp, D]: split heads across the
        # axis, gather the full sequence
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    out = reference_attention(qg, kg, vg, causal=causal, scale=scale)
    # [B, S, H/sp, D] -> [B, S/sp, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, *,
                              causal: bool = True,
                              batch_axis: str = "dp",
                              seq_axis: str = "sp"):
    """Driver-level entry: q,k,v global [B, S, H, D]; batch over dp,
    sequence over sp (heads stay replicated outside, sharded inside)."""
    spec = P(batch_axis, seq_axis, None, None)
    from ray_tpu.parallel.mesh import shard_map

    fn = shard_map(
        functools.partial(ulysses_attention, axis_name=seq_axis,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
