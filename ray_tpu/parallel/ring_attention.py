"""Ring attention: exact attention over sequence shards with a ppermute
ring (sequence/context parallelism — capability absent from the reference,
SURVEY §2.4; supplied here as a first-class primitive).

Each device on the `sp` axis holds a sequence block of Q, K, V. K/V blocks
rotate around the ring; every step each device accumulates its Q block's
attention against the visiting K/V block with streaming (flash-style)
softmax — max/denominator carried in float32 — so the result is exact
regardless of ring size. Communication (ppermute over ICI) overlaps with
the block matmuls under XLA's latency-hiding scheduler.

Causal masking uses global positions derived from each block's ring
origin, so blocks whose keys are entirely in the future are fully masked
(they still transit the ring — uniform schedule keeps the ICI pattern
static and XLA-friendly).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _block_attn(q, k, v, o, m, l, q_offset, kv_offset, causal, scale):
    """One streaming-softmax accumulation step.

    q: [B, Tq, H, D]   k/v: [B, Tk, H, D]
    o: [B, Tq, H, D] f32 accumulator, m/l: [B, H, Tq] f32 running max/denom.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(tq)[:, None]
        k_pos = kv_offset + jnp.arange(tk)[None, :]
        mask = q_pos >= k_pos
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    block_max = jnp.max(scores, axis=-1)  # [B,H,Tq]
    new_m = jnp.maximum(m, block_max)
    # fully-masked rows have new_m == -inf; keep exp() finite
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    p = jnp.exp(scores - safe_m[..., None])  # [B,H,Tq,Tk]
    if causal:
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    l_new = l * correction + p.sum(-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                    preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, new_m, l_new


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   scale: float | None = None):
    """Call INSIDE shard_map: q,k,v are local blocks [B, T_local, H, D]
    sharded along T over `axis_name`. Returns the local output block."""
    from ray_tpu.parallel.mesh import axis_size

    sp = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_local), jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    q_offset = idx * t_local
    for step in range(sp):
        kv_origin = (idx - step) % sp
        o, m, l = _block_attn(q, k, v, o, m, l,
                              q_offset, kv_origin * t_local, causal, scale)
        if step != sp - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
    denom = jnp.where(l > 0, l, 1.0).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, *, causal: bool = True,
                           batch_axis: str = "dp", seq_axis: str = "sp",
                           head_axis: str = "tp"):
    """Driver-level entry: q,k,v are global [B, T, H, D]; batch sharded over
    dp, sequence over sp, heads over tp."""
    spec = P(batch_axis, seq_axis, head_axis, None)
    from ray_tpu.parallel.mesh import shard_map

    fn = shard_map(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True,
                        scale: float | None = None):
    """Dense reference used in tests and as the sp=1 fast path."""
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)
