"""Multi-host meshes: K worker-actor processes form ONE global JAX
runtime, so `pjit` over a global Mesh spans hosts and XLA's compiled
collectives (psum/all_gather over ICI/DCN) are the gradient plane.

This is the TPU-native replacement for the reference's process-group
rendezvous (reference: python/ray/util/sgd/torch/worker_group.py:153
_setup_process_group + util/collective NCCL groups): instead of wiring
NCCL communicators, actors rendezvous a jax.distributed runtime through
the GCS KV store and then just build a Mesh over `jax.devices()` — which
is now the *global* device list.

Promised by ray_tpu.collective.backends.xla_backend since round 2; built
here. Works identically on TPU pods (PJRT distributed) and in tests
(multi-process CPU with xla_force_host_platform_device_count)."""

from __future__ import annotations

import logging
import os
import socket
import time

logger = logging.getLogger("ray_tpu.multihost")

_KV_PREFIX = "multihost"
_initialized_group: str | None = None


def _host_ip() -> str:
    """Routable-ish address for the coordinator service."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))  # no traffic sent; picks the route
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


def initialize(group_name: str, world_size: int, rank: int,
               *, coordinator_port: int | None = None,
               timeout: float = 60.0, local_device_ids=None) -> str:
    """Join this process into the `group_name` global JAX runtime.

    Rank 0 hosts the jax.distributed coordinator and publishes its
    address under a GCS KV key; other ranks poll the key. Must be called
    before this process's first JAX backend use (the runtime is wired at
    backend-init time). Idempotent per process.

    Returns the coordinator address.
    """
    global _initialized_group
    if _initialized_group is not None:
        if _initialized_group != group_name:
            raise RuntimeError(
                f"process already in multihost group {_initialized_group!r}")
        from ray_tpu.experimental import internal_kv

        return internal_kv._kv_get(_key(group_name)).decode()

    from ray_tpu.experimental import internal_kv

    key = _key(group_name)
    if rank == 0:
        from ray_tpu._private.rpc import free_port

        port = coordinator_port or free_port()
        addr = f"{_host_ip()}:{port}"
        internal_kv._kv_put(key, addr.encode())
    else:
        deadline = time.monotonic() + timeout
        addr_b = None
        while time.monotonic() < deadline:
            addr_b = internal_kv._kv_get(key)
            if addr_b:
                break
            time.sleep(0.05)
        if not addr_b:
            raise TimeoutError(
                f"multihost group {group_name!r}: coordinator address "
                f"never appeared in GCS KV")
        addr = addr_b.decode()

    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        # CPU runtimes (tests under --xla_force_host_platform_device_count)
        # need the gloo collective implementation wired in BEFORE backend
        # init, or every cross-process computation fails with
        # "Multiprocess computations aren't implemented on the CPU
        # backend" — which also starves the collective DEVICE tier.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            logger.debug("jax_cpu_collectives_implementation knob absent; "
                         "assuming this jax defaults to a working one")
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=world_size,
        process_id=rank, local_device_ids=local_device_ids)
    _initialized_group = group_name
    logger.info("joined multihost group %s as rank %d/%d (coordinator %s); "
                "%d global devices", group_name, rank, world_size, addr,
                jax.device_count())
    return addr


def _key(group_name: str) -> str:
    return f"{_KV_PREFIX}:{group_name}:coordinator"


def is_initialized() -> bool:
    return _initialized_group is not None


def shard_host_batch(batch, sharding):
    """Per-process local batch shard -> global jax.Array.

    Each process passes ITS slice of the global batch (e.g. with a
    'dp'-sharded global batch of size B over P processes, each passes
    B/P rows); rows land on that process's local devices — host data
    never crosses hosts (XLA collectives move only what the computation
    needs)."""
    import jax

    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x),
        batch)
