"""Pipeline parallelism: GPipe microbatch schedule over the `pp` mesh axis
(capability absent from the reference, SURVEY §2.4 — nearest analog was
streaming channels N16).

Each device on the pp axis holds one stage's parameters (stacked leading
`stage` axis sharded over pp). Activations flow stage-to-stage with
ppermute; the schedule runs M + P - 1 ticks for M microbatches over P
stages. Everything is a static python loop — XLA sees a fixed ICI
communication pattern it can software-pipeline.

Backward just works: jax differentiates through ppermute, producing the
mirrored reverse schedule.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(stage_params, x_micro, *, stage_fn: Callable,
                    axis_name: str):
    """Runs inside shard_map. stage_params: this stage's params (leading
    stage axis already sliced to size 1 — squeezed here). x_micro:
    [M, mb, ...] microbatched input (replicated; only stage 0 reads it).
    Returns [M, mb, ...] outputs (replicated via masked psum)."""
    from ray_tpu.parallel.mesh import axis_size

    pp = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)
    m = x_micro.shape[0]
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    carry = jnp.zeros_like(x_micro[0])  # inter-stage activation register
    outputs = jnp.zeros_like(x_micro)
    for tick in range(m + pp - 1):
        # stage 0 injects microbatch `tick` (if still in range)
        inject = x_micro[jnp.minimum(tick, m - 1)]
        stage_in = jnp.where(idx == 0,
                             jnp.where(tick < m, inject, jnp.zeros_like(inject)),
                             carry)
        y = stage_fn(params, stage_in)
        # last stage commits microbatch (tick - pp + 1)
        out_slot = tick - (pp - 1)
        if 0 <= out_slot < m:
            commit = jnp.where(idx == pp - 1, 1.0, 0.0)
            outputs = outputs.at[out_slot].add(
                (commit * y).astype(outputs.dtype))
        carry = jax.lax.ppermute(y, axis_name, perm)
    # replicate last-stage outputs to all pp ranks
    return jax.lax.psum(outputs, axis_name)


def pipeline_apply(stage_fn: Callable, stage_params, x, *,
                   mesh: Mesh, num_microbatches: int, axis_name: str = "pp",
                   data_axis: str = "dp"):
    """stage_fn(params, x) -> y with matching x/y shapes (transformer-block
    stack). stage_params: pytree with leading `stage` axis of size pp.
    x: [B, ...] global batch (sharded over dp)."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError("batch not divisible by num_microbatches")
    x_micro = x.reshape((num_microbatches, b // num_microbatches)
                        + x.shape[1:])

    from ray_tpu.parallel.mesh import shard_map

    fn = shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P(None, data_axis)),
        out_specs=P(None, data_axis),
    )
    y_micro = fn(stage_params, x_micro)
    return y_micro.reshape((b,) + y_micro.shape[2:])


def stack_stage_params(per_stage_params: list):
    """Stack per-stage param pytrees along a new leading `stage` axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
