"""Expert parallelism: Switch-style top-1 MoE with all_to_all dispatch over
the `ep` mesh axis (capability absent from the reference, SURVEY §2.4).

Dense-dispatch formulation (einsum with one-hot dispatch/combine masks):
no gathers/scatters with dynamic shapes, so everything tiles onto the MXU
and the only cross-device traffic is two all_to_alls on [experts, capacity,
model] buffers riding ICI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def top1_routing(router_logits, capacity: int):
    """router_logits: [N, E]. Returns (dispatch [N,E,C], combine [N,E,C],
    aux_loss scalar)."""
    n, e = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [N]
    expert_mask = jax.nn.one_hot(expert_idx, e, dtype=probs.dtype)  # [N,E]
    # load-balancing auxiliary loss (Switch Transformer eq. 4)
    density = expert_mask.mean(0)
    density_proxy = probs.mean(0)
    aux_loss = (density * density_proxy).sum() * e
    # position of each token within its expert's capacity buffer
    position = (jnp.cumsum(expert_mask, axis=0) - 1.0) * expert_mask  # [N,E]
    keep = (position < capacity).astype(probs.dtype) * expert_mask
    pos_onehot = jax.nn.one_hot(position.sum(-1).astype(jnp.int32), capacity,
                                dtype=probs.dtype)  # [N,C]
    dispatch = keep[:, :, None] * pos_onehot[:, None, :]  # [N,E,C]
    gate = (probs * expert_mask).sum(-1)  # [N]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine, aux_loss


def _moe_local(x, router_w, w_in, w_out, *, axis_name: str,
               capacity_factor: float):
    """Inside shard_map over ep. x: [N_local, D] local tokens; router_w:
    [D, E_total]; w_in/w_out: this shard's experts [E_local, D, F] /
    [E_local, F, D]."""
    from ray_tpu.parallel.mesh import axis_size

    ep = axis_size(axis_name)
    n_local, d = x.shape
    e_local = w_in.shape[0]
    e_total = e_local * ep
    capacity = max(1, int(capacity_factor * n_local / e_total))

    logits = x @ router_w  # [N_local, E_total]
    dispatch, combine, aux = top1_routing(logits, capacity)

    # [N,E,C] x [N,D] -> [E_total, C, D] -> group by owner shard
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)
    expert_in = expert_in.reshape(ep, e_local, capacity, d)
    # all_to_all: shard i sends block j to shard j; receives [ep, e_local,C,D]
    expert_in = jax.lax.all_to_all(expert_in, axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
    # -> [ep(sources), e_local, C, D]; fold sources into capacity
    expert_in = expert_in.transpose(1, 0, 2, 3).reshape(
        e_local, ep * capacity, d)

    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, w_in))
    y = jnp.einsum("ecf,efd->ecd", h, w_out)  # [e_local, ep*C, D]

    y = y.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
    y = jax.lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                           tiled=False)  # back: [ep, e_local, C, D]
    y = y.reshape(e_total, capacity, d)
    out = jnp.einsum("nec,ecd->nd", combine, y)
    return out.astype(x.dtype), aux[None]


def moe_apply(x, router_w, w_in, w_out, *, mesh: Mesh,
              capacity_factor: float = 1.25, axis_name: str = "ep",
              token_axis: str = "dp"):
    """Driver-level entry. x: [N, D] tokens (sharded over dp); w_in/w_out:
    [E, D, F] / [E, F, D] sharded over ep on the expert axis."""
    from ray_tpu.parallel.mesh import shard_map

    fn = shard_map(
        functools.partial(_moe_local, axis_name=axis_name,
                          capacity_factor=capacity_factor),
        mesh=mesh,
        in_specs=(P(token_axis, None), P(), P(axis_name), P(axis_name)),
        out_specs=(P(token_axis, None), P(token_axis)),
    )
    out, aux = fn(x, router_w, w_in, w_out)
    return out, jnp.mean(aux)
