"""Logical-axis sharding rules (the flax.linen.spmd idea, self-contained).

Model code annotates parameters with *logical* axis names ("embed",
"heads", "mlp", "vocab", ...); a rule table maps logical names to mesh
axes. Changing the parallelism layout = changing the table, not the model.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rule table for transformer-family models.
DEFAULT_RULES: dict[str, str | None] = {
    "batch": "dp",
    "seq": "sp",
    "embed": None,          # replicated across tp (activations gather)
    "heads": "tp",
    "kv": None,
    "mlp": "tp",
    "vocab": "tp",
    "expert": "ep",
    "stage": "pp",          # stacked pipeline-stage leading axis
    "norm": None,
}


def spec_for(logical_axes: tuple[str | None, ...],
             rules: dict[str, str | None] | None = None) -> P:
    rules = {**DEFAULT_RULES, **(rules or {})}
    mesh_axes = []
    for name in logical_axes:
        if name is None:
            mesh_axes.append(None)
        else:
            mesh_axes.append(rules.get(name))
    return P(*mesh_axes)


class WithLogicalAxes:
    """Wrapper marking an initializer's output with logical axes; used by
    models to attach metadata without depending on flax internals."""

    def __init__(self, init_fn, logical_axes: tuple[str | None, ...]):
        self.init_fn = init_fn
        self.logical_axes = logical_axes

    def __call__(self, *args, **kwargs):
        return self.init_fn(*args, **kwargs)


def tree_shardings(mesh: Mesh, logical_tree: Any,
                   rules: dict[str, str | None] | None = None):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def shard_bounds(dim: int, rank: int, num_shards: int) -> tuple[int, int]:
    """Contiguous [lo, hi) range of a dimension owned by `rank` when the
    dimension is split over `num_shards` Megatron-style. Uneven splits
    spread the remainder over the FIRST shards (every rank still gets a
    non-degenerate slice as long as dim >= num_shards)."""
    if not 0 <= rank < num_shards:
        raise ValueError(f"rank {rank} outside [0, {num_shards})")
    if dim < num_shards:
        raise ValueError(
            f"cannot split dimension {dim} over {num_shards} shards")
    base, rem = divmod(dim, num_shards)
    lo = rank * base + min(rank, rem)
    hi = lo + base + (1 if rank < rem else 0)
    return lo, hi


def column_shard(w, rank: int, num_shards: int):
    """This rank's slice of a COLUMN-parallel weight (SNIPPETS [3]
    ColumnParallelLinear: output features sharded, logical axes
    ("embed", "mlp") -> P(None, "model")): w[..., lo:hi] of the LAST
    axis. The activation after x @ w_col is already shard-local, so no
    communication follows it."""
    lo, hi = shard_bounds(w.shape[-1], rank, num_shards)
    return w[..., lo:hi]


def kv_slice(width: int, rank: int, num_shards: int) -> tuple[int, int]:
    """This rank's [lo, hi) slice of a KV vector's inner dimension —
    the per-shard KV PAGE slice of the streaming tier's paged cache
    (serve/kv_cache.py): each gang rank caches only the columns its
    column-sharded up-projection produces, so cache reads/writes are
    shard-local and only the per-step logits allreduce crosses ranks.
    Identical arithmetic to column_shard's last-axis bounds, named so
    cache sizing and weight slicing can't drift apart."""
    return shard_bounds(width, rank, num_shards)


def row_shard(w, rank: int, num_shards: int):
    """This rank's slice of a ROW-parallel weight (SNIPPETS [3]
    RowParallelLinear: input features sharded, logical axes
    ("mlp", "embed") -> P("model", None)): w[lo:hi] of the FIRST axis.
    The per-shard output is a PARTIAL sum — callers allreduce(SUM) it
    across the shard group to recover the full matmul."""
    lo, hi = shard_bounds(w.shape[0], rank, num_shards)
    return w[lo:hi]


def infer_param_logical_axes(params: Any) -> Any:
    """Heuristic logical axes for unannotated param trees: last axis of a
    kernel is its output features. Used when a model doesn't carry
    annotations — everything replicated except obvious tensor-parallel
    candidates is a safe default."""

    def leaf_axes(path, leaf):
        name = "/".join(str(p.key) if hasattr(p, "key") else str(p)
                        for p in path).lower()
        rank = getattr(leaf, "ndim", 0)
        if rank == 0:
            return ()
        if "embedding" in name and rank == 2:
            return ("vocab", "embed")
        return tuple([None] * rank)

    return jax.tree_util.tree_map_with_path(leaf_axes, params)
