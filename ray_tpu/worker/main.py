"""Worker process entrypoint (reference:
python/ray/workers/default_worker.py): connect to the local raylet, register
into its pool, and run the task execution loop."""

from __future__ import annotations

import argparse
import logging
import os


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-address", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--store-root", required=True)
    parser.add_argument("--log-file", default=None)
    args = parser.parse_args()

    from ray_tpu._private import failpoints
    from ray_tpu._private.config import Config, get_config, set_config
    from ray_tpu._private.core_worker import WORKER, CoreWorker
    from ray_tpu._private.log_utils import setup_process_logging

    setup_process_logging("worker", args.log_file)
    failpoints.set_role("worker")
    set_config(Config.load())

    # Workers default to CPU JAX so they never fight the driver for the TPU;
    # tasks that declare TPU resources run in a worker the raylet started
    # with TPU visibility (round-1: inherit node env when RAY_TPU_WORKER_TPU
    # is set).
    if not os.environ.get("RAY_TPU_WORKER_TPU"):
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    cw = CoreWorker(
        mode=WORKER,
        raylet_address=args.raylet_address,
        gcs_address=args.gcs_address,
        session_dir=args.session_dir,
        store_root=args.store_root,
        config=get_config(),
    )
    # print()/stderr from task code streams to the driver console
    # (reference: log_monitor.py:48 republishing).
    from ray_tpu._private.log_utils import install_stdout_forwarder

    install_stdout_forwarder(cw)
    logging.getLogger("ray_tpu.worker").info(
        "worker %s registered with raylet %s",
        cw.worker_id.hex()[:8], args.raylet_address)
    cw.run_task_execution_loop()


if __name__ == "__main__":
    main()
