"""Core microbenchmark suite (reference: python/ray/ray_perf.py, invoked
as `ray microbenchmark`; harness: _private/ray_microbenchmark_helpers.py).
Metric names match the reference's release logs
(release/release_logs/1.2.0/microbenchmark.txt) so numbers are directly
comparable with BASELINE.md."""

from __future__ import annotations

import json
import time

import numpy as np

import ray_tpu


def timeit(name: str, fn, multiplier: int = 1, seconds: float = 2.0,
           results: list | None = None, trials: int = 3):
    """reference: ray_microbenchmark_helpers.py:timeit — N>=3 repetitions,
    MEDIAN reported (this box is 1 time-shared core: a single scheduler
    hiccup skews a mean; the median survives one bad window). Cases whose
    trial spread exceeds 50% of the median are flagged high_variance —
    read those numbers as window noise, not signal."""
    # warmup
    fn()
    trials = max(3, trials)
    rates = []
    for _ in range(trials):
        start = time.perf_counter()
        count = 0
        while time.perf_counter() - start < seconds / trials:
            fn()
            count += 1
        dt = time.perf_counter() - start
        rates.append(count * multiplier / dt)
    med = float(np.median(rates))
    sd = float(np.std(rates))
    flagged = bool(med > 0 and sd > 0.5 * med)
    print(f"{name} per second {med:.2f} +- {sd:.2f} "
          f"(median of {trials})"
          + ("  [HIGH VARIANCE: sd > 50% of median]" if flagged else ""))
    if results is not None:
        row = {"name": name, "per_second": med, "sd": sd,
               "trials": [round(r, 2) for r in rates]}
        if flagged:
            row["high_variance"] = True
        results.append(row)
    return med


def timeit_ab(name: str, arms: dict, multiplier: int = 1,
              seconds_per_window: float = 0.7, windows: int = 3,
              results: list | None = None):
    """Paired interleaved A/B: every arm runs once inside EACH window
    (so a box-load swing hits all arms equally), median of N windows per
    arm. `arms` maps suffix -> (setup, fn): setup() flips the process
    into that arm (e.g. the legacy task path) before its slice runs."""
    rates: dict[str, list] = {suffix: [] for suffix in arms}
    for suffix, (setup, fn) in arms.items():
        setup()
        fn()  # warm this arm
    for _ in range(windows):
        for suffix, (setup, fn) in arms.items():
            setup()
            start = time.perf_counter()
            count = 0
            while time.perf_counter() - start < seconds_per_window:
                fn()
                count += 1
            rates[suffix].append(
                count * multiplier / (time.perf_counter() - start))
    # leave the process in the FIRST (default) arm
    next(iter(arms.values()))[0]()
    out = {}
    for suffix, rr in rates.items():
        med = float(np.median(rr))
        sd = float(np.std(rr))
        full = name if not suffix else f"{name} ({suffix})"
        flagged = bool(med > 0 and sd > 0.5 * med)
        print(f"{full} per second {med:.2f} +- {sd:.2f} "
              f"(median of {windows} interleaved windows)"
              + ("  [HIGH VARIANCE]" if flagged else ""))
        if results is not None:
            row = {"name": full, "per_second": med, "sd": sd,
                   "trials": [round(r, 2) for r in rr]}
            if flagged:
                row["high_variance"] = True
            results.append(row)
        out[suffix] = med
    return out


def calibrate(results: list) -> None:
    """Same-process calibration controls captured with EVERY run
    (VERDICT next-round #5): a pure-python loop rate (interpreter speed
    under the current box load) and a raw-socket echo rate (syscall +
    scheduler round-trip, zero framework). Cross-session comparisons of
    the framework metrics should be read against these — if calibration
    moved 3x between windows, so did everything else."""
    def py_loop():
        n = 0
        for _ in range(10_000):
            n += 1
        return n

    timeit("calibration python loop iters", py_loop, multiplier=10_000,
           seconds=1.0, results=results)

    import socket
    import threading

    a, b = socket.socketpair()
    done = threading.Event()

    def echo():
        while not done.is_set():
            try:
                d = b.recv(64)
                if not d:
                    return
                b.sendall(d)
            except OSError:
                return

    t = threading.Thread(target=echo, daemon=True)
    t.start()

    def roundtrip():
        a.sendall(b"x")
        a.recv(64)

    timeit("calibration raw-socket echo roundtrips", roundtrip,
           seconds=1.0, results=results)
    done.set()
    a.close()
    b.close()


def main(seconds_per_case: float = 2.0) -> list[dict]:
    results: list[dict] = []
    calibrate(results)
    ray_tpu.init()

    arr = np.zeros(100, dtype=np.int64)            # small: inline path
    big = np.zeros(10 * 1024 * 1024, dtype=np.uint8)  # 10MB: plasma path

    def put_small():
        ray_tpu.put(arr)

    timeit("single client put calls", put_small, results=results)

    def get_small():
        ref = ray_tpu.put(arr)
        ray_tpu.get(ref)

    timeit("single client get calls", get_small, results=results)

    def put_large():
        ray_tpu.get(ray_tpu.put(big))

    n = timeit("single client put+get large (10MB)", put_large,
               results=results)
    gb_s = n * big.nbytes / 1e9
    print(f"single client put gigabytes per second {gb_s:.2f}")
    results.append({"name": "single client put gigabytes",
                    "per_second": gb_s, "sd": 0.0})

    from ray_tpu._private import global_state

    def _arm(legacy: bool):
        """Flip the driver between the optimized task path and the
        preserved round-7 control (RAY_TPU_TASK_LEGACY semantics) —
        spec caching, batched/soft lease prewarm, shared lease reaper
        vs per-call rebuilds, one-at-a-time hard leases, per-push grace
        timers. Worker-side changes (coalesced reply delivery, gated
        profile flush) are active in BOTH arms; see PERF.md round 8."""

        def setup():
            cw = global_state.get_core_worker()
            if cw is not None:
                cw._legacy = legacy
                # each arm builds its own leases: a lease granted to the
                # other arm differs structurally (no direct task channel
                # on legacy leases) and must not leak across windows
                cw._io.run(cw._return_all_leases(), timeout=30)

        return setup

    AB = lambda fn: {"": (_arm(False), fn),  # noqa: E731
                     "legacy-path control": (_arm(True), fn)}

    @ray_tpu.remote
    def small_task():
        return b"ok"

    def task_sync():
        ray_tpu.get(small_task.remote())

    timeit_ab("single client tasks sync", AB(task_sync), results=results)

    def tasks_async():
        ray_tpu.get([small_task.remote() for _ in range(100)])

    timeit_ab("single client tasks async", AB(tasks_async),
              multiplier=100, results=results)

    @ray_tpu.remote
    class TaskClient:
        """Client actor driving its own task fan-out (BASELINE.md 'multi
        client' rows use independent client processes)."""

        def batch(self, fn, n):
            import ray_tpu as rt

            rt.get([fn.remote() for _ in range(n)])
            return n

    clients = [TaskClient.remote() for _ in range(2)]

    def multi_client_tasks():
        ray_tpu.get([c.batch.remote(small_task, 50) for c in clients])

    timeit("multi client tasks async", multi_client_tasks, multiplier=100,
           results=results)

    @ray_tpu.remote
    class Actor:
        def small_value(self):
            return b"ok"

    a = Actor.remote()

    def actor_sync():
        ray_tpu.get(a.small_value.remote())

    timeit_ab("1:1 actor calls sync", AB(actor_sync), results=results)

    def actor_async():
        ray_tpu.get([a.small_value.remote() for _ in range(100)])

    timeit("1:1 actor calls async", actor_async, multiplier=100,
           results=results)

    @ray_tpu.remote
    class AsyncActor:
        async def small_value(self):
            return b"ok"

    aa = AsyncActor.remote()
    ray_tpu.get(aa.small_value.remote())  # warm the async loop

    def async_actor_async():
        ray_tpu.get([aa.small_value.remote() for _ in range(100)])

    timeit("1:1 async-actor calls async", async_actor_async,
           multiplier=100, results=results)

    n_actors = 4
    actors = [Actor.remote() for _ in range(n_actors)]

    def actors_1n_async():
        refs = []
        for actor in actors:
            refs.extend(actor.small_value.remote() for _ in range(25))
        ray_tpu.get(refs)

    # NOTE: this single-driver fan-out carried the label "n:n actor
    # calls async" through round 7; it is 1:n-shaped (one client, n
    # server actors) and is now labeled to match BASELINE.md column
    # definitions. The true n:n row below drives the same targets from
    # n concurrent CLIENT actors.
    timeit("1:n actor calls async", actors_1n_async, multiplier=100,
           results=results)

    @ray_tpu.remote
    class CallerClient:
        def __init__(self, targets):
            self.targets = targets

        def fan(self, calls_per_target):
            import ray_tpu as rt

            refs = []
            for t in self.targets:
                refs.extend(t.small_value.remote()
                            for _ in range(calls_per_target))
            rt.get(refs)
            return len(refs)

    callers = [CallerClient.remote(actors) for _ in range(2)]

    def actors_nn_async():
        ray_tpu.get([c.fan.remote(13) for c in callers])

    timeit("n:n actor calls async", actors_nn_async,
           multiplier=2 * n_actors * 13, results=results)

    _collective_bench(results)

    _serve_qps(results)

    _tracing_ab(results)

    _profiling_ab(results)

    _state_ab(results)

    _serve_mixed(results)

    _serve_stream(results)

    _serve_prefix(results)

    _cold_gang_ttft(results)

    _train_sharded(results)

    ray_tpu.shutdown()

    _cross_node_bench(results)
    _control_plane(results)
    _placement_topology(results)
    return results


def _cross_node_bench(results: list[dict], windows: int = 5):
    """Cross-node object pull A/B (needs real raylet process boundaries,
    so it runs on its own cluster_utils cluster AFTER the single-node
    suite). Per size, each window times ONE pull per arm — streaming
    bulk-channel pull vs the preserved round-8 stop-and-wait fetch_chunk
    control (set_transfer_mode flips the puller raylet live, so the arms
    interleave inside the same windows) — median of N windows. Also: a
    2-source striped pull, and the control-plane probe: peer_ping RTTs
    over the shared raylet<->raylet CONTROL connection while a 64MB pull
    is in flight (legacy chunks head-of-line-block that conn; streaming
    must leave it idle)."""
    from ray_tpu._private import global_state
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        _cross_node_bench_body(results, windows, cluster)
    finally:
        # a failed assert/timeout must not orphan the gcs/raylet
        # children (orphans poison every later benchmark on this box)
        cw = global_state.get_core_worker()
        if cw is not None:
            cw.shutdown()
        cluster.shutdown()


def _cross_node_bench_body(results: list[dict], windows: int, cluster):
    import asyncio

    src_b = cluster.add_node(num_cpus=1, resources={"srcb": 1})
    src_c = cluster.add_node(num_cpus=1, resources={"srcc": 1})
    cw = cluster.connect_driver()
    head = cw.raylet

    def rcall(method, data, timeout=180.0):
        return cw._io.run(head.call(method, data), timeout=timeout)

    def set_mode(legacy):
        rcall("set_transfer_mode", {"legacy": legacy})

    def pull(oid, free_after=True) -> float:
        t0 = time.perf_counter()
        ok = rcall("wait_object_local", {"object_id": oid, "timeout": 150})
        dt = time.perf_counter() - t0
        assert ok is True, f"pull did not complete: {ok!r}"
        if free_after:
            rcall("free_objects", {"object_ids": [oid]})
        return dt

    @ray_tpu.remote(num_cpus=1, resources={"srcb": 1})
    def produce(nbytes):
        import numpy as _np

        return _np.arange(nbytes, dtype=_np.uint8)

    @ray_tpu.remote(num_cpus=1, resources={"srcc": 1})
    def touch(arr):
        return int(arr.nbytes)

    def wait_locations(oid, n):
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if len(cw._io.run(cw.gcs.call(
                    "get_object_locations", {"object_id": oid}))) >= n:
                return
            time.sleep(0.1)
        raise TimeoutError("object location never registered")

    refs = {}
    for mb in (1, 16, 64):
        refs[mb] = produce.remote(mb * 1024 * 1024)
        wait_locations(refs[mb].id().binary(), 1)

    def record(name, rates, nbytes):
        med = float(np.median(rates))
        sd = float(np.std(rates))
        gb_s = med * nbytes / 1e9
        flagged = bool(med > 0 and sd > 0.5 * med)
        print(f"{name} per second {med:.2f} ({gb_s:.3f} GB/s, median of "
              f"{len(rates)})" + ("  [HIGH VARIANCE]" if flagged else ""))
        row = {"name": name, "per_second": med, "sd": sd,
               "gb_s": round(gb_s, 4),
               "trials": [round(r, 3) for r in rates]}
        if flagged:
            row["high_variance"] = True
        results.append(row)

    for mb in (1, 16, 64):
        oid = refs[mb].id().binary()
        for legacy in (False, True):  # warm both arms' connections
            set_mode(legacy)
            pull(oid)
        rates: dict[bool, list] = {False: [], True: []}
        for _ in range(windows):
            for legacy in (False, True):  # interleaved within the window
                set_mode(legacy)
                rates[legacy].append(1.0 / pull(oid))
        record(f"cross_node_pull {mb}MB", rates[False], mb * 1024 * 1024)
        record(f"cross_node_pull {mb}MB (legacy-path control)",
               rates[True], mb * 1024 * 1024)
    set_mode(None)

    # --- 1src vs 2src striped pull (64MB), PAIRED interleaved: the
    # second source's directory entry is removed for the 1src slice of
    # each window and restored for the 2src slice, so a box-load swing
    # hits both sides equally (the arms' trial spread on this shared
    # 2-core host is wider than the striping delta — unpaired medians
    # are noise).
    nbytes = 64 * 1024 * 1024
    oid = refs[64].id().binary()
    assert ray_tpu.get(touch.remote(refs[64]), timeout=300) > 0
    wait_locations(oid, 2)

    def set_second_source(present: bool):
        method = ("add_object_location" if present
                  else "remove_object_location")
        data = {"object_id": oid, "node_id": src_c.node_id.binary()}
        if present:
            data["size"] = nbytes
        cw._io.run(cw.gcs.call(method, data))

    striped0 = rcall("get_metrics", {}).get(
        "raylet.pulls_striped_total", {}).get("value", 0)
    for present in (False, True):  # warm both shapes
        set_second_source(present)
        pull(oid)
    rates1, rates2 = [], []
    for _ in range(max(windows, 7)):
        set_second_source(False)
        rates1.append(1.0 / pull(oid))
        set_second_source(True)
        rates2.append(1.0 / pull(oid))
    striped = rcall("get_metrics", {}).get(
        "raylet.pulls_striped_total", {}).get("value", 0) - striped0
    record("cross_node_pull 64MB 1src (paired)", rates1, nbytes)
    record("cross_node_pull 64MB 2src", rates2, nbytes)
    results[-1]["striped_pulls"] = striped

    # --- control-plane RTT during a 64MB bulk pull ---
    # peer_ping rides the head raylet's shared control connection to the
    # source — exactly where legacy bulk frames also travel.
    async def ping_during_pull(oid):
        lats = []
        pull_fut = asyncio.ensure_future(head.call(
            "wait_object_local", {"object_id": oid, "timeout": 150}))
        await asyncio.sleep(0.005)  # let the pull get going
        while not pull_fut.done():
            lats.append(await head.call("peer_ping",
                                        {"address": src_b.address}))
        assert (await pull_fut) is True
        await head.call("free_objects", {"object_ids": [oid]})
        return lats

    oid = refs[16].id().binary()  # single-source (B) object
    for legacy, suffix in ((False, ""), (True, " (legacy-path control)")):
        set_mode(legacy)
        lats: list[float] = []
        for _ in range(windows):
            lats.extend(cw._io.run(ping_during_pull(oid), timeout=300))
        name = f"cross_node_pull control ping during 16MB pull{suffix}"
        if not lats:
            # pull outraced every ping this window: no row (NaN would
            # make MICROBENCH.json invalid JSON for strict parsers)
            print(f"{name}: no pings completed during the pull; skipped")
            continue
        p99 = float(np.percentile(lats, 99))
        p50 = float(np.median(lats))
        print(f"{name}: p50 {p50 * 1e3:.2f}ms p99 {p99 * 1e3:.2f}ms "
              f"({len(lats)} pings)")
        results.append({"name": name, "per_second": 1.0 / p99,
                        "sd": 0.0, "p99_ms": round(p99 * 1e3, 3),
                        "p50_ms": round(p50 * 1e3, 3),
                        "samples": len(lats)})
    set_mode(None)


def _collective_bench(results: list[dict], nbytes: int = 16 * 1024 * 1024,
                      world: int = 4, windows: int = 5):
    """Host collective data-plane A/B: one 16MB float32 allreduce across
    4 single-node ranks per window, every transport forced in turn
    inside the SAME window (interleaved — a box-load swing hits all arms
    equally), median of N windows, GB/s/rank. `ring_unpipelined` is the
    preserved pre-pipelining control arm; the small-hub case guards
    control-plane latency against regressions from the routing layer.
    Round-12 arms: `device` (the Transport.DEVICE tier over the shared
    jax runtime — device-resident payload, timed to block_until_ready)
    and `ring_quantized` (int8 block-scaled wire format on the pipelined
    ring; same payload, ~4x fewer socket bytes)."""
    from ray_tpu.collective import collective as col

    @ray_tpu.remote(num_cpus=0)
    class BenchRank(col.CollectiveActorMixin):
        def join_runtime(self, world, rank):
            # BEFORE first jax backend use: makes the group
            # device-capable so the 'device' arm is forcible
            from ray_tpu.parallel import multihost

            multihost.initialize("bench_mh", world, rank)
            return True

        def timed_allreduce(self, transport, n_elems):
            import time as _t

            import numpy as _np

            from ray_tpu.collective import collective as C

            group = C._manager.get_group("bench_col")
            quantize = None
            if transport == "ring_quantized":
                transport, quantize = "ring", "int8"
            group.barrier()  # hub-direct: lines ranks up, never routed
            group.force_transport = transport
            if transport in ("device", "pallas"):
                import jax
                import jax.numpy as jnp

                arr = jnp.ones(n_elems, jnp.float32)
                jax.block_until_ready(arr)
                t0 = _t.perf_counter()
                out = group.allreduce(arr)
                jax.block_until_ready(out)
                return _t.perf_counter() - t0
            arr = _np.ones(n_elems, _np.float32)
            t0 = _t.perf_counter()
            group.allreduce(arr, quantize=quantize)
            return _t.perf_counter() - t0

        def read_counter(self, name):
            from ray_tpu._private import stats

            snap = stats.snapshot().get(name)
            return float(snap["value"]) if snap else 0.0

        def teardown(self):
            from ray_tpu.collective import collective as C

            C.destroy_collective_group("bench_col")  # rank 0 unlinks
            return True                              # the shm segment

    ranks = [BenchRank.remote() for _ in range(world)]
    ray_tpu.get([r.join_runtime.remote(world, i)
                 for i, r in enumerate(ranks)], timeout=300)
    col.create_collective_group(ranks, world, list(range(world)),
                                backend="host", group_name="bench_col")
    cases = ["shm", "ring", "ring_quantized", "ring_unpipelined", "hub",
             "device"]
    for tr in cases:  # warm at FULL size: segment sized+faulted in, ring
        ray_tpu.get(   # built, hub buffers grown, device bodies jitted —
            [r.timed_allreduce.remote(tr, nbytes // 4) for r in ranks],
            timeout=300)  # no setup in the windows
    # small-message fused-kernel arm (round 15): decode-step-sized
    # payloads — the latency class the PALLAS tier exists for — pallas
    # vs the device (shard_map dispatch stack) control, interleaved in
    # the same windows. 4096 f32 = 16KB, under pallas_max_bytes.
    SMALL_ELEMS = 4096
    small_cases = ["pallas", "device"]
    for tr in small_cases:  # warm: kernels traced, vote round paid once
        ray_tpu.get([r.timed_allreduce.remote(tr, SMALL_ELEMS)
                     for r in ranks], timeout=300)
    samples: dict[str, list[float]] = {tr: [] for tr in cases}
    small: list[float] = []
    small_samples: dict[str, list[float]] = {tr: [] for tr in small_cases}
    for _ in range(windows):
        for tr in cases:
            ts = ray_tpu.get(
                [r.timed_allreduce.remote(tr, nbytes // 4) for r in ranks],
                timeout=300)
            samples[tr].append(max(ts))  # slowest rank bounds the op
        for tr in small_cases:
            ts = ray_tpu.get(
                [r.timed_allreduce.remote(tr, SMALL_ELEMS) for r in ranks],
                timeout=120)
            small_samples[tr].append(max(ts))
        ts = ray_tpu.get(
            [r.timed_allreduce.remote("hub", 256) for r in ranks],
            timeout=120)
        small.append(max(ts))
    for tr in cases:
        med = float(np.median(samples[tr]))
        gbps = nbytes / med / 1e9
        print(f"collective_allreduce_{tr} 16MB/4-rank GB/s/rank "
              f"{gbps:.3f} (median of {windows})")
        results.append({
            "name": f"collective_allreduce_{tr}", "per_second": 1.0 / med,
            "gb_s_per_rank": round(gbps, 4),
            "sd": float(np.std(samples[tr])),
            "trials": [round(t, 4) for t in samples[tr]]})
    med = float(np.median(small))
    print(f"collective_allreduce_hub_small (1KB) per second {1 / med:.1f}")
    results.append({"name": "collective_allreduce_hub_small",
                    "per_second": 1.0 / med, "sd": float(np.std(small)),
                    "trials": [round(t, 5) for t in small]})
    # counter-verify the fused-kernel arm actually ran on the PALLAS
    # tier (ops counted per rank: warm + one per window)
    pallas_ops = ray_tpu.get([r.read_counter.remote(
        "collective.pallas_ops_total") for r in ranks], timeout=60)
    for tr in small_cases:
        med = float(np.median(small_samples[tr]))
        row = {"name": f"collective_allreduce_{tr}_small",
               "per_second": 1.0 / med,
               "payload_bytes": SMALL_ELEMS * 4,
               "sd": float(np.std(small_samples[tr])),
               "trials": [round(t, 5) for t in small_samples[tr]]}
        if tr == "pallas":
            row["pallas_ops_per_rank"] = float(np.mean(pallas_ops))
        results.append(row)
        print(f"collective_allreduce_{tr}_small (16KB decode-step) "
              f"per second {1 / med:.1f} (median of {windows})")
    # counter-verify the quantized wire reduction: saved bytes per op
    # per rank vs the exact f32 wire the same schedule would have sent
    saved = ray_tpu.get([r.read_counter.remote(
        "collective.quantized_bytes_saved_total") for r in ranks],
        timeout=60)
    q_ops = windows + 1  # warm + one per window
    chunk = (nbytes // 4) // world
    exact_wire = 2 * (world - 1) * chunk * 4
    saved_per_op = float(np.mean(saved)) / q_ops
    reduction = exact_wire / max(exact_wire - saved_per_op, 1.0)
    for row in results:
        if row["name"] == "collective_allreduce_ring_quantized":
            row["wire_bytes_exact"] = exact_wire
            row["wire_bytes_saved_per_op"] = int(saved_per_op)
            row["wire_reduction_x"] = round(reduction, 2)
    print(f"collective_allreduce_ring_quantized wire reduction "
          f"{reduction:.2f}x (counter-verified, saved "
          f"{saved_per_op / 1e6:.1f}MB/op/rank of {exact_wire / 1e6:.1f}MB)")
    ray_tpu.get([r.teardown.remote() for r in ranks], timeout=60)
    for r in ranks:
        ray_tpu.kill(r)


def _http_qps_window(pool, tls, port: int, route: str,
                     seconds: float = 0.7) -> float:
    """Keep-alive HTTP throughput over one timed window: 16 pooled
    client threads, one persistent conn per (thread, port) — urllib
    reconnects per request, which would measure TCP handshakes, not the
    proxy. Shared by the legacy-proxy and tracing A/Bs so both rows
    measure through the identical harness."""
    import http.client

    stop = time.perf_counter() + seconds

    def worker(_):
        conns = getattr(tls, "conns", None)
        if conns is None:
            conns = tls.conns = {}
        n = 0
        while time.perf_counter() < stop:
            conn = conns.get(port)
            if conn is None:
                conn = conns[port] = http.client.HTTPConnection(
                    "127.0.0.1", port)
            try:
                conn.request("GET", route)
                conn.getresponse().read()
            except (http.client.HTTPException, OSError):
                conns.pop(port, None)
                raise
            n += 1
        return n

    t0 = time.perf_counter()
    counts = list(pool.map(worker, range(16)))
    return sum(counts) / (time.perf_counter() - t0)


def _rate_rows(results: list[dict], rows, windows: int):
    """Median/sd/high-variance row emission for the hand-rolled
    interleaved A/Bs (timeit_ab covers the closed-loop cases)."""
    for name, rates in rows:
        med = float(np.median(rates))
        sd = float(np.std(rates))
        flagged = bool(med > 0 and sd > 0.5 * med)
        print(f"{name} per second {med:.2f} +- {sd:.2f} "
              f"(median of {windows} interleaved windows)"
              + ("  [HIGH VARIANCE]" if flagged else ""))
        row = {"name": name, "per_second": med, "sd": sd,
               "trials": [round(r, 2) for r in rates]}
        if flagged:
            row["high_variance"] = True
        results.append(row)


def _serve_qps(results: list[dict]):
    """Serve noop throughput (reference: serve release bench, ~3-4k qps
    noop via HTTP). Measured through the handle (router batching path),
    through a router-only asyncio control (no HTTP), and through the
    HTTP proxy as a PAIRED interleaved A/B: the optimized request path
    (call_async + coalesced wakeups) against a legacy-path control proxy
    (assign_async + wrap_future per ref) serving the same backend in the
    same process window — so a box-load swing hits both sides equally."""
    import asyncio

    from ray_tpu import serve

    client = serve.start(http=True)
    client.create_backend("noop", lambda _=None: "ok", config={
        "num_replicas": 2, "max_batch_size": 32,
        "batch_wait_timeout": 0.001, "max_concurrent_queries": 8})
    client.create_endpoint("noop", backend="noop", route="/noop")
    handle = client.get_handle("noop")
    ray_tpu.get(handle.remote(None))  # warm the path

    # qps is a CONCURRENT-load metric (the reference measures with wrk):
    # router.assign intentionally blocks each caller until its batch is
    # dispatched, so drive it from a client thread pool.
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=16)

    def one_handle_call(_):
        return ray_tpu.get(handle.remote(None), timeout=30)

    def handle_call():
        list(pool.map(one_handle_call, range(64)))

    timeit("serve handle noop calls", handle_call, multiplier=64,
           results=results)

    # Router-only control (round-5 definition): assign_async + await ref
    # at concurrency 16, no HTTP anywhere. Bounds what any proxy in this
    # process could deliver.
    router = handle._router

    def router_window(seconds: float = 0.7) -> float:
        async def drive():
            stop = time.perf_counter() + seconds

            async def worker():
                n = 0
                while time.perf_counter() < stop:
                    ref = await router.assign_async(None)
                    await ref
                    n += 1
                return n

            t0 = time.perf_counter()
            counts = await asyncio.gather(*[worker() for _ in range(16)])
            return sum(counts) / (time.perf_counter() - t0)

        return asyncio.run(drive())

    router_rates = [router_window() for _ in range(3)]
    med = float(np.median(router_rates))
    print(f"serve router-only control per second {med:.2f} "
          f"+- {float(np.std(router_rates)):.2f} (median of 3)")
    results.append({"name": "serve router-only control",
                    "per_second": med,
                    "sd": float(np.std(router_rates)),
                    "trials": [round(r, 2) for r in router_rates]})

    # Legacy-path control proxy: same controller, same backend, own
    # port. Coexists with the optimized proxy so the A/B interleaves
    # within one window.
    from ray_tpu.serve.http_proxy import HTTPProxy

    legacy = ray_tpu.remote(HTTPProxy).remote(
        client._controller, "127.0.0.1", 0, False, True)
    legacy_port = ray_tpu.get(legacy.port.remote(), timeout=60)

    import threading as _threading

    tls = _threading.local()

    def http_window(port: int, seconds: float = 0.7) -> float:
        return _http_qps_window(pool, tls, port, "/noop", seconds)

    http_window(client.http_port, 0.2)  # warm both proxies' conns
    http_window(legacy_port, 0.2)
    opt_rates, leg_rates = [], []
    for _ in range(5):  # interleaved: load swings hit both sides
        opt_rates.append(http_window(client.http_port))
        leg_rates.append(http_window(legacy_port))
    _rate_rows(results, [("serve http noop qps", opt_rates),
                         ("serve http noop qps (legacy-path control)",
                          leg_rates)], windows=5)
    ray_tpu.kill(legacy)
    pool.shutdown()
    serve.shutdown()


def _serve_mixed(results: list[dict], window_s: float = 1.5,
                 windows: int = 3):
    """Mixed-traffic serve bench (ROADMAP item 1 acceptance): sustained
    small-JSON + large (8MB octet-stream) bodies through the HTTP proxy
    at 1x and 2x admission capacity, paired-interleaved windows. Large
    bodies ride the zero-copy plane (plasma + bulk channel past the 1MB
    threshold). Records per arm: qps (2xx only), client-side p99 of
    SUCCESSFUL requests (what admitted traffic experiences), and the
    shed rate (503 fraction). The tier-1 gate
    (tests/test_serve_sharded.py::test_microbench_serve_mixed_gate)
    asserts the recorded 2x row kept p99 bounded WITH nonzero typed
    sheds — overload must degrade via 503s, not latency collapse.

    Capacity arithmetic: 2 replicas x max_concurrent_queries=2 in
    service + max_queued_requests=4 queue ~= 8 outstanding. 1x drives 7
    closed-loop clients (6 small + 1 large, no sheds expected); 2x
    drives 14 (12 small + 2 large, the excess MUST shed)."""
    import http.client
    import threading as _threading

    import numpy as _np

    from ray_tpu import serve

    client = serve.start(http=True)
    client.create_backend(
        "mixed", lambda d=None: (len(d) if isinstance(d, (bytes,
                                                          bytearray))
                                 else "ok"),
        config={"num_replicas": 2, "max_concurrent_queries": 2,
                "max_batch_size": 4, "batch_wait_timeout": 0.001,
                "max_queued_requests": 4,
                "large_payload_threshold": 1 << 20})
    client.create_endpoint("mixed", backend="mixed", route="/mixed",
                           methods=["GET", "POST"])
    port = client.http_port
    big = _np.zeros(8 << 20, dtype=_np.uint8).tobytes()  # 8MB
    tls = _threading.local()

    def one_request(body):
        conns = getattr(tls, "conns", None)
        if conns is None:
            conns = tls.conns = {}
        conn = conns.get(port)
        if conn is None:
            conn = conns[port] = http.client.HTTPConnection(
                "127.0.0.1", port)
        t0 = time.perf_counter()
        try:
            if body is None:
                conn.request("GET", "/mixed")
            else:
                conn.request("POST", "/mixed", body=body, headers={
                    "Content-Type": "application/octet-stream"})
            resp = conn.getresponse()
            resp.read()
            status = resp.status
        except (http.client.HTTPException, OSError):
            conns.pop(port, None)
            raise
        return status, time.perf_counter() - t0

    def drive(n_small: int, n_large: int, seconds: float):
        """One closed-loop window; returns (ok_lat, shed, errors, dt)."""
        stop = time.perf_counter() + seconds
        lock = _threading.Lock()
        ok_lat: list[float] = []
        counts = {"shed": 0, "other": 0}

        def worker(body):
            while time.perf_counter() < stop:
                try:
                    status, dt = one_request(body)
                except (http.client.HTTPException, OSError):
                    # dropped keep-alive conn: reconnect next loop —
                    # a dead worker thread would silently shrink the
                    # offered load mid-window
                    with lock:
                        counts["other"] += 1
                    continue
                with lock:
                    if status == 200:
                        ok_lat.append(dt)
                    elif status == 503:
                        counts["shed"] += 1
                    else:
                        counts["other"] += 1

        threads = ([_threading.Thread(target=worker, args=(None,))
                    for _ in range(n_small)]
                   + [_threading.Thread(target=worker, args=(big,))
                      for _ in range(n_large)])
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return ok_lat, counts["shed"], counts["other"], \
            time.perf_counter() - t0

    # warm the route + the zero-copy path (sleep on EVERY miss — a 404
    # while the route table syncs returns without raising and must not
    # hot-spin; a transient conn drop on the first 8MB body must not
    # abort the whole suite)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if one_request(None)[0] == 200:
                break
        except Exception:
            pass
        time.sleep(0.2)
    for _ in range(10):
        try:
            one_request(big)
            break
        except Exception:
            time.sleep(0.5)

    arms = {"serve_mixed 1x": (6, 1), "serve_mixed 2x overload": (12, 2)}
    acc = {name: {"lat": [], "shed": 0, "ok": 0, "other": 0, "dt": 0.0}
           for name in arms}
    for _ in range(windows):  # paired: load swings hit both arms
        for name, (ns, nl) in arms.items():
            lat, shed, other, dt = drive(ns, nl, window_s)
            a = acc[name]
            a["lat"].extend(lat)
            a["shed"] += shed
            a["ok"] += len(lat)
            a["other"] += other
            a["dt"] += dt
    for name, a in acc.items():
        total = a["ok"] + a["shed"] + a["other"]
        qps = a["ok"] / a["dt"] if a["dt"] else 0.0
        p99_ms = (float(_np.percentile(a["lat"], 99)) * 1000.0
                  if a["lat"] else 0.0)
        shed_rate = a["shed"] / total if total else 0.0
        row = {"name": name, "per_second": round(qps, 2),
               "p99_ms": round(p99_ms, 1),
               "shed_rate": round(shed_rate, 4),
               "ok": a["ok"], "shed": a["shed"], "other": a["other"],
               "windows": windows, "window_s": window_s}
        results.append(row)
        print(f"{name}: {qps:.1f} qps ok, p99 {p99_ms:.0f}ms, "
              f"shed rate {shed_rate:.1%} ({a['shed']}/{total})")
    serve.shutdown()


def _serve_stream(results: list[dict], windows: int = 3,
                  gen_tokens: int = 96):
    """Streaming inference bench (ROADMAP item 1 acceptance): tokens/s
    per replica and time-to-first-token through the HTTP proxy at 2x
    admission capacity, paired-interleaved against the PRESERVED
    request-level path (same integer-weight ShardedTokenLM, deployed
    once with streaming=True/SSE and once as a plain request/response
    backend whose whole generation blocks its slot).

    Capacity arithmetic: the continuous arm runs one engine with
    max_decode_batch=4 running sequences; 2x = 8 closed-loop SSE
    clients (the excess waits in the bounded admission queue and is
    admitted into the RUNNING batch between steps). The request-level
    arm serves the same 8 clients with max_batch_size=4 batches — a
    whole batch's generations complete before the next dispatch.

    Recorded per arm: tokens/s/replica (2xx tokens only), client-side
    TTFT p50/p99 (first SSE data frame; for request-level the full
    JSON IS the first byte, so TTFT == total latency — the coupling the
    tier decouples), and full-generation p99. The tier-1 gate
    (tests/test_serve_streaming.py::test_microbench_serve_stream_gate)
    asserts the recorded continuous row kept TTFT p99 under 25% of the
    full-generation p99 at 2x overload with tokens/s >= the
    request-level arm."""
    import http.client
    import threading as _threading

    import numpy as _np

    from ray_tpu import serve
    from ray_tpu.serve.engine import ShardedTokenLM
    from ray_tpu.serve.streaming import iter_sse_lines

    model = ShardedTokenLM.make(11, vocab=2048, hidden=64, inner=256)
    margs = (model.embed.copy(), model.w_up.copy(), model.w_out.copy())
    client = serve.start(http=True)
    client.create_backend(
        "bench_stream", ShardedTokenLM, *margs,
        config={"streaming": True, "max_decode_batch": 4,
                "max_waiting_sequences": 64, "kv_pages_total": 4096,
                "num_replicas": 1, "large_payload_threshold": 0})
    client.create_endpoint("bench_stream", backend="bench_stream",
                           route="/bench_stream", methods=["POST"])
    client.create_backend(
        "bench_reqlvl", ShardedTokenLM, *margs,
        config={"num_replicas": 1, "max_batch_size": 4,
                "batch_wait_timeout": 0.002, "max_concurrent_queries": 1,
                "large_payload_threshold": 0})
    client.create_endpoint("bench_reqlvl", backend="bench_reqlvl",
                           route="/bench_reqlvl", methods=["POST"])
    port = client.http_port
    n_clients = 8  # 2x the engine's 4 running slots

    def _req_tokens(i: int) -> int:
        # long-tailed lengths (x0.25, x0.5, x1, x4 of gen_tokens — the
        # LLM-traffic shape iteration-level scheduling exists for):
        # short sequences retire early and hand their running slot to
        # the admission queue mid-flight, while request-level lockstep
        # batches burn pad compute until their LONGEST row finishes
        return int(gen_tokens * (0.25, 0.5, 1.0, 4.0)[i % 4])

    def one_stream(i) -> tuple[float, float, int]:
        """(ttft, total, tokens) for one SSE generation."""
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        body = json.dumps({"prompt": [i % 7 + 1, 3, 5],
                           "max_tokens": _req_tokens(i), "stream": True})
        t0 = time.perf_counter()
        conn.request("POST", "/bench_stream", body=body, headers={
            "Content-Type": "application/json",
            "Accept": "text/event-stream"})
        resp = conn.getresponse()
        ttft, n = None, 0
        for ev, data in iter_sse_lines(resp.fp):
            if ev == "error":
                break
            if ttft is None and data.get("tokens"):
                ttft = time.perf_counter() - t0
            n += len(data.get("tokens") or [])
            if ev == "done" or data.get("done"):
                break
        total = time.perf_counter() - t0
        conn.close()
        return ttft if ttft is not None else total, total, n

    def one_reqlvl(i) -> tuple[float, float, int]:
        """(ttft, total, tokens) for one request-level generation —
        the full JSON is the first byte the client sees."""
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        body = json.dumps({"prompt": [i % 7 + 1, 3, 5],
                           "max_tokens": _req_tokens(i)})
        t0 = time.perf_counter()
        conn.request("POST", "/bench_reqlvl", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        doc = resp.read()
        total = time.perf_counter() - t0
        conn.close()
        if resp.status != 200:
            return total, total, 0
        return total, total, len(json.loads(doc).get("result") or [])

    def drive(fn, reqs_per_client: int = 3):
        ttfts: list[float] = []
        totals: list[float] = []
        counts = {"tokens": 0}
        lock = _threading.Lock()

        def worker(i):
            # staggered starts: closed-loop clients self-desynchronize
            # after a few requests; the stagger keeps window 1's TTFT
            # from measuring a thundering herd instead of steady state
            time.sleep(i * 0.025)
            for _ in range(reqs_per_client):
                try:
                    ttft, total, n = fn(i)
                except (http.client.HTTPException, OSError):
                    continue
                with lock:
                    if n:
                        ttfts.append(ttft)
                        totals.append(total)
                        counts["tokens"] += n

        threads = [_threading.Thread(target=worker, args=(i,))
                   for i in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        return ttfts, totals, counts["tokens"], dt

    # warm both routes (the route table syncs asynchronously) and both
    # engines' first-step paths
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if one_stream(0)[2] and one_reqlvl(0)[2]:
                break
        except Exception:
            pass
        time.sleep(0.3)

    arms = {"serve_stream continuous 2x": one_stream,
            "serve_stream request-level 2x": one_reqlvl}
    acc = {name: {"ttft": [], "total": [], "tokens": 0, "dt": 0.0}
           for name in arms}
    for _ in range(windows):  # paired: load swings hit both arms
        for name, fn in arms.items():
            ttfts, totals, tokens, dt = drive(fn)
            a = acc[name]
            a["ttft"].extend(ttfts)
            a["total"].extend(totals)
            a["tokens"] += tokens
            a["dt"] += dt
    for name, a in acc.items():
        tps = a["tokens"] / a["dt"] if a["dt"] else 0.0
        row = {
            "name": name,
            "tokens_per_s_per_replica": round(tps, 1),
            "ttft_p50_ms": round(float(_np.percentile(a["ttft"], 50))
                                 * 1000, 1) if a["ttft"] else 0.0,
            "ttft_p99_ms": round(float(_np.percentile(a["ttft"], 99))
                                 * 1000, 1) if a["ttft"] else 0.0,
            "gen_p99_ms": round(float(_np.percentile(a["total"], 99))
                                * 1000, 1) if a["total"] else 0.0,
            "generations": len(a["total"]),
            "gen_tokens": gen_tokens,
            "clients": n_clients,
            "windows": windows,
        }
        results.append(row)
        print(f"{name}: {tps:.1f} tok/s/replica, ttft p99 "
              f"{row['ttft_p99_ms']:.0f}ms, gen p99 "
              f"{row['gen_p99_ms']:.0f}ms ({row['generations']} gens)")
    serve.shutdown()


def _serve_prefix(results: list[dict], windows: int = 3,
                  prefix_tokens: int = 2048, gen_tokens: int = 16):
    """Cross-session prefix-sharing bench (ROADMAP item 4 acceptance):
    a multi-tenant workload where every request carries the same long
    page-aligned system prefix (prefix_tokens, a whole-page multiple of
    kv_page_size) plus a short per-session tail, paired-interleaved
    against an identical backend with prefix_sharing=False — the
    per-session baseline that re-prefills the shared prefix for every
    admission.

    Recorded per arm: tokens/s/replica, client-side TTFT p50/p99 (first
    SSE data frame), full-generation p99; the shared arm additionally
    records the replica's prefix counters (hits, tokens saved, hit
    rate, shared pages) read from engine_state AFTER the drive. The
    tier-1 gate (test_serve_streaming.py::
    test_microbench_serve_prefix_gate) asserts a nonzero recorded
    hit-rate and shared-arm TTFT p99 no worse than the baseline."""
    import http.client
    import threading as _threading

    import numpy as _np

    from ray_tpu import serve
    from ray_tpu.serve.engine import ShardedTokenLM
    from ray_tpu.serve.streaming import iter_sse_lines

    # model sized so prefill embed (~10ms for the full prefix) is the
    # dominant TTFT term — the thing prefix sharing actually removes
    model = ShardedTokenLM.make(11, vocab=2048, hidden=256, inner=512)
    margs = (model.embed.copy(), model.w_up.copy(), model.w_out.copy())
    page = 16
    assert prefix_tokens % page == 0
    base_cfg = {"streaming": True, "max_decode_batch": 4,
                "max_waiting_sequences": 64, "kv_page_size": page,
                "kv_pages_total": 2560, "num_replicas": 1,
                "prefix_index_max_nodes": 2 * prefix_tokens // page,
                "large_payload_threshold": 0}
    client = serve.start(http=True)
    client.create_backend("bench_pfx_shared", ShardedTokenLM, *margs,
                          config={**base_cfg, "prefix_sharing": True})
    client.create_endpoint("bench_pfx_shared",
                           backend="bench_pfx_shared",
                           route="/bench_pfx_shared", methods=["POST"])
    client.create_backend("bench_pfx_base", ShardedTokenLM, *margs,
                          config={**base_cfg, "prefix_sharing": False})
    client.create_endpoint("bench_pfx_base", backend="bench_pfx_base",
                           route="/bench_pfx_base", methods=["POST"])
    port = client.http_port
    n_clients = 8
    # the fleet-shared system prompt: page-aligned by construction
    shared_prefix = [(7 * i + 3) % 2048 for i in range(prefix_tokens)]

    def one(route):
        def fn(i) -> tuple[float, float, int]:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=120)
            body = json.dumps({
                "prompt": shared_prefix + [i % 7 + 1, 3],
                "max_tokens": gen_tokens, "stream": True})
            t0 = time.perf_counter()
            conn.request("POST", route, body=body, headers={
                "Content-Type": "application/json",
                "Accept": "text/event-stream"})
            resp = conn.getresponse()
            ttft, n = None, 0
            for ev, data in iter_sse_lines(resp.fp):
                if ev == "error":
                    break
                if ttft is None and data.get("tokens"):
                    ttft = time.perf_counter() - t0
                n += len(data.get("tokens") or [])
                if ev == "done" or data.get("done"):
                    break
            total = time.perf_counter() - t0
            conn.close()
            return ttft if ttft is not None else total, total, n
        return fn

    def drive(fn, reqs_per_client: int = 3):
        ttfts: list[float] = []
        totals: list[float] = []
        counts = {"tokens": 0}
        lock = _threading.Lock()

        def worker(i):
            time.sleep(i * 0.025)  # de-herd window starts
            for _ in range(reqs_per_client):
                try:
                    ttft, total, n = fn(i)
                except (http.client.HTTPException, OSError):
                    continue
                with lock:
                    if n:
                        ttfts.append(ttft)
                        totals.append(total)
                        counts["tokens"] += n

        threads = [_threading.Thread(target=worker, args=(i,))
                   for i in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return ttfts, totals, counts["tokens"], time.perf_counter() - t0

    arms = {"serve_prefix shared": one("/bench_pfx_shared"),
            "serve_prefix per-session baseline": one("/bench_pfx_base")}
    deadline = time.time() + 30
    while time.time() < deadline:  # route-table warmup
        try:
            if all(fn(0)[2] for fn in arms.values()):
                break
        except Exception:
            pass
        time.sleep(0.3)

    acc = {name: {"ttft": [], "total": [], "tokens": 0, "dt": 0.0}
           for name in arms}
    for _ in range(windows):  # paired: load swings hit both arms
        for name, fn in arms.items():
            ttfts, totals, tokens, dt = drive(fn)
            a = acc[name]
            a["ttft"].extend(ttfts)
            a["total"].extend(totals)
            a["tokens"] += tokens
            a["dt"] += dt

    # the shared replica's own books: hits / tokens saved / hit rate
    import ray_tpu as _rt
    state = _rt.get(client._controller.get_routing_state.remote(
        "bench_pfx_shared"), timeout=30)
    eng = _rt.get(state["backends"]["bench_pfx_shared"]["replicas"][0]
                  .engine_state.remote(), timeout=30)
    pref = (eng.get("kv") or {}).get("prefix") or {}

    for name, a in acc.items():
        tps = a["tokens"] / a["dt"] if a["dt"] else 0.0
        row = {
            "name": name,
            "tokens_per_s_per_replica": round(tps, 1),
            "ttft_p50_ms": round(float(_np.percentile(a["ttft"], 50))
                                 * 1000, 1) if a["ttft"] else 0.0,
            "ttft_p99_ms": round(float(_np.percentile(a["ttft"], 99))
                                 * 1000, 1) if a["ttft"] else 0.0,
            "gen_p99_ms": round(float(_np.percentile(a["total"], 99))
                                * 1000, 1) if a["total"] else 0.0,
            "generations": len(a["total"]),
            "prefix_tokens": prefix_tokens,
            "gen_tokens": gen_tokens,
            "clients": n_clients,
            "windows": windows,
        }
        if name == "serve_prefix shared":
            row.update({
                "prefix_hits": pref.get("hits", 0),
                "prefix_hit_rate": pref.get("hit_rate", 0.0),
                "prefix_tokens_saved": pref.get("tokens_saved", 0),
                "kv_pages_shared": (eng.get("kv") or {}).get(
                    "pages_shared", 0),
            })
        results.append(row)
        print(f"{name}: {tps:.1f} tok/s/replica, ttft p50 "
              f"{row['ttft_p50_ms']:.0f}ms p99 "
              f"{row['ttft_p99_ms']:.0f}ms ({row['generations']} gens)")
    print(f"serve_prefix shared counters: hits={pref.get('hits')} "
          f"saved={pref.get('tokens_saved')} "
          f"hit_rate={pref.get('hit_rate')}")
    serve.shutdown()


def _cold_gang_ttft(results: list[dict], pairs: int = 3):
    """Serve gang restart TTFT, compile cache cold vs warm, PAIRED
    (round 15): each pair clears the persistent AOT compile cache,
    deploys a fresh streaming replica and times create_backend -> first
    SSE token (the restart path a gang pays end-to-end: replica actor
    spawn, engine build, kv-arena alloc, first decode-step dispatch),
    then tears it down and repeats WITHOUT clearing — the second
    replica's jax seams resolve against the executables the first one
    stored. The warm arm's hit delta is counter-verified from the
    shared on-disk index (the replica records hits into it), so the row
    proves the cache engaged rather than assuming it."""
    import http.client

    import numpy as _np

    from ray_tpu import serve
    from ray_tpu._private import compile_cache as _cc
    from ray_tpu.serve.engine import ShardedTokenLM
    from ray_tpu.serve.streaming import iter_sse_lines

    model = ShardedTokenLM.make(11, vocab=512, hidden=32, inner=64)
    margs = (model.embed.copy(), model.w_up.copy(), model.w_out.copy())
    client = serve.start(http=True)
    port = client.http_port
    seq = [0]

    def _index_hits() -> int:
        return sum(int(e.get("hits", 0))
                   for e in _cc.read_index().values())

    def restart_ttft() -> float:
        """create_backend -> first streamed token, one fresh replica.
        kv_backend=jax so the decode path runs the donated-arena jitted
        update — the seam the persistent compile cache hooks (the numpy
        default never compiles anything and the A/B would measure
        nothing)."""
        seq[0] += 1
        name = f"bench_cg{seq[0]}"
        t0 = time.perf_counter()
        client.create_backend(
            name, ShardedTokenLM, *margs,
            config={"streaming": True, "max_decode_batch": 2,
                    "max_waiting_sequences": 8, "kv_pages_total": 256,
                    "kv_backend": "jax",
                    "num_replicas": 1, "large_payload_threshold": 0})
        client.create_endpoint(name, backend=name, route=f"/{name}",
                               methods=["POST"])
        ttft = None
        deadline = time.time() + 120
        while ttft is None and time.time() < deadline:
            try:  # route table syncs asynchronously: retry until live
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=15)
                body = json.dumps({"prompt": [1, 3, 5], "max_tokens": 4,
                                   "stream": True})
                conn.request("POST", f"/{name}", body=body, headers={
                    "Content-Type": "application/json",
                    "Accept": "text/event-stream"})
                resp = conn.getresponse()
                if resp.status != 200:  # route not synced yet: a 404
                    resp.read()         # body is NOT an SSE stream —
                    conn.close()        # iterating it would block on
                    time.sleep(0.1)     # the kept-alive socket
                    continue
                # drain to done (4 tokens): abandoning the stream early
                # can wedge the proxy-side handler on the half-closed
                # socket and stall the NEXT trial's request behind it
                for ev, data in iter_sse_lines(resp.fp):
                    if ev == "error":
                        break
                    if ttft is None and data.get("tokens"):
                        ttft = time.perf_counter() - t0
                    if ev == "done" or data.get("done"):
                        break
                conn.close()
            except (http.client.HTTPException, OSError):
                time.sleep(0.2)
        client.delete_endpoint(name)
        client.delete_backend(name)
        # wait out the route-teardown sync so trial N+1 never races a
        # stale route to the now-dead replica
        gone = time.time() + 30
        while time.time() < gone:
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=5)
                conn.request("POST", f"/{name}",
                             body=json.dumps({"prompt": [1]}),
                             headers={"Content-Type": "application/json"})
                status = conn.getresponse().status
                conn.close()
                if status == 404:
                    break
            except (http.client.HTTPException, OSError):
                pass
            time.sleep(0.1)
        return ttft if ttft is not None else time.perf_counter() - t0

    cold, warm, hit_deltas = [], [], []
    for _ in range(pairs):
        _cc.clear()
        cold.append(restart_ttft())
        h0 = _index_hits()
        warm.append(restart_ttft())
        hit_deltas.append(_index_hits() - h0)
    cold_ms = float(_np.median(cold)) * 1000
    warm_ms = float(_np.median(warm)) * 1000
    results.append({
        "name": "cold_gang_ttft",
        "cold_ttft_ms": round(cold_ms, 1),
        "warm_ttft_ms": round(warm_ms, 1),
        "speedup_x": round(cold_ms / warm_ms, 3) if warm_ms else 0.0,
        "warm_cache_hits_per_restart": float(_np.mean(hit_deltas)),
        "pairs": pairs,
        "cold_trials_ms": [round(t * 1000, 1) for t in cold],
        "warm_trials_ms": [round(t * 1000, 1) for t in warm],
    })
    print(f"cold_gang_ttft: cold {cold_ms:.0f}ms vs warm {warm_ms:.0f}ms "
          f"(x{cold_ms / max(warm_ms, 1e-9):.2f}, "
          f"{float(_np.mean(hit_deltas)):.1f} cache hits/restart, "
          f"median of {pairs} pairs)")
    serve.shutdown()


def _tracing_ab(results: list[dict]):
    """Distributed-tracing overhead A/B (the tier-1 microbench gate in
    test_observability reads these rows): tracing at the DEFAULT head
    sampling rate (1%, what a cluster pays out of the box) against a
    tracing-off control, paired-interleaved on the two rows the gate
    watches — tasks sync and serve http qps. The sampling flip rides the
    live KV+pubsub plane (`ray_tpu.set_trace_sampling`), so both slices
    of each window run identical code; the only delta is maybe_trace()'s
    rate check on every entry point plus span record/flush for the ~1%
    sampled calls."""
    from ray_tpu import serve

    def arm(rate: float):
        def setup():
            ray_tpu.set_trace_sampling(rate)
            # the pubsub flip reaches raylet/worker/proxy processes
            # asynchronously; give it a beat before the slice starts
            time.sleep(0.1)

        return setup

    TR = lambda fn: {"": (arm(0.01), fn),  # noqa: E731
                     "tracing-off control": (arm(0.0), fn)}

    @ray_tpu.remote
    def small_task():
        return b"ok"

    def task_sync():
        ray_tpu.get(small_task.remote())

    timeit_ab("tracing A/B tasks sync", TR(task_sync), results=results)

    # serve http: optimized proxy only (the legacy A/B lives in
    # _serve_qps); the sampling rate toggles between the two slices of
    # EACH window so box-load swings hit both arms equally.
    client = serve.start(http=True)
    client.create_backend("noop_tr", lambda _=None: "ok", config={
        "num_replicas": 2, "max_batch_size": 32,
        "batch_wait_timeout": 0.001, "max_concurrent_queries": 8})
    client.create_endpoint("noop_tr", backend="noop_tr", route="/noop_tr")
    handle = client.get_handle("noop_tr")
    ray_tpu.get(handle.remote(None), timeout=60)  # warm the path

    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=16)
    tls = _threading.local()
    port = client.http_port

    def http_window(seconds: float = 0.7) -> float:
        return _http_qps_window(pool, tls, port, "/noop_tr", seconds)

    arm(0.01)()
    http_window(0.2)  # warm keep-alive conns
    on_rates, off_rates = [], []
    for _ in range(5):
        arm(0.01)()
        on_rates.append(http_window())
        arm(0.0)()
        off_rates.append(http_window())
    arm(0.01)()  # leave the cluster at the default rate
    _rate_rows(results, [
        ("tracing A/B serve http qps", on_rates),
        ("tracing A/B serve http qps (tracing-off control)", off_rates),
    ], windows=5)
    pool.shutdown()
    serve.shutdown()


def _profiling_ab(results: list[dict]):
    """Continuous-profiler overhead A/B (the tier-1 gate in
    test_observability reads these rows): the wall-clock sampler armed
    at its DEFAULT rate (~67 Hz, what every process pays out of the
    box) against a profiler-off control, paired-interleaved on the two
    rows the gate watches — tasks sync and serve http qps. The arm flip
    rides the live KV+pubsub plane (`ray_tpu.set_profiling`), so both
    slices of each window run identical code; the only delta is the
    sampler thread walking `sys._current_frames` plus the ~2s window
    flush into the GCS profile ring."""
    from ray_tpu import serve
    from ray_tpu._private import sampling_profiler as _sprof

    def arm(hz: float):
        def setup():
            ray_tpu.set_profiling(hz)
            # the pubsub flip reaches raylet/worker/proxy processes
            # asynchronously; give it a beat before the slice starts
            time.sleep(0.1)

        return setup

    default_hz = _sprof.default_hz()
    PR = lambda fn: {"": (arm(default_hz), fn),  # noqa: E731
                     "profiler-off control": (arm(0.0), fn)}

    @ray_tpu.remote
    def small_task():
        return b"ok"

    def task_sync():
        ray_tpu.get(small_task.remote())

    # 5 windows (not the default 3): the sampler's per-window cost is
    # small relative to box drift on this class of 1-2 core runner, so
    # the median needs more interleaved windows to converge
    timeit_ab("profiling A/B tasks sync", PR(task_sync), windows=5,
              results=results)

    client = serve.start(http=True)
    client.create_backend("noop_pr", lambda _=None: "ok", config={
        "num_replicas": 2, "max_batch_size": 32,
        "batch_wait_timeout": 0.001, "max_concurrent_queries": 8})
    client.create_endpoint("noop_pr", backend="noop_pr", route="/noop_pr")
    handle = client.get_handle("noop_pr")
    ray_tpu.get(handle.remote(None), timeout=60)  # warm the path

    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=16)
    tls = _threading.local()
    port = client.http_port

    def http_window(seconds: float = 0.7) -> float:
        return _http_qps_window(pool, tls, port, "/noop_pr", seconds)

    arm(default_hz)()
    http_window(0.2)  # warm keep-alive conns
    on_rates, off_rates = [], []
    for _ in range(9):  # see the tasks-sync note: more pairs, less drift
        arm(default_hz)()
        on_rates.append(http_window())
        arm(0.0)()
        off_rates.append(http_window())
    arm(default_hz)()  # leave the cluster at the default rate
    _rate_rows(results, [
        ("profiling A/B serve http qps", on_rates),
        ("profiling A/B serve http qps (profiler-off control)",
         off_rates),
    ], windows=9)
    pool.shutdown()
    serve.shutdown()


def _state_ab(results: list[dict]):
    """Live-state-introspection overhead A/B (the tier-1 gate in
    tests/test_state_api.py reads these rows): the stall doctor armed
    at its 1s cadence — a background thread collecting cluster_state
    (GCS + raylet + per-worker debug_state fan-out) plus histogram
    diagnosis plus stall-event dedup EVERY second, ray_tpu.start_doctor
    — against a doctor-off control, paired-interleaved on the same two
    rows the tracing gate watches (tasks sync, serve http qps). The
    introspection plane must be cheap enough to leave armed in
    production: the gate fails tier-1 on >5% regression."""
    from ray_tpu import api as _api
    from ray_tpu import serve

    def arm(on: bool):
        def setup():
            if on:
                _api.start_doctor(interval=1.0)
            else:
                _api.stop_doctor()
            time.sleep(0.05)

        return setup

    AB = lambda fn: {"": (arm(True), fn),  # noqa: E731
                     "state-off control": (arm(False), fn)}

    @ray_tpu.remote
    def small_task():
        return b"ok"

    def task_sync():
        ray_tpu.get(small_task.remote())

    timeit_ab("state A/B tasks sync", AB(task_sync), results=results)
    _api.stop_doctor()

    client = serve.start(http=True)
    client.create_backend("noop_st", lambda _=None: "ok", config={
        "num_replicas": 2, "max_batch_size": 32,
        "batch_wait_timeout": 0.001, "max_concurrent_queries": 8})
    client.create_endpoint("noop_st", backend="noop_st", route="/noop_st")
    handle = client.get_handle("noop_st")
    ray_tpu.get(handle.remote(None), timeout=60)  # warm the path

    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=16)
    tls = _threading.local()
    port = client.http_port

    def http_window(seconds: float = 0.7) -> float:
        return _http_qps_window(pool, tls, port, "/noop_st", seconds)

    http_window(0.2)  # warm keep-alive conns
    on_rates, off_rates = [], []
    for _ in range(5):
        arm(True)()
        on_rates.append(http_window())
        arm(False)()
        off_rates.append(http_window())
    arm(False)()
    _rate_rows(results, [
        ("state A/B serve http qps", on_rates),
        ("state A/B serve http qps (state-off control)", off_rates),
    ], windows=5)
    pool.shutdown()
    serve.shutdown()


def _control_plane(results: list[dict], shards: int = 4):
    """Sharded-control-plane scale-sim rows (scalesim/harness.py): 16
    spoofed raylets over 3 client processes drive the steady-state
    table-op mix and scheduler-decision stream against a real
    director+shards plane, paired-interleaved per window against the
    single-shard legacy arm (median of 5 windows), with a seeded
    mid-window SIGKILL+journal-replay restart of one shard.

    Besides the two rates, each row carries the **director-bypass**
    check — per-arm server CPU from /proc normalized per op. On boxes
    with fewer than shards+2 cores (this 2-core box included) the
    wall-clock rates UNDERSTATE the sharded plane: every extra server
    process multiplies per-tick socket syscalls (~0.4ms each under
    gVisor) on the same two cores, so the legacy arm's single perfectly-
    coalesced connection wins the transport race while its director
    burns ~14x the CPU per op. The scaling claim rides
    `director_cpu_us_per_op` (the single-process ceiling collapsing),
    not the same-box rate ratio; see PERF.md round 11."""
    from ray_tpu.scalesim.harness import run_scalesim

    sim = run_scalesim(shards=shards, raylets=16, windows=5,
                       window_s=1.0, client_procs=3, kill_shard=True)
    for label in (f"shards{shards}", "shards1"):
        arm = sim["arms"][label]
        suffix = ("" if label != "shards1"
                  else " (single-shard legacy control)")
        for kind, key in (("gcs ops", "gcs_ops_per_s"),
                          ("scheduler decisions", "decisions_per_s")):
            stat = arm[key]
            trials = stat["samples"]
            mean = sum(trials) / len(trials)
            sd = (sum((t - mean) ** 2 for t in trials)
                  / max(len(trials) - 1, 1)) ** 0.5
            row = {"name": f"control_plane {kind}{suffix}",
                   "per_second": stat["median"], "sd": round(sd, 2),
                   "trials": trials,
                   "director_cpu_us_per_op":
                       arm["director_cpu_us_per_op"]}
            if kind == "gcs ops" and not suffix:
                row["director_bypass_ratio"] = sim[
                    "director_bypass_ratio"]
                row["cores"] = sim["cores"]
                row["shard_kill"] = sim["kill"]
            results.append(row)
            print(f"{row['name']} per second "
                  f"{row['per_second']:.1f} "
                  f"(director {row['director_cpu_us_per_op']}us/op)")


def _placement_topology(results: list[dict], windows: int = 3):
    """Topology placement scale-sim row (scalesim/topology_sim.py): 16
    spoofed raylets with seeded-shuffled 4x4-torus coords answer the
    REAL 2PC against two live directors, paired-interleaved ICI_RING vs
    PACK windows. Per arm: mean ring circumference (torus wire around
    consecutive bundle ranks — ICI_RING's target is == world size, the
    perfect ring), simulated spillback-chain hops, client placement
    latency, and the director's own `gcs.placement_score_s` p99
    (warmup-excluded bucket delta; the <=5% latency A/B)."""
    from ray_tpu.scalesim.topology_sim import run_topology_sim

    sim = run_topology_sim(raylets=16, windows=windows, bundles=4)
    for arm in ("ici_ring", "pack"):
        a = sim["arms"][arm]
        lat_ms = a["placement_latency_ms"]["mean"]
        row = {"name": f"placement_topology {arm}",
               "per_second": round(1e3 / max(lat_ms, 1e-9), 2),
               "sd": 0.0,
               "gangs": a["gangs"],
               "mean_ring_circumference": a["mean_ring_circumference"],
               "mean_spillback_hops": a["mean_spillback_hops"],
               "placement_latency_ms": lat_ms,
               "score_p99_s": a["score_p99_s"],
               "fallbacks": a["fallbacks"],
               "leaked_holds": a["leaked_holds"]}
        if arm == "ici_ring":
            row["circumference_ratio_vs_pack"] = sim[
                "circumference_ratio"]
            row["spillback_hops_ratio_vs_pack"] = sim[
                "spillback_hops_ratio"]
            row["score_p99_ratio_vs_pack"] = sim["score_p99_ratio"]
        results.append(row)
        print(f"placement_topology {arm}: circumference "
              f"{a['mean_ring_circumference']}, spillback hops "
              f"{a['mean_spillback_hops']}, latency {lat_ms}ms")


def _train_sharded(results: list[dict], epochs: int = 3,
                   steps_per_epoch: int = 8):
    """ZeRO-sharded trainer A/B (paired arms, same model/data/steps):
    `replicated` = allreduce + full optax state on every worker; `zero`
    = reducescatter → shard update → allgather; `zero_int8` adds the
    int8 block-scaled grad wire. Rows record tokens/s, per-worker
    optimizer bytes (`train.optim_shard_bytes`), peak worker RSS, and —
    for the int8 arm — socket bytes saved, counter-verified against
    `collective.quantized_bytes_saved_total` next to the analytic exact
    wire size. A second pair (`train_ingest off/on`) runs the streaming
    ingest pipeline at depth 2 and records `train.ingest_wait_s` p50 —
    the tier-1 gate (tests/test_train_sharded.py) asserts the sharded
    arm's optimizer memory is below replicated's, the int8 arm saved
    >= 70% of exact wire bytes, and the ingest-on arm is not
    input-bound."""
    import jax.numpy as jnp
    import optax

    from ray_tpu.train import IngestSpec, Trainer, TrainingOperator
    from ray_tpu.train.ingest import hist_quantile
    from ray_tpu.train import sharding as _shardlib

    DIM, OUT, BS = 256, 96, 16  # 24576 params -> 96KiB f32 grad bucket
    WORLD = 3  # ring tier needs world > 2 (pairwise degenerates to hub)

    class BenchOp(TrainingOperator):
        def setup(self, config):
            rng = np.random.default_rng(0)
            X = rng.standard_normal((16, 256)).astype(np.float32)
            Y = rng.standard_normal((16, 96)).astype(np.float32)
            self.register(
                model_init=lambda k: {
                    "w": jnp.zeros((256, 96), jnp.float32)},
                loss_fn=lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2),
                optimizer=optax.adam(1e-3))
            if not config.get("bench_ingest"):
                self.register_data(
                    train_loader=[(X, Y)] * config["bench_steps"])

    def dataset_fn(shard_index, num_shards, config):
        rng = np.random.default_rng(shard_index)
        X = rng.standard_normal((16, 256)).astype(np.float32)
        Y = rng.standard_normal((16, 96)).astype(np.float32)
        return [(X, Y)] * config["bench_steps"]

    def run(name, *, sharded=False, quantize=None, ingest=False):
        config = {"bench_steps": steps_per_epoch, "bench_ingest": ingest}
        tr = Trainer(
            BenchOp, num_workers=WORLD, config=config, backend="host",
            collective_transport="ring", placement_strategy=None,
            sharded=sharded, quantize=quantize,
            # zero-CPU actors: the harness runs on 1-core containers and
            # the arms are a paired A/B, so logical-CPU contention
            # cancels out of every comparison the gate reads
            resources_per_worker={"CPU": 0},
            ingest=IngestSpec(dataset_fn, resources={"CPU": 0})
            if ingest else None)
        try:
            rates = []
            for _ in range(epochs):
                res = tr.train()
                rates.append(res["samples_per_s"])
            w = tr.workers
            opt_bytes = max(ray_tpu.get(
                [x.read_counter.remote("train.optim_shard_bytes")
                 for x in w], timeout=60))
            saved = sum(ray_tpu.get(
                [x.read_counter.remote(
                    "collective.quantized_bytes_saved_total")
                 for x in w], timeout=60))
            rss = max(ray_tpu.get(
                [x.peak_rss.remote() for x in w], timeout=60))
            wait = ray_tpu.get(
                w[0].read_metric.remote("train.ingest_wait_s"), timeout=60)
            row = {"name": name,
                   "per_second": float(np.median(rates)),
                   "sd": float(np.std(rates)),
                   "tokens_per_s": float(np.median(rates)),
                   "optim_state_bytes_per_worker": int(opt_bytes),
                   "peak_worker_rss_mb": round(rss / 1e6, 1),
                   "wire_saved_bytes": int(saved)}
            if quantize:
                # analytic exact-tier wire: (w-1) * chunk elems * 4B per
                # reducescatter, one per step per worker
                pad = _shardlib.padded_numel(DIM * OUT, WORLD)
                steps = epochs * steps_per_epoch
                row["wire_exact_bytes"] = int(
                    steps * WORLD * (WORLD - 1) * (pad // WORLD) * 4)
            if ingest:
                row["ingest_wait_p50_s"] = hist_quantile(wait or {}, 0.5)
                row["ingest_wait_count"] = (wait or {}).get("count", 0)
            results.append(row)
            print(f"{name}: {row['per_second']:.1f} tokens/s, "
                  f"opt {opt_bytes / 1024:.0f}KiB/worker, "
                  f"rss {row['peak_worker_rss_mb']}MB, "
                  f"wire saved {int(saved)}B")
        finally:
            tr.shutdown(force=True)

    run("train_sharded replicated")
    run("train_sharded zero", sharded=True)
    run("train_sharded zero_int8", sharded=True, quantize="int8")
    run("train_ingest off", sharded=True)
    run("train_ingest on depth2", sharded=True, ingest=True)


if __name__ == "__main__":
    from ray_tpu._private.bench_meta import run_metadata as _metadata
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--json", action="store_true",
                        help="also print one JSON line with all results")
    parser.add_argument("--out", default=None,
                        help="write results JSON to this path")
    parser.add_argument("--only", default=None,
                        help="run a single bench group (e.g. serve_mixed)"
                             " instead of the full suite; always includes"
                             " the same-window calibration controls")
    parser.add_argument("--merge", default=None,
                        help="merge this run's rows into an existing "
                             "results JSON (same-name rows replaced, new"
                             " ones appended) — for recording one new "
                             "bench without a full-suite rerun")
    args = parser.parse_args()
    if args.only:
        groups = {"serve_mixed": _serve_mixed, "serve": _serve_qps,
                  "serve_stream": _serve_stream,
                  "serve_prefix": _serve_prefix,
                  "tracing": _tracing_ab, "state": _state_ab,
                  "collective": _collective_bench,
                  "cold_gang": _cold_gang_ttft,
                  "placement_topology": _placement_topology,
                  "train_sharded": _train_sharded}
        if args.only not in groups:
            parser.error(f"--only must be one of {sorted(groups)}")
        results: list = []
        calibrate(results)
        ray_tpu.init()
        try:
            groups[args.only](results)
        finally:
            ray_tpu.shutdown()
    else:
        results = main()
    doc = {"metadata": _metadata(), "results": results}
    if args.merge:
        with open(args.merge) as f:
            base = json.load(f)
        rows = {r["name"]: r for r in results}
        # the base file's calibration rows contextualize ITS rows; this
        # partial window's calibration travels with the partial-run
        # metadata instead of overwriting them
        calib = {n: rows.pop(n) for n in list(rows)
                 if n.startswith("calibration")}
        merged = [rows.pop(r["name"], r) for r in base["results"]]
        merged.extend(rows.values())
        base["results"] = merged
        base.setdefault("metadata", {})
        base["metadata"]["last_partial_run"] = {
            "only": args.only, "calibration": list(calib.values()),
            **_metadata()}
        doc = base
        with open(args.merge, "w") as f:
            json.dump(doc, f, indent=1)
    if args.json:
        print(json.dumps(doc))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
