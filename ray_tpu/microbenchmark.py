"""Core microbenchmark suite (reference: python/ray/ray_perf.py, invoked
as `ray microbenchmark`; harness: _private/ray_microbenchmark_helpers.py).
Metric names match the reference's release logs
(release/release_logs/1.2.0/microbenchmark.txt) so numbers are directly
comparable with BASELINE.md."""

from __future__ import annotations

import json
import time

import numpy as np

import ray_tpu


def timeit(name: str, fn, multiplier: int = 1, seconds: float = 2.0,
           results: list | None = None):
    """reference: ray_microbenchmark_helpers.py:timeit."""
    # warmup
    fn()
    trials = []
    for _ in range(3):
        start = time.perf_counter()
        count = 0
        while time.perf_counter() - start < seconds / 3:
            fn()
            count += 1
        dt = time.perf_counter() - start
        trials.append(count * multiplier / dt)
    mean = float(np.mean(trials))
    sd = float(np.std(trials))
    print(f"{name} per second {mean:.2f} +- {sd:.2f}")
    if results is not None:
        results.append({"name": name, "per_second": mean, "sd": sd})
    return mean


def main(seconds_per_case: float = 2.0) -> list[dict]:
    results: list[dict] = []
    ray_tpu.init()

    arr = np.zeros(100, dtype=np.int64)            # small: inline path
    big = np.zeros(10 * 1024 * 1024, dtype=np.uint8)  # 10MB: plasma path

    def put_small():
        ray_tpu.put(arr)

    timeit("single client put calls", put_small, results=results)

    def get_small():
        ref = ray_tpu.put(arr)
        ray_tpu.get(ref)

    timeit("single client get calls", get_small, results=results)

    def put_large():
        ray_tpu.get(ray_tpu.put(big))

    n = timeit("single client put+get large (10MB)", put_large,
               results=results)
    gb_s = n * big.nbytes / 1e9
    print(f"single client put gigabytes per second {gb_s:.2f}")
    results.append({"name": "single client put gigabytes",
                    "per_second": gb_s, "sd": 0.0})

    @ray_tpu.remote
    def small_task():
        return b"ok"

    def task_sync():
        ray_tpu.get(small_task.remote())

    timeit("single client tasks sync", task_sync, results=results)

    def tasks_async():
        ray_tpu.get([small_task.remote() for _ in range(100)])

    timeit("single client tasks async", tasks_async, multiplier=100,
           results=results)

    @ray_tpu.remote
    class Actor:
        def small_value(self):
            return b"ok"

    a = Actor.remote()

    def actor_sync():
        ray_tpu.get(a.small_value.remote())

    timeit("1:1 actor calls sync", actor_sync, results=results)

    def actor_async():
        ray_tpu.get([a.small_value.remote() for _ in range(100)])

    timeit("1:1 actor calls async", actor_async, multiplier=100,
           results=results)

    n_actors = 4
    actors = [Actor.remote() for _ in range(n_actors)]

    def actors_async():
        refs = []
        for actor in actors:
            refs.extend(actor.small_value.remote() for _ in range(25))
        ray_tpu.get(refs)

    timeit("n:n actor calls async", actors_async, multiplier=100,
           results=results)

    _serve_qps(results)

    ray_tpu.shutdown()
    return results


def _serve_qps(results: list[dict]):
    """Serve noop throughput (reference: serve release bench, ~3-4k qps
    noop via HTTP). Measured through the handle (router batching path)
    and through the HTTP proxy."""
    from ray_tpu import serve

    client = serve.start(http=True)
    client.create_backend("noop", lambda _=None: "ok", config={
        "num_replicas": 2, "max_batch_size": 32,
        "batch_wait_timeout": 0.001, "max_concurrent_queries": 8})
    client.create_endpoint("noop", backend="noop", route="/noop")
    handle = client.get_handle("noop")
    ray_tpu.get(handle.remote(None))  # warm the path

    # qps is a CONCURRENT-load metric (the reference measures with wrk):
    # router.assign intentionally blocks each caller until its batch is
    # dispatched, so drive it from a client thread pool.
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=16)

    def one_handle_call(_):
        return ray_tpu.get(handle.remote(None), timeout=30)

    def handle_call():
        list(pool.map(one_handle_call, range(64)))

    timeit("serve handle noop calls", handle_call, multiplier=64,
           results=results)

    # Keep-alive connections (urllib reconnects per request, which would
    # measure TCP handshakes, not the proxy).
    import http.client
    import threading as _threading

    tls = _threading.local()

    def one_http_call(_):
        conn = getattr(tls, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection("127.0.0.1",
                                              client.http_port)
            tls.conn = conn
        try:
            conn.request("GET", "/noop")
            conn.getresponse().read()
        except (http.client.HTTPException, OSError):
            tls.conn = None
            raise

    def http_call():
        list(pool.map(one_http_call, range(64)))

    timeit("serve http noop qps", http_call, multiplier=64,
           results=results)
    pool.shutdown()
    serve.shutdown()


if __name__ == "__main__":
    from ray_tpu._private.bench_meta import run_metadata as _metadata
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--json", action="store_true",
                        help="also print one JSON line with all results")
    parser.add_argument("--out", default=None,
                        help="write results JSON to this path")
    args = parser.parse_args()
    doc = {"metadata": _metadata(), "results": main()}
    if args.json:
        print(json.dumps(doc))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
