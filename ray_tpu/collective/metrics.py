"""Collective data-plane counters (registered at import so the
metrics-registry drift gate — tests/test_observability.py — can hold
ARCHITECTURE.md to them).

device_ops_total counts ops dispatched on the DEVICE (ICI/XLA) tier;
quantized_bytes_saved_total accumulates wire bytes the int8 block-scaled
format avoided sending versus the exact dtype (host ring: real socket
bytes; device tier: ICI transfer bytes the quantized ppermute ring
skipped).
"""

from __future__ import annotations

from ray_tpu._private import stats

DEVICE_OPS = stats.Count(
    "collective.device_ops_total",
    "collective ops dispatched on the DEVICE (ICI/XLA) transport tier")

PALLAS_OPS = stats.Count(
    "collective.pallas_ops_total",
    "collective ops dispatched on the PALLAS fused-kernel tier (one "
    "pallas_call per op: quantize/DMA/combine ring fused)")

QUANT_SAVED = stats.Count(
    "collective.quantized_bytes_saved_total",
    "wire bytes avoided by int8 block-scaled quantized collectives "
    "(exact-dtype bytes minus quantized payload+scale bytes)")

TRANSPORT_DERIVED = stats.Count(
    "collective.transport_derived_total",
    "collective groups whose transport tier was derived from an "
    "ICI_RING placement record (per rank) instead of the unanimous "
    "probe round — the placement GUARANTEED the geometry the probe "
    "used to discover")

OP_S = stats.Histogram(
    "collective.op_s", stats.LATENCY_BOUNDARIES_S,
    "collective op wall time (allreduce/reduce/broadcast/allgather/"
    "reducescatter/barrier), every call on every tier; exemplar links "
    "the sampled caller's trace")
