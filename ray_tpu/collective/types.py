"""Collective types (reference: python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum


class Backend(str, enum.Enum):
    """Collective backends.

    XLA — in-process device-mesh collectives (the ICI path): ops compile to
          XLA collectives (psum/all_gather/...) over a jax Mesh; this is the
          TPU-native replacement for the reference's NCCL backend
          (reference: collective_group/nccl_collective_group.py:115).
    HOST — cross-process CPU collectives over TCP with GCS rendezvous (the
          gloo-equivalent; also the DCN stand-in between TPU hosts).
    AUTO — XLA when the group is a single process with >1 device, else HOST.
    """

    XLA = "xla"
    HOST = "host"
    AUTO = "auto"


class Transport(str, enum.Enum):
    """HOST-backend data-plane tiers (selected per op by payload size and
    node placement; pin one with HostGroup(transport=...) or the
    RAY_TPU_COLLECTIVE_TRANSPORT env var — tests and the perf A/B do).

    HUB — star topology through rank 0's socket; latency-optimal for
          control-sized tensors, carries every op kind.
    RING — direct rank-to-rank TCP ring, chunk-pipelined and zero-copy;
          the bandwidth path for large tensors across nodes.
    RING_UNPIPELINED — the pre-pipelining ring ALLREDUCE, preserved as
          the control arm of the perf A/B. Allreduce-only: the other
          collectives never had an unpipelined ring, so under this pin
          they run the pipelined ring data plane.
    SHM — one mmap'd tmpfs segment per group when every rank shares a
          node: collectives become pure memory traffic.
    DEVICE — the accelerator's own interconnect: when every rank's
          payload is a jax.Array and the group's processes share one
          jax runtime (parallel/multihost), ops dispatch through cached
          jitted shard_map collectives (psum/all_gather/psum_scatter)
          so bytes ride ICI/XLA without touching host RAM
          (backends/xla_backend.DeviceTransport).
    PALLAS — the fused-kernel refinement of the device plane for
          SMALL latency-critical ops (decode-step allreduce, small grad
          buckets): the whole quantized/exact ring schedule — chunk,
          DMA to the ICI neighbor, combine, relay-gather — runs inside
          ONE pallas_call (backends/pallas_backend.PallasTransport), so
          an op is one kernel launch instead of a shard_map dispatch
          graph. Ops above `pallas_max_bytes` fall through to DEVICE;
          a pallas pin therefore behaves like a device pin for large
          payloads and for the op kinds the kernel tier does not carry
          (broadcast).
    AUTO — pallas for small device arrays when the runtime spans the
          group, else device, else shm when node-local, else ring,
          else hub.
    """

    AUTO = "auto"
    HUB = "hub"
    RING = "ring"
    RING_UNPIPELINED = "ring_unpipelined"
    SHM = "shm"
    DEVICE = "device"
    PALLAS = "pallas"


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"  # TPU-native addition: fused mean avoids a divide pass


_NUMPY_REDUCE = {
    ReduceOp.SUM: "add",
    ReduceOp.PRODUCT: "multiply",
    ReduceOp.MIN: "minimum",
    ReduceOp.MAX: "maximum",
}

# Block-scaled int8 quantization (EQuARX-style): payloads are cut into
# QUANT_BLOCK-element blocks, each carried on the wire as int8 values
# plus one float32 scale (absmax/127); the reduce happens on the
# dequantized float32 values.  Shared by the host ring's quantized chunk
# format and the device tier's quantized ppermute ring so both planes
# agree on the wire granularity (and the analytic error bound).
QUANT_BLOCK = 256
QUANTIZE_INT8 = "int8"


def is_jax_array(tensor) -> bool:
    """True for jax.Arrays WITHOUT importing jax in pure-host processes:
    if jax was never imported, the payload cannot be one. The single
    probe behind the public-API payload prep and the DEVICE-tier
    routing — they must never disagree about what counts as a device
    array."""
    import sys

    jmod = sys.modules.get("jax")
    return jmod is not None and isinstance(tensor, jmod.Array)


def normalize_quantize(quantize) -> str | None:
    """Canonicalize the `quantize=` knob: None/""/"none"/False mean
    exact; "int8" selects block-scaled int8. Anything else is a typo
    that must fail loudly (a silently-ignored lossy knob would corrupt
    an A/B)."""
    if quantize in (None, False, "", "none"):
        return None
    if str(quantize).lower() == QUANTIZE_INT8:
        return QUANTIZE_INT8
    raise ValueError(
        f"unknown quantize mode {quantize!r} (expected None or 'int8')")
