"""Collective types (reference: python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum


class Backend(str, enum.Enum):
    """Collective backends.

    XLA — in-process device-mesh collectives (the ICI path): ops compile to
          XLA collectives (psum/all_gather/...) over a jax Mesh; this is the
          TPU-native replacement for the reference's NCCL backend
          (reference: collective_group/nccl_collective_group.py:115).
    HOST — cross-process CPU collectives over TCP with GCS rendezvous (the
          gloo-equivalent; also the DCN stand-in between TPU hosts).
    AUTO — XLA when the group is a single process with >1 device, else HOST.
    """

    XLA = "xla"
    HOST = "host"
    AUTO = "auto"


class Transport(str, enum.Enum):
    """HOST-backend data-plane tiers (selected per op by payload size and
    node placement; pin one with HostGroup(transport=...) or the
    RAY_TPU_COLLECTIVE_TRANSPORT env var — tests and the perf A/B do).

    HUB — star topology through rank 0's socket; latency-optimal for
          control-sized tensors, carries every op kind.
    RING — direct rank-to-rank TCP ring, chunk-pipelined and zero-copy;
          the bandwidth path for large tensors across nodes.
    RING_UNPIPELINED — the pre-pipelining ring ALLREDUCE, preserved as
          the control arm of the perf A/B. Allreduce-only: the other
          collectives never had an unpipelined ring, so under this pin
          they run the pipelined ring data plane.
    SHM — one mmap'd tmpfs segment per group when every rank shares a
          node: collectives become pure memory traffic.
    AUTO — shm when node-local, else ring, else hub.
    """

    AUTO = "auto"
    HUB = "hub"
    RING = "ring"
    RING_UNPIPELINED = "ring_unpipelined"
    SHM = "shm"


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"  # TPU-native addition: fused mean avoids a divide pass


_NUMPY_REDUCE = {
    ReduceOp.SUM: "add",
    ReduceOp.PRODUCT: "multiply",
    ReduceOp.MIN: "minimum",
    ReduceOp.MAX: "maximum",
}
