"""Collective types (reference: python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum


class Backend(str, enum.Enum):
    """Collective backends.

    XLA — in-process device-mesh collectives (the ICI path): ops compile to
          XLA collectives (psum/all_gather/...) over a jax Mesh; this is the
          TPU-native replacement for the reference's NCCL backend
          (reference: collective_group/nccl_collective_group.py:115).
    HOST — cross-process CPU collectives over TCP with GCS rendezvous (the
          gloo-equivalent; also the DCN stand-in between TPU hosts).
    AUTO — XLA when the group is a single process with >1 device, else HOST.
    """

    XLA = "xla"
    HOST = "host"
    AUTO = "auto"


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"  # TPU-native addition: fused mean avoids a divide pass


_NUMPY_REDUCE = {
    ReduceOp.SUM: "add",
    ReduceOp.PRODUCT: "multiply",
    ReduceOp.MIN: "minimum",
    ReduceOp.MAX: "maximum",
}
