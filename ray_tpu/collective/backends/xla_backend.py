"""XLA backend: device-mesh collectives — the TPU ICI data plane.

This replaces the reference's NCCL groups (reference:
collective_group/nccl_collective_group.py:115) with XLA collectives over a
jax Mesh: every op is a cached jitted shard_map whose body is the
corresponding lax collective (psum / all_gather / psum_scatter / ppermute),
so on TPU the transfer rides ICI links and fuses with surrounding
computation when called under jit.

Single-controller model: one process drives all devices in the group
("ranks" = devices, not processes). The caller holds a stacked array whose
leading axis is the rank axis; each op returns the per-rank results stacked
the same way. For multi-host pods the same code runs under
jax.distributed with a global mesh (see ray_tpu.parallel.multihost).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.collective.types import ReduceOp

AXIS = "ranks"


def _shard_map(fn, mesh, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


class XlaGroup:
    def __init__(self, group_name: str, devices=None):
        self.group_name = group_name
        self.devices = list(devices) if devices is not None else jax.devices()
        self.world_size = len(self.devices)
        self.mesh = Mesh(self.devices, (AXIS,))

    # Each op: stacked input of shape [world_size, ...] -> stacked output.

    @functools.cached_property
    def _allreduce_sum(self):
        return jax.jit(_shard_map(
            lambda x: jax.lax.psum(x, AXIS), self.mesh, P(AXIS), P(AXIS)))

    @functools.cached_property
    def _allreduce_max(self):
        return jax.jit(_shard_map(
            lambda x: jax.lax.pmax(x, AXIS), self.mesh, P(AXIS), P(AXIS)))

    @functools.cached_property
    def _allreduce_min(self):
        return jax.jit(_shard_map(
            lambda x: jax.lax.pmin(x, AXIS), self.mesh, P(AXIS), P(AXIS)))

    @functools.cached_property
    def _allreduce_mean(self):
        return jax.jit(_shard_map(
            lambda x: jax.lax.pmean(x, AXIS), self.mesh, P(AXIS), P(AXIS)))

    def allreduce(self, stacked, op: ReduceOp = ReduceOp.SUM):
        """stacked: [world, ...]; returns [world, ...] where every slice is
        the reduction across the leading axis."""
        fn = {
            ReduceOp.SUM: self._allreduce_sum,
            ReduceOp.MAX: self._allreduce_max,
            ReduceOp.MIN: self._allreduce_min,
            ReduceOp.MEAN: self._allreduce_mean,
        }[ReduceOp(op)]
        return fn(stacked)

    @functools.cached_property
    def _allgather(self):
        # per-rank shard [1, ...] -> full copy on every rank
        def body(x):
            return jax.lax.all_gather(x[0], AXIS)[None]

        return jax.jit(_shard_map(body, self.mesh, P(AXIS), P(AXIS)))

    def allgather(self, stacked):
        """[world, ...] -> [world, world, ...]: every rank sees all slices."""
        return self._allgather(stacked)

    @functools.cached_property
    def _reducescatter(self):
        def body(x):
            # x: [1, world*chunk, ...] per rank; scatter the sum along axis 1
            return jax.lax.psum_scatter(x[0], AXIS, scatter_dimension=0,
                                        tiled=False)

        return jax.jit(_shard_map(body, self.mesh, P(AXIS), P(AXIS)))

    def reducescatter(self, stacked):
        """[world, world, ...] -> [world, ...]: rank r holds sum of
        stacked[:, r]."""
        out = self._reducescatter(stacked)
        return out

    @functools.cached_property
    def _ppermute_right(self):
        perm = [(i, (i + 1) % self.world_size)
                for i in range(self.world_size)]

        def body(x):
            return jax.lax.ppermute(x, AXIS, perm)

        return jax.jit(_shard_map(body, self.mesh, P(AXIS), P(AXIS)))

    def shift_right(self, stacked):
        """Ring permute: rank r's slice moves to rank (r+1) % world."""
        return self._ppermute_right(stacked)

    def broadcast(self, value, src_rank: int = 0):
        src = value[src_rank] if value.ndim and value.shape[0] == \
            self.world_size else value
        return jnp.broadcast_to(src, (self.world_size,) + src.shape)

    def barrier(self):
        # Device-level barrier: a trivial psum forces all ranks to sync.
        x = jnp.zeros((self.world_size, 1), jnp.float32)
        jax.block_until_ready(self.allreduce(x))

    def destroy(self):
        pass
