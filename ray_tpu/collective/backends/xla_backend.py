"""XLA backend: device-mesh collectives — the TPU ICI data plane.

This replaces the reference's NCCL groups (reference:
collective_group/nccl_collective_group.py:115) with XLA collectives over a
jax Mesh: every op is a cached jitted shard_map whose body is the
corresponding lax collective (psum / all_gather / psum_scatter / ppermute),
so on TPU the transfer rides ICI links and fuses with surrounding
computation when called under jit.

One implementation, three front doors (the former xla_global.py global-mesh
group is unified here — the shard_map plumbing exists exactly once):

- `XlaGroup` — single-controller: one process drives all devices in the
  group ("ranks" = devices). The caller holds a stacked array whose
  leading axis is the rank axis; each op returns per-rank results stacked
  the same way.
- `ProcessMeshGroup` (alias `GlobalMeshGroup`) — Backend.XLA across actor
  PROCESSES: N actors joined one jax.distributed runtime
  (parallel/multihost) are one rank each; ops ride the global mesh.
- `DeviceTransport` — the HOST backend's Transport.DEVICE tier
  (host_backend._device_route): per-op dispatch of a host collective
  group onto the device plane when every rank holds a jax.Array and the
  runtime spans the group.

All three share `_DeviceOps`, a cache of jitted shard_map bodies keyed by
(op kind, dtype, shape-class): flat payloads pad to the next power of two
so nearby sizes reuse one compiled body and the cache stays O(log size)
per op/dtype instead of one entry per exact shape.

Quantized allreduce (`quantize="int8"`, EQuARX-style — PAPERS.md): the
payload is cut into QUANT_BLOCK-element blocks, each carried as int8
values plus one float32 scale (absmax/127), and the op runs as a
ppermute ring inside one shard_map body — the reduce-scatter phase
re-quantizes the partial sum every hop and accumulates on the
dequantized float32 values; the allgather phase quantizes the reduced
chunk once and relays the same bytes, so every rank dequantizes
identical data and outputs agree bitwise across ranks. ICI transfer
volume drops ~4x for float32 (int8 payload + one f32 scale per block).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.collective.types import (QUANT_BLOCK, QUANTIZE_INT8,
                                      ReduceOp, normalize_quantize)

AXIS = "ranks"


def _shard_map(fn, mesh, in_specs, out_specs):
    # the one version-portable shim, shared with the sharded kernels
    from ray_tpu.parallel.mesh import shard_map

    return shard_map(fn, mesh, in_specs, out_specs)


def _bucket(n: int) -> int:
    """Shape-class for the jit cache: next power of two >= n (floor 16)."""
    return 1 << max(4, (max(n, 1) - 1).bit_length())


def quantize_blocks(x, block: int = QUANT_BLOCK):
    """Block-scaled symmetric int8: flat float [n] (n % block == 0) ->
    (int8 [n], float32 scales [n // block]); scale = absmax/127 per
    block (1.0 for all-zero blocks so dequant stays exact zeros)."""
    b = x.reshape(-1, block)
    absmax = jnp.max(jnp.abs(b), axis=1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(b / scale[:, None]), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale


def dequantize_blocks(q, scale, block: int = QUANT_BLOCK):
    return (q.reshape(-1, block).astype(jnp.float32)
            * scale[:, None]).reshape(-1)


# combine step for the quantized ring (MEAN accumulates with add; the
# caller divides by world size at the end)
_QRING_COMBINE = {
    ReduceOp.SUM: jnp.add,
    ReduceOp.MEAN: jnp.add,
    ReduceOp.MAX: jnp.maximum,
    ReduceOp.MIN: jnp.minimum,
}


class _DeviceOps:
    """Cached jitted shard_map collectives over one mesh axis.

    Bodies operate on the flat [world, B] layout (each rank holds one
    [1, B] row of an axis-sharded global array); the cache key is
    (op kind, dtype, shape-class, static extras), so compilation is paid
    once per size class and shared by every caller of the mesh."""

    def __init__(self, mesh, axis: str, world: int):
        self.mesh = mesh
        self.axis = axis
        self.world = world
        self._cache: dict = {}

    def _jit(self, key, body, out_specs=None):
        fn = self._cache.get(key)
        if fn is None:
            from jax.sharding import PartitionSpec as P

            from ray_tpu._private import compile_cache as _cc

            jitted = jax.jit(_shard_map(
                body, self.mesh, P(self.axis, None),
                out_specs if out_specs is not None
                else P(self.axis, None)))
            # the persistent AOT cache fronts the compile seam: a warm
            # restart deserializes the stored executable — a cache HIT
            # records NO compile, so jax.compiles_total stays flat —
            # while a cold process compiles, records it exactly as
            # before, and exports + stores for the next generation.
            # `key` already carries every compile-relevant input (op,
            # dtype, shape-class, axis, world); the runtime fingerprint
            # (jax version, backend, device kinds, process count) rides
            # inside the cache key derivation.
            fn = self._cache[key] = _cc.CachedFunction(
                "collective", key, jitted,
                record_key="collective:" + ":".join(map(str, key)))
        return fn

    # -- exact bodies ---------------------------------------------------

    def allreduce(self, garr, op: ReduceOp):
        axis = self.axis
        op = ReduceOp(op)
        kind = ReduceOp.SUM if op == ReduceOp.MEAN else op
        # key audit: EVERY compile-relevant input — op kind, reduce
        # dtype, shape-class, axis name, world size, exact-vs-quantized
        # wire format — so two ops differing in any of them never share
        # an executable (the quantized ring keys "qar"+"int8" below)
        key = ("ar", "exact", kind.value, garr.dtype.name,
               garr.shape[1], axis, self.world)
        if op in (ReduceOp.SUM, ReduceOp.MEAN):
            def body(x):
                return jax.lax.psum(x, axis)
        elif op == ReduceOp.MAX:
            def body(x):
                return jax.lax.pmax(x, axis)
        elif op == ReduceOp.MIN:
            def body(x):
                return jax.lax.pmin(x, axis)
        else:  # PRODUCT: no lax primitive — gather rows, multiply local
            def body(x):
                return jnp.prod(jax.lax.all_gather(x[0], axis), axis=0)[None]
        return self._jit(key, body)(garr)

    def allgather(self, garr):
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        key = ("ag", garr.dtype.name, garr.shape[1], axis, self.world)

        def body(x):
            return jax.lax.all_gather(x[0], axis)[None]

        return self._jit(key, body, P(axis, None, None))(garr)

    def reducescatter_even(self, garr):
        """[w, P] -> [w, P//w]: rank r's row is the sum of everyone's
        chunk r (psum_scatter; P must divide by world)."""
        axis = self.axis
        key = ("rs", garr.dtype.name, garr.shape[1], axis, self.world)

        def body(x):
            return jax.lax.psum_scatter(x[0], axis, scatter_dimension=0,
                                        tiled=True)[None]

        return self._jit(key, body)(garr)

    def broadcast(self, garr, src: int):
        axis = self.axis
        key = ("bc", src, garr.dtype.name, garr.shape[1], axis,
               self.world)

        def body(x):
            r = jax.lax.axis_index(axis)
            return jax.lax.psum(
                jnp.where(r == src, x, jnp.zeros_like(x)), axis)

        return self._jit(key, body)(garr)

    def shift_right(self, garr):
        axis, w = self.axis, self.world
        perm = [(i, (i + 1) % w) for i in range(w)]
        key = ("shift", garr.dtype.name, garr.shape[1], axis, w)

        def body(x):
            return jax.lax.ppermute(x, axis, perm)

        return self._jit(key, body)(garr)

    # -- quantized ring -------------------------------------------------

    def allreduce_quantized(self, garr, op: ReduceOp):
        """garr: [w, w*C] float32, C % QUANT_BLOCK == 0. Block-scaled
        int8 ppermute ring: w-1 reduce hops (re-quantize the partial
        each hop, combine dequantized f32), then quantize the reduced
        chunk once and relay the same bytes w-1 gather hops — all ranks
        dequantize identical data, so outputs agree bitwise."""
        axis, w = self.axis, self.world
        cmb = _QRING_COMBINE[ReduceOp(op)]
        C = garr.shape[1] // w
        perm = [(i, (i + 1) % w) for i in range(w)]
        key = ("qar", QUANTIZE_INT8, QUANT_BLOCK,
               ReduceOp(op).value if cmb is not jnp.add else "add",
               garr.dtype.name, garr.shape[1], axis, w)

        def body(x):
            r = jax.lax.axis_index(axis)
            chunks = x[0].reshape(w, C)

            def fwd(v):
                return jax.lax.ppermute(v, axis, perm)

            # reduce-scatter: after w-1 hops rank r holds chunk (r+1)%w
            acc = jnp.take(chunks, r, axis=0)
            for s in range(1, w):
                q, sc = quantize_blocks(acc)
                q, sc = fwd(q), fwd(sc)
                acc = cmb(dequantize_blocks(q, sc),
                          jnp.take(chunks, (r - s) % w, axis=0))
            # allgather: quantize once, relay the same bytes
            q, sc = quantize_blocks(acc)
            out = jnp.zeros((w, C), jnp.float32)
            out = out.at[(r + 1) % w].set(dequantize_blocks(q, sc))
            for s in range(1, w):
                q, sc = fwd(q), fwd(sc)
                out = out.at[(r - s + 1) % w].set(dequantize_blocks(q, sc))
            return out.reshape(1, w * C)

        return self._jit(key, body)(garr)


def _qring_pad(n: int, w: int) -> int:
    """Padded per-rank payload length for the quantized ring: bucket the
    size class, then round the per-rank chunk up to the quant block."""
    c = -(-_bucket(n) // w)
    c = -(-c // QUANT_BLOCK) * QUANT_BLOCK
    return w * c


def _qring_saved_bytes(n_padded: int, w: int, in_dtype, op) -> int:
    """Wire bytes the int8 format avoids for one quantized ring
    allreduce: 2(w-1) chunk hops of C elements each, the EXACT tier's
    wire dtype (input dtype, except f16 MEAN which accumulates f32 on
    the exact paths) vs int8 payload + one f32 scale per block."""
    if ReduceOp(op) == ReduceOp.MEAN and np.dtype(in_dtype) == np.float16:
        itemsize = 4
    else:
        itemsize = np.dtype(in_dtype).itemsize
    c = n_padded // w
    hops = 2 * max(w - 1, 0)
    exact = hops * c * itemsize
    quant = hops * (c + 4 * (c // QUANT_BLOCK))
    return max(exact - quant, 0)


class XlaGroup:
    """Single-controller device group: one process drives all devices
    ("ranks" = devices, not processes). The caller holds a stacked array
    whose leading axis is the rank axis; each op returns the per-rank
    results stacked the same way."""

    def __init__(self, group_name: str, devices=None, quantize=None):
        from jax.sharding import Mesh

        self.group_name = group_name
        self.devices = list(devices) if devices is not None else jax.devices()
        self.world_size = len(self.devices)
        self.quantize = normalize_quantize(quantize)
        self.mesh = Mesh(np.asarray(self.devices), (AXIS,))
        self._ops = _DeviceOps(self.mesh, AXIS, self.world_size)

    def _flat(self, stacked, pad_to: int | None = None, dtype=None):
        """[w, ...] -> (mesh-sharded [w, B], n, trailing shape)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jnp.asarray(stacked)
        if dtype is not None:
            x = x.astype(dtype)
        trailing = x.shape[1:]
        n = int(np.prod(trailing)) if trailing else 1
        flat = x.reshape(self.world_size, n)
        B = pad_to if pad_to is not None else _bucket(n)
        if n < B:
            flat = jnp.pad(flat, ((0, 0), (0, B - n)))
        flat = jax.device_put(flat, NamedSharding(self.mesh, P(AXIS, None)))
        return flat, n, trailing

    def allreduce(self, stacked, op: ReduceOp = ReduceOp.SUM, quantize=None):
        """stacked: [world, ...]; returns [world, ...] where every slice is
        the reduction across the leading axis."""
        op = ReduceOp(op)
        q = normalize_quantize(
            self.quantize if quantize is None else quantize)
        stacked = jnp.asarray(stacked)
        in_dt = stacked.dtype
        if (q and op in _QRING_COMBINE
                and jnp.issubdtype(in_dt, jnp.floating)):
            n = int(np.prod(stacked.shape[1:])) if stacked.ndim > 1 else 1
            flat, n, trailing = self._flat(
                stacked, pad_to=_qring_pad(n, self.world_size),
                dtype=jnp.float32)
            out = self._ops.allreduce_quantized(flat, op)
            from ray_tpu.collective import metrics as _cm

            _cm.QUANT_SAVED.inc(_qring_saved_bytes(
                flat.shape[1], self.world_size, in_dt, op))
            out = out[:, :n]
            if op == ReduceOp.MEAN:
                out = out / self.world_size
            return out.astype(in_dt).reshape(
                (self.world_size,) + trailing)
        flat, n, trailing = self._flat(stacked)
        out = self._ops.allreduce(flat, op)
        out = out[:, :n]
        if op == ReduceOp.MEAN:
            out = out / self.world_size
            out = out.astype(in_dt) if jnp.issubdtype(
                in_dt, jnp.floating) else out
        return out.reshape((self.world_size,) + trailing)

    def allgather(self, stacked):
        """[world, ...] -> [world, world, ...]: every rank sees all slices."""
        flat, n, trailing = self._flat(stacked)
        out = self._ops.allgather(flat)  # [w, w, B]
        w = self.world_size
        return out[:, :, :n].reshape((w, w) + trailing)

    def reducescatter(self, stacked, op: ReduceOp = ReduceOp.SUM,
                      quantize=None):
        """[world, world, ...] -> [world, ...]: rank r holds sum of
        stacked[:, r] (psum_scatter over the tiled flat layout)."""
        if ReduceOp(op) != ReduceOp.SUM:
            raise NotImplementedError(
                "single-controller reducescatter lowers to psum_scatter "
                "(SUM only)")
        w = self.world_size
        x = jnp.asarray(stacked)
        flat = x.reshape(w, -1)  # [w, w*T] — tiled chunks line up with
        out = self._ops.reducescatter_even(flat)   # the stacked rows
        return out.reshape((w,) + x.shape[2:])

    def shift_right(self, stacked):
        """Ring permute: rank r's slice moves to rank (r+1) % world."""
        flat, n, trailing = self._flat(stacked)
        out = self._ops.shift_right(flat)
        return out[:, :n].reshape((self.world_size,) + trailing)

    def broadcast(self, value, src_rank: int = 0):
        src = value[src_rank] if value.ndim and value.shape[0] == \
            self.world_size else value
        return jnp.broadcast_to(src, (self.world_size,) + src.shape)

    def barrier(self):
        # Device-level barrier: a trivial psum forces all ranks to sync.
        x = jnp.zeros((self.world_size, 1), jnp.float32)
        jax.block_until_ready(self.allreduce(x))

    def destroy(self):
        self._ops._cache.clear()


class DeviceTransport:
    """Transport.DEVICE: one collective RANK per PROCESS of the active
    jax.distributed runtime (parallel/multihost). Each rank's payload
    becomes one row of a [world, B] global array sharded over a
    one-device-per-process mesh; ops are the cached `_DeviceOps` bodies,
    so on TPU pods the bytes ride ICI/DCN through XLA's compiled
    collectives without touching host RAM. Serves as the data plane of
    ProcessMeshGroup (backend="xla" across actors) and as the HOST
    backend's per-op DEVICE tier (host_backend._device_route)."""

    AXIS = "proc"

    def __init__(self, world_size: int, rank: int):
        n_proc = jax.process_count()
        if world_size != n_proc:
            raise ValueError(
                f"device collective group needs one rank per joined "
                f"process: world_size={world_size} but "
                f"jax.process_count()={n_proc}")
        if rank != jax.process_index():
            raise ValueError(
                f"rank {rank} must equal jax.process_index() "
                f"{jax.process_index()} — the global runtime fixes rank "
                "order")
        self.world_size = world_size
        self.rank = rank
        by_proc: dict[int, list] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, []).append(d)
        if len(by_proc) != n_proc:
            raise ValueError(
                f"expected devices from {n_proc} processes, saw "
                f"{len(by_proc)}")
        # one device per process: the rank axis maps 1:1 onto processes
        # and a rank's row never replicates across sibling local devices
        from jax.sharding import Mesh

        devs = [by_proc[p][0] for p in sorted(by_proc)]
        self._local_dev = devs[rank]
        self.mesh = Mesh(np.asarray(devs), (self.AXIS,))
        self._ops = _DeviceOps(self.mesh, self.AXIS, world_size)
        self._dtype_ok_cache: dict = {}

    # -- plumbing -------------------------------------------------------

    def dtype_ok(self, dtype) -> bool:
        """jax must preserve the payload dtype (with x64 disabled f64/i64
        silently demote to 32-bit, which would break cross-tier
        exactness — such payloads stay on the host tiers)."""
        dtype = np.dtype(dtype)
        ok = self._dtype_ok_cache.get(dtype.str)
        if ok is None:
            try:
                ok = jnp.asarray(np.empty(0, dtype)).dtype == dtype
            except (TypeError, ValueError):
                ok = False
            self._dtype_ok_cache[dtype.str] = ok
        return ok

    def _lift(self, flat, B: int, dtype) -> jax.Array:
        """Local flat [n] payload -> this rank's [1, B] row of the
        [world, B] global array. Device-resident inputs move
        device-to-device; host arrays upload once."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jnp.asarray(flat, dtype)
        n = x.shape[0]
        if n < B:
            x = jnp.pad(x, (0, B - n))
        x = jax.device_put(x.reshape(1, B), self._local_dev)
        sharding = NamedSharding(self.mesh, P(self.AXIS, None))
        return jax.make_array_from_single_device_arrays(
            (self.world_size, B), sharding, [x])

    @staticmethod
    def _local_row(garr) -> jax.Array:
        """This process's row of a P(proc, ...) sharded output."""
        return garr.addressable_shards[0].data[0]

    @staticmethod
    def _is_np_in(arr) -> bool:
        from ray_tpu.collective.types import is_jax_array

        return not is_jax_array(arr)

    @staticmethod
    def _deliver(x, np_out: bool):
        return np.asarray(x) if np_out else x

    def _counted(self):
        from ray_tpu.collective import metrics as _cm

        _cm.DEVICE_OPS.inc()

    # -- op surface (mirrors host_backend semantics) --------------------

    def allreduce(self, arr, op: ReduceOp = ReduceOp.SUM, quantize=None):
        op = ReduceOp(op)
        q = normalize_quantize(quantize)
        np_in = self._is_np_in(arr)
        in_dt = np.dtype(arr.dtype)
        shape, n = tuple(arr.shape), int(arr.size)
        floating = np.issubdtype(in_dt, np.floating)
        flat = arr.reshape(-1)
        self._counted()
        if q and floating and op in _QRING_COMBINE:
            return self._allreduce_quantized(flat, n, shape, in_dt, op,
                                             np_in)
        if op == ReduceOp.MEAN and not floating:
            # hub semantics: integer MEAN promotes to float64 — the exact
            # integer SUM runs on device, the division on the host (f64
            # doesn't exist on device with x64 off, so promotion leaves
            # the device plane by definition)
            total = np.asarray(
                self.allreduce(arr, ReduceOp.SUM), np.float64)
            return total / self.world_size
        work_dt = in_dt
        if op == ReduceOp.MEAN and in_dt == np.float16:
            work_dt = np.dtype(np.float32)  # f32 accumulate, f16 out
        garr = self._lift(flat, _bucket(n), work_dt)
        row = self._local_row(self._ops.allreduce(garr, op))[:n]
        if op == ReduceOp.MEAN:
            row = (row / self.world_size).astype(in_dt)
        return self._deliver(row.reshape(shape), np_in)

    def _allreduce_quantized(self, flat, n, shape, in_dt, op, np_in):
        from ray_tpu._private import failpoints as _fp

        if _fp.ARMED:
            _fp.fire_strict("collective.quantize")
        w = self.world_size
        padded = _qring_pad(n, w)
        garr = self._lift(flat, padded, np.dtype(np.float32))
        row = self._local_row(self._ops.allreduce_quantized(garr, op))[:n]
        from ray_tpu.collective import metrics as _cm

        _cm.QUANT_SAVED.inc(_qring_saved_bytes(padded, w, in_dt, op))
        if op == ReduceOp.MEAN:
            row = row / w
        return self._deliver(row.astype(in_dt).reshape(shape), np_in)

    def reduce(self, arr, dst_rank: int = 0,
               op: ReduceOp = ReduceOp.SUM, quantize=None):
        out = self.allreduce(arr, op, quantize=quantize)
        return out if self.rank == dst_rank else arr

    def broadcast(self, arr, src_rank: int = 0):
        np_in = self._is_np_in(arr)
        in_dt = np.dtype(arr.dtype)
        shape, n = tuple(arr.shape), int(arr.size)
        self._counted()
        garr = self._lift(arr.reshape(-1), _bucket(n), in_dt)
        row = self._local_row(self._ops.broadcast(garr, src_rank))[:n]
        return self._deliver(row.reshape(shape), np_in)

    def allgather(self, arr) -> list:
        np_in = self._is_np_in(arr)
        shape, n = tuple(arr.shape), int(arr.size)
        self._counted()
        garr = self._lift(arr.reshape(-1), _bucket(n), np.dtype(arr.dtype))
        local = self._local_row(self._ops.allgather(garr))  # [w, B]
        return [self._deliver(local[i, :n].reshape(shape), np_in)
                for i in range(self.world_size)]

    def reducescatter(self, arr, op: ReduceOp = ReduceOp.SUM,
                      quantize=None):
        # hub semantics: reduce, then np.array_split along axis 0
        from ray_tpu.collective.backends.shm_transport import split_bounds

        op = ReduceOp(op)
        np_in = self._is_np_in(arr)
        w = self.world_size
        rows = arr.shape[0] if arr.ndim else 1
        rb = split_bounds(rows, w)
        if (op == ReduceOp.SUM and arr.ndim and rows and rows % w == 0
                and not normalize_quantize(quantize)):
            # even split: one psum_scatter moves 1/w of the bytes an
            # allreduce would
            self._counted()
            n = int(arr.size)
            garr = self._lift(arr.reshape(-1), n, np.dtype(arr.dtype))
            mine = self._local_row(self._ops.reducescatter_even(garr))
            return self._deliver(
                mine.reshape((rows // w,) + tuple(arr.shape[1:])), np_in)
        total = self.allreduce(arr, op, quantize=quantize)
        return total[rb[self.rank]:rb[self.rank + 1]]

    def barrier(self):
        np.asarray(self.allreduce(np.zeros(1, np.float32)))

    def send(self, arr, dst_rank: int, tag: int = 0):
        raise NotImplementedError(
            "point-to-point ops are HOST-backend only; the device mesh "
            "expresses transfers as collectives")

    recv = send

    def destroy(self):
        self._ops._cache.clear()


class ProcessMeshGroup:
    """Backend.XLA across actor PROCESSES (the former
    xla_global.GlobalMeshGroup): N actors joined one jax.distributed
    runtime are one collective rank each; every op delegates to the
    shared DeviceTransport over the global mesh, so cross-host traffic
    is XLA's compiled collectives (ICI/DCN), never the HOST TCP hub."""

    def __init__(self, group_name: str, world_size: int, rank: int,
                 quantize=None):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self.quantize = normalize_quantize(quantize)
        self.transport = DeviceTransport(world_size, rank)
        self.mesh = self.transport.mesh

    def _q(self, quantize):
        return self.quantize if quantize is None else quantize

    def allreduce(self, arr, op: ReduceOp = ReduceOp.SUM, quantize=None):
        return self.transport.allreduce(arr, op, quantize=self._q(quantize))

    def reduce(self, arr, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM,
               quantize=None):
        return self.transport.reduce(arr, dst_rank, op,
                                     quantize=self._q(quantize))

    def broadcast(self, arr, src_rank: int = 0):
        return self.transport.broadcast(arr, src_rank)

    def allgather(self, arr) -> list:
        return self.transport.allgather(arr)

    def reducescatter(self, arr, op: ReduceOp = ReduceOp.SUM,
                      quantize=None):
        return self.transport.reducescatter(arr, op,
                                            quantize=self._q(quantize))

    def barrier(self):
        self.transport.barrier()

    def send(self, arr, dst_rank: int, tag: int = 0):
        raise NotImplementedError(
            "point-to-point ops are HOST-backend only; the global mesh "
            "expresses transfers as collectives")

    recv = send

    def destroy(self):
        self.transport.destroy()


# continuity alias: the global-mesh group used to live in xla_global.py
GlobalMeshGroup = ProcessMeshGroup
