"""HOST backend: cross-process CPU collectives with a tiered data plane.

The gloo-equivalent of the reference's collective backends (reference:
python/ray/util/collective/collective_group/ — NCCLGroup :115 and the MPI
stub). Rendezvous goes through the GCS KV (the reference used a named
"Info" actor, util.py) — rank 0 binds a TCP hub, publishes its address
under `collective/<group>`, and every other rank connects.

Four transports, selected per op by payload placement, size and node
placement:

device — the accelerator plane: when every rank's payload is a
        jax.Array and the group's processes share one jax.distributed
        runtime (parallel/multihost), the op dispatches through
        xla_backend.DeviceTransport — cached jitted shard_map
        collectives over a one-device-per-process mesh — so bytes ride
        ICI/XLA and never touch host RAM. The vote is per op and
        unanimous (a 1-byte kind-tagged hub ctl round, like the shm
        ok-flag exchange); any rank holding a host array vetoes and the
        op falls back to the tiers below.
hub   — star topology, all contributions through rank 0's socket +
        shared op table. Latency-optimal for control-sized tensors
        (metrics, barriers, rendezvous); carries every op kind.
ring  — direct rank-to-rank TCP ring for large tensors: reduce-scatter
        + allgather schedules for allreduce/reducescatter, block
        rotation for allgather, a pipelined relay chain for broadcast.
        Steps are chunk-pipelined (the reduce of chunk k overlaps the
        receive of chunk k+1) and zero-copy (memoryview slices of the
        work buffer go straight to sendall; recv_into fills scratch or
        the destination — no tobytes per step). The unpipelined ring
        allreduce is preserved verbatim as `ring_unpipelined`, the
        control arm of the perf A/B. With `quantize="int8"` the
        allreduce wire format becomes block-scaled int8 (EQuARX-style:
        per-QUANT_BLOCK f32 scales ride ahead of each chunk's int8
        payload, the reduce runs on dequantized float32) — ~4x fewer
        socket bytes for float32 gradients.
shm   — ranks that rendezvous on the same node map one tmpfs segment
        (native/store segment alloc) and collectives become pure memory
        traffic: write slot, counter-barrier, reduce a 1/w stripe,
        read result — zero socket syscalls, zero serialization
        (shm_transport.py).

Every tier keeps the abort-not-hang contract: a dead peer turns into a
TimeoutError within the group timeout on every survivor (hub per-op
timeouts, ring socket timeouts + teardown, shm barrier deadline + abort
word, device vote round bounded by the hub deadline — a rank that dies
inside an in-flight XLA collective is bounded by the device runtime's
own failure detection), so the SGD layer above can resize the group.
"""

from __future__ import annotations

import functools
import logging
import os
import socket
import struct
import threading
import time

import msgpack
import numpy as np

from ray_tpu._private import failpoints as _fp
from ray_tpu.collective.types import (_NUMPY_REDUCE, QUANT_BLOCK, ReduceOp,
                                      Transport, normalize_quantize)

logger = logging.getLogger(__name__)

_HDR = struct.Struct(">I")


def _op_entry(name: str):
    """Wrap a public collective op: tracks (op, phase, age) in the
    group's debug row — the `ray-tpu state collectives` / stall-doctor
    feed — and makes group-timeout hangs self-describing by attaching a
    bounded state snapshot to the raised TimeoutError (it travels inside
    pickled rpc error replies via the exception __dict__, so the driver
    sees WHICH op wedged on which rank without a reproduction run)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            dbg = self._dbg
            dbg["op"] = name
            dbg["phase"] = "route"
            dbg["t0"] = time.monotonic()
            try:
                return fn(self, *args, **kwargs)
            except TimeoutError as e:
                if not hasattr(e, "state_snapshot"):
                    from ray_tpu._private import debug_state as _ds

                    try:
                        e.state_snapshot = _ds.bounded(self.debug_state())
                    except Exception:
                        pass
                raise
            finally:
                dbg["ops_done"] = dbg.get("ops_done", 0) + 1
                dbg["op"] = None
                dbg["phase"] = "idle"
        return wrapper
    return deco

# ops the int8 block-scaled wire format can carry (the reduce happens on
# dequantized float32; PRODUCT would compound the per-hop error
# multiplicatively, so it stays exact)
_QUANT_OPS = (ReduceOp.SUM, ReduceOp.MEAN, ReduceOp.MAX, ReduceOp.MIN)


def _quant_np(x: np.ndarray):
    """Block-scaled symmetric int8 (numpy twin of
    xla_backend.quantize_blocks — same block size and scale rule, so the
    host-ring and device-ring formats agree, and so does the analytic
    error bound): flat float32 [n] (n % QUANT_BLOCK == 0) ->
    (int8 [n], float32 scales [n // QUANT_BLOCK])."""
    b = x.reshape(-1, QUANT_BLOCK)
    absmax = np.max(np.abs(b), axis=1)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(b / scale[:, None]), -127, 127).astype(np.int8)
    return q.reshape(-1), scale


def _dequant_np(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    return (q.reshape(-1, QUANT_BLOCK).astype(np.float32)
            * scale[:, None]).reshape(-1)


def _send_msg(sock: socket.socket, header: dict, payload: bytes = b""):
    h = msgpack.packb(header, use_bin_type=True)
    sock.sendall(_HDR.pack(len(h)) + h + _HDR.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("collective peer disconnected")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (hlen,) = _HDR.unpack(_recv_exact(sock, 4))
    header = msgpack.unpackb(_recv_exact(sock, hlen), raw=False)
    (plen,) = _HDR.unpack(_recv_exact(sock, 4))
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


def _arr_meta(arr: np.ndarray) -> dict:
    return {"dtype": arr.dtype.str, "shape": list(arr.shape)}


def _arr_from(meta: dict, payload: bytes) -> np.ndarray:
    return np.frombuffer(payload, dtype=np.dtype(meta["dtype"])).reshape(
        meta["shape"]).copy()


def _reduce(arrays: list[np.ndarray], op: ReduceOp) -> np.ndarray:
    if op == ReduceOp.MEAN:
        return np.mean(np.stack(arrays), axis=0)
    ufunc = getattr(np, _NUMPY_REDUCE[ReduceOp(op)])
    out = arrays[0].copy()
    for arr in arrays[1:]:
        out = ufunc(out, arr)
    return out


class _CollectiveState:
    """Hub-side shared op table. contribute() blocks until the op's result
    is ready; the last contributor computes it."""

    def __init__(self, world_size: int, sweep_timeout: float = 600.0):
        self.world_size = world_size
        self.sweep_timeout = sweep_timeout
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.ops: dict[int, dict] = {}
        self.mailboxes: dict[tuple[int, int, int], tuple[dict, bytes]] = {}

    def _sweep_locked(self):
        """Completed-but-unread ops leak when a rank dies after
        contributing but before reading (e.g. rank 0 interrupted inside
        its local contribute — its arrival completes the op later, but
        its reader slot never fills, so `readers` can't reach
        world_size). Drop done ops past the sweep deadline, mirroring
        the timeout-withdraw path for incomplete ones."""
        now = time.monotonic()
        dead = [op_id for op_id, op in self.ops.items()
                if op.get("done")
                and now - op.get("done_at", now) > self.sweep_timeout]
        for op_id in dead:
            del self.ops[op_id]

    def contribute(self, op_id: int, kind: str, rank: int, meta: dict,
                   payload: bytes, timeout: float = 300.0):
        with self.cv:
            self._sweep_locked()
            op = self.ops.setdefault(op_id, {"arrivals": {}, "result": None,
                                             "done": False})
            op["arrivals"][rank] = (kind, meta, payload)
            if len(op["arrivals"]) == self.world_size:
                try:
                    op["result"] = self._compute(kind, op["arrivals"])
                except Exception as e:  # mismatched kinds/dtypes: surface
                    op["error"] = str(e)  # to every rank, don't hang them
                op["done"] = True
                op["done_at"] = time.monotonic()
                self.cv.notify_all()
            else:
                deadline = time.monotonic() + timeout
                while not op["done"]:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        # Withdraw this rank's contribution so a late
                        # straggler can't complete the op with data the
                        # timed-out ranks already abandoned (silent
                        # divergence); last withdrawer frees the op.
                        op["arrivals"].pop(rank, None)
                        if not op["arrivals"]:
                            self.ops.pop(op_id, None)
                        raise TimeoutError(
                            f"collective op {op_id} ({kind}) timed out: "
                            f"{len(op['arrivals'])}/{self.world_size} arrived")
                    self.cv.wait(remaining)
            result = op["result"]
            err = op.get("error")
            # last reader cleans up (pop: the sweep may have beaten us)
            op.setdefault("readers", set()).add(rank)
            if len(op["readers"]) == self.world_size:
                self.ops.pop(op_id, None)
        if err is not None:
            raise ValueError(f"collective op {op_id} failed: {err}")
        return result

    def _compute(self, kind: str, arrivals: dict):
        ranks = sorted(arrivals)
        kinds = {arrivals[r][0] for r in ranks}
        if len(kinds) != 1:  # not an assert: must survive python -O —
            # this is the loud-failure net for route divergence
            raise ValueError(f"mismatched collective kinds: {kinds}")
        metas = {r: arrivals[r][1] for r in ranks}
        payloads = {r: arrivals[r][2] for r in ranks}
        if kind == "barrier":
            return {"kind": "barrier"}
        if kind == "broadcast":
            src = metas[ranks[0]]["src"]
            return {"kind": "bcast", "meta": metas[src],
                    "payload": payloads[src]}
        if kind in ("allreduce", "reduce"):
            op = ReduceOp(metas[ranks[0]]["op"])
            arrays = [_arr_from(metas[r], payloads[r]) for r in ranks]
            out = _reduce(arrays, op)
            return {"kind": kind, "meta": _arr_meta(out),
                    "payload": out.tobytes(),
                    "dst": metas[ranks[0]].get("dst", -1)}
        if kind in ("allgather", "allgather_ctl_shm",
                    "allgather_ctl_ring", "allgather_ctl_device",
                    "allgather_ctl_pallas"):
            # ctl kinds: transport-plumbing exchanges (ring addresses,
            # shm ok flags), one kind EACH so a rank whose ROUTE diverged
            # (ragged sizes straddling RING_MIN_BYTES) pairs with a real
            # allgather as a kind mismatch — a loud ValueError on every
            # rank, never a silent payload swap.
            return {"kind": "allgather",
                    "metas": [metas[r] for r in ranks],
                    "payloads": [payloads[r] for r in ranks]}
        if kind == "allgather_meta":
            # metadata-only control round for the ring data plane: a rank
            # that routed the payload to the ring must never pair with a
            # payload-carrying hub allgather (kind mismatch asserts above)
            return {"kind": "allgather",
                    "metas": [metas[r] for r in ranks],
                    "payloads": [b"" for _ in ranks]}
        if kind == "reducescatter":
            op = ReduceOp(metas[ranks[0]]["op"])
            arrays = [_arr_from(metas[r], payloads[r]) for r in ranks]
            out = _reduce(arrays, op)
            chunks = np.array_split(out, len(ranks), axis=0)
            return {"kind": "reducescatter",
                    "metas": [_arr_meta(c) for c in chunks],
                    "payloads": [np.ascontiguousarray(c).tobytes()
                                 for c in chunks]}
        raise ValueError(f"unknown collective kind {kind!r}")

    # p2p
    def post(self, src: int, dst: int, tag: int, meta: dict, payload: bytes):
        with self.cv:
            self.mailboxes[(src, dst, tag)] = (meta, payload)
            self.cv.notify_all()

    def take(self, src: int, dst: int, tag: int, timeout: float = 300.0):
        deadline = time.monotonic() + timeout
        with self.cv:
            while (src, dst, tag) not in self.mailboxes:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"recv from {src} tag {tag} timed out")
                self.cv.wait(remaining)
            return self.mailboxes.pop((src, dst, tag))


class HostGroup:
    def __init__(self, group_name: str, world_size: int, rank: int,
                 timeout: float = 60.0, transport: str = "auto",
                 quantize=None, placement_plan: dict | None = None):
        from ray_tpu.experimental import internal_kv

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        # live-op debug row (debug_state.py; _op_entry maintains it)
        self._dbg: dict = {"op": None, "phase": "idle", "t0": 0.0,
                           "ops_done": 0}
        # Rendezvous AND per-op timeout: ops abort (not hang) when a peer
        # dies mid-collective, so the SGD layer can resize the group.
        self._timeout = timeout
        self._op_id = 0
        self._key = f"collective/{group_name}"
        self._sock: socket.socket | None = None
        self._destroyed = False
        # Data-plane state: force_transport pins every op to one tier
        # (tests/benchmarks); "auto" routes by size and node placement.
        tr = Transport(transport)
        self.force_transport = None if tr == Transport.AUTO else tr.value
        # Placement-derived tier (topology.transport_plan riding the
        # gang's ICI_RING record): pins the transport WITHOUT the probe
        # rounds the auto router pays (shm ok-flag exchange on non-shm
        # groups, device vote). Explicit transport= wins over the plan.
        self._transport_derived = False
        self._placement_plan = placement_plan
        self._probe_rounds = 0  # auto-router discovery rounds paid
        if (placement_plan and placement_plan.get("transport")
                and self.force_transport is None):
            self.force_transport = Transport(
                placement_plan["transport"]).value
            self._transport_derived = True
            from ray_tpu.collective import metrics as _metrics

            _metrics.TRANSPORT_DERIVED.inc()
        # Group-default wire quantization (per-op quantize= overrides)
        self.quantize = normalize_quantize(quantize)
        # DEVICE tier state: built lazily on the first unanimous vote;
        # _device_shaped is the group-uniform round-entry gate, decided
        # ONCE at construction (ranks create the group at the same
        # protocol step, so the multihost-runtime facts they read here
        # agree by contract — a lazy read could catch ranks on opposite
        # sides of a late multihost.initialize); _device_disabled is
        # this rank's veto after a device failure
        self._device = None
        self._device_disabled = False
        self._device_shaped: bool = self._compute_device_shaped()
        # PALLAS (fused-kernel) tier state: same construction-time shape
        # gate as the device tier; _pallas_disabled is this rank's veto
        # after a kernel failure (the device tier stays routable — the
        # planes fail independently)
        self._pallas = None
        self._pallas_disabled = False
        self._shm = None
        self._shm_gen = 0
        self._shm_disabled = False
        self._shm_keys: list[str] = []
        # buffered peer-direct sends awaiting their receiver, ONE per
        # (dst, tag): a re-send overwrites the unclaimed predecessor
        # (hub-mailbox semantics — keeps loop-sends to a wedged receiver
        # from pinning unbounded snapshots/fds); destroy() reaps the rest
        self._p2p_direct: dict[tuple[int, int], socket.socket] = {}
        self._p2p_lock = threading.Lock()
        if world_size == 1:
            self._state = _CollectiveState(1, sweep_timeout=timeout * 2)
            return
        if rank == 0:
            self._state = _CollectiveState(world_size,
                                           sweep_timeout=timeout * 2)
            self._listener = socket.socket()
            self._listener.bind(("127.0.0.1", 0))
            self._listener.listen(world_size)
            port = self._listener.getsockname()[1]
            # group metadata rides the rendezvous KV entry: a derived
            # tier (and its per-rank placement rows) is visible to every
            # joining rank, so an ad-hoc member initialized WITHOUT the
            # plan (probe fallback path) still adopts the gang's tier
            internal_kv._kv_put(
                self._key,
                msgpack.packb({"addr": f"127.0.0.1:{port}",
                               "world_size": world_size,
                               "transport": (self.force_transport
                                             if self._transport_derived
                                             else None),
                               "plan": self._placement_plan}))
            self._conn_threads = []
            accept_thread = threading.Thread(target=self._accept_loop,
                                             daemon=True)
            accept_thread.start()
        else:
            deadline = time.monotonic() + timeout
            info = None
            while time.monotonic() < deadline:
                data = internal_kv._kv_get(self._key)
                if data:
                    info = msgpack.unpackb(data, raw=False)
                    break
                time.sleep(0.05)
            if info is None:
                raise TimeoutError(
                    f"rendezvous for group {group_name!r} timed out")
            if info["world_size"] != world_size:
                raise ValueError("world_size mismatch at rendezvous")
            if (info.get("transport") and self.force_transport is None
                    and not self._transport_derived):
                # adopt the leader's placement-derived tier from the KV
                # metadata (this rank joined without the plan)
                self.force_transport = Transport(info["transport"]).value
                self._transport_derived = True
                self._placement_plan = info.get("plan")
                from ray_tpu.collective import metrics as _metrics

                _metrics.TRANSPORT_DERIVED.inc()
            host, port = info["addr"].rsplit(":", 1)
            self._sock = socket.create_connection((host, int(port)),
                                                  timeout=timeout)
            self._sock.settimeout(None)
            _send_msg(self._sock, {"hello": rank})

    # ---- hub side ----
    def _accept_loop(self):
        joined = 0
        while joined < self.world_size - 1:
            conn, _ = self._listener.accept()
            hello, _ = _recv_msg(conn)
            t = threading.Thread(target=self._serve_conn,
                                 args=(conn, hello["hello"]), daemon=True)
            t.start()
            self._conn_threads.append(t)
            joined += 1

    def _serve_conn(self, conn: socket.socket, peer_rank: int):
        try:
            while True:
                header, payload = _recv_msg(conn)
                kind = header["kind"]
                if kind == "p2p_send":
                    self._state.post(peer_rank, header["dst"], header["tag"],
                                     header["meta"], payload)
                    _send_msg(conn, {"ok": True})
                elif kind == "p2p_recv":
                    try:
                        meta, data = self._state.take(
                            header["src"], peer_rank, header["tag"],
                            timeout=self._timeout)
                    except TimeoutError as e:
                        # TimeoutError is an OSError: without this reply
                        # the outer except would eat it and the client
                        # would block forever on a reply that never comes
                        _send_msg(conn, {"error": str(e), "timeout": True})
                        continue
                    _send_msg(conn, {"meta": meta}, data)
                else:
                    try:
                        result = self._state.contribute(
                            header["op_id"], kind, peer_rank, header["meta"],
                            payload, timeout=self._timeout)
                    except Exception as e:
                        _send_msg(conn, {
                            "error": str(e),
                            "timeout": isinstance(e, TimeoutError)})
                        continue
                    reply, data = self._slice_result(result, peer_rank, kind)
                    _send_msg(conn, reply, data)
        except (ConnectionError, OSError):
            pass

    @staticmethod
    def _slice_result(result: dict, rank: int, kind: str):
        if result["kind"] == "barrier":
            return {"barrier": True}, b""
        if result["kind"] in ("bcast", "allreduce"):
            return {"meta": result["meta"]}, result["payload"]
        if result["kind"] == "reduce":
            if rank == result["dst"]:
                return {"meta": result["meta"]}, result["payload"]
            return {"meta": None}, b""
        if result["kind"] == "allgather":
            return ({"metas": result["metas"],
                     "sizes": [len(p) for p in result["payloads"]]},
                    b"".join(result["payloads"]))
        if result["kind"] == "reducescatter":
            return {"meta": result["metas"][rank]}, result["payloads"][rank]
        raise ValueError(result["kind"])

    # ---- participant ----
    def _next_op(self) -> int:
        self._op_id += 1
        return self._op_id

    def debug_state(self) -> dict:
        """Msgpack-safe live row: which op this rank is inside, at which
        transport phase, for how long (the stall doctor's collective
        feed; also attached to group-timeout errors by _op_entry)."""
        dbg = self._dbg
        op = dbg.get("op")
        return {
            "group": self.group_name,
            "rank": self.rank,
            "world_size": self.world_size,
            "backend": "host",
            "transport": self._forced() or "auto",
            "transport_derived": self._transport_derived,
            "probe_rounds": self._probe_rounds,
            "quantize": self.quantize or "",
            "op": op or "",
            "phase": dbg.get("phase", "idle"),
            "age_s": (round(time.monotonic() - dbg["t0"], 3)
                      if op else 0.0),
            "ops_done": dbg.get("ops_done", 0),
            "op_seq": self._op_id,
            "timeout_s": self._timeout,
        }

    def _collective(self, kind: str, meta: dict, payload: bytes):
        self._dbg["phase"] = f"hub:{kind}"
        op_id = self._next_op()
        if self.rank == 0 or self.world_size == 1:
            result = self._state.contribute(op_id, kind, 0, meta, payload,
                                            timeout=self._timeout)
            return self._slice_result(result, 0, kind)
        _send_msg(self._sock, {"kind": kind, "op_id": op_id, "meta": meta},
                  payload)
        reply, data = _recv_msg(self._sock)
        if "error" in reply:
            if reply.get("timeout", True):
                raise TimeoutError(reply["error"])
            raise ValueError(reply["error"])
        return reply, data

    def _hub_allgather(self, arr: np.ndarray,
                       kind: str = "allgather") -> list[np.ndarray]:
        reply, data = self._collective(kind, _arr_meta(arr),
                                       arr.tobytes())
        out, offset = [], 0
        for m, size in zip(reply["metas"], reply["sizes"]):
            out.append(_arr_from(m, data[offset:offset + size]))
            offset += size
        return out

    def _hub_allgather_meta(self, arr: np.ndarray) -> list[dict]:
        """Metadata-only allgather (control round for the ring plane)."""
        reply, _ = self._collective("allgather_meta", _arr_meta(arr), b"")
        return reply["metas"]

    # ---- transport routing ----
    # The hub is latency-optimal for control-sized tensors but serializes
    # all-to-hub bandwidth through one socket — wrong for gradients
    # (reference role: gloo's ring algorithms behind torch.distributed).
    # Large tensors take the shm segment when the whole group shares a
    # node, else the direct rank-to-rank TCP ring.

    RING_MIN_BYTES = 1 << 16
    _PIPE_BYTES = 1 << 18  # ring pipeline slice: reduce(k) overlaps recv(k+1)
    # PALLAS tier size ceiling: only small latency-critical ops (the
    # decode-step allreduce regime) take the fused kernel; larger
    # payloads fall through to DEVICE, whose shard_map pipeline is the
    # bandwidth shape. Group-uniform by the collective contract (same-
    # geometry payloads; ragged allgather is caught by the meta round).
    PALLAS_MAX_BYTES = int(os.environ.get(
        "RAY_TPU_COLLECTIVE_PALLAS_MAX_KB", "64")) << 10
    # Segments grow by rebuild but never shrink, so one oversize op would
    # pin (w+2)*slot of tmpfs for the group's life; above the cap the
    # ring carries the op with no resident cost. Forced shm overrides.
    SHM_MAX_SLOT_BYTES = int(os.environ.get(
        "RAY_TPU_COLLECTIVE_SHM_MAX_MB", "32")) << 20

    def _forced(self) -> str | None:
        f = self.force_transport or os.environ.get(
            "RAY_TPU_COLLECTIVE_TRANSPORT", "")
        f = (f or "").strip().lower()
        if not f or f == Transport.AUTO.value:
            return None
        return Transport(f).value  # validates the name

    def _route(self, arr: np.ndarray) -> list[str]:
        """Ordered transport candidates for one op. All ranks compute the
        same route (collectives pass same-geometry tensors by contract;
        ragged allgather is caught by the allgather_meta control round)."""
        f = self._forced()
        if f:
            return [f]
        if (self._destroyed or self.world_size == 1 or arr.ndim == 0
                or arr.ndim > 24 or arr.nbytes < self.RING_MIN_BYTES):
            return [Transport.HUB.value]
        tiers = []
        if (not self._shm_disabled
                and arr.nbytes <= self.SHM_MAX_SLOT_BYTES):
            tiers.append(Transport.SHM.value)
        if self.world_size > 2:  # 2-rank ring degenerates to pairwise
            tiers.append(Transport.RING.value)
        tiers.append(Transport.HUB.value)
        return tiers

    def _forced_unavailable(self, tr: str):
        if self._forced() == tr:
            raise RuntimeError(
                f"forced collective transport {tr!r} is unavailable for "
                f"group {self.group_name!r} (world={self.world_size})")

    def _demote_derived(self) -> None:
        """A placement-DERIVED pin (not user-forced) turned out
        unbuildable on this rank's runtime: fall back to auto routing.
        Only called at group-uniform decision points (device shape
        check, post-allgather vote result, the shm ok-flag exchange),
        so every rank demotes in the same op and the routes stay
        aligned."""
        logger.warning(
            "group %s: placement-derived transport %r unavailable; "
            "demoting to auto routing", self.group_name,
            self.force_transport)
        self.force_transport = None
        self._transport_derived = False

    def _tier_unavailable(self, tr: str) -> bool:
        """A routed tier could not be built. A placement-derived pin is
        SOFT: demote to auto routing and tell the caller to re-route
        (returns True). A user-forced pin raises."""
        if self._transport_derived and self.force_transport == tr:
            self._demote_derived()
            return True
        self._forced_unavailable(tr)
        return False

    @staticmethod
    def _abort_not_hang(e: Exception):
        """Normalize transport failures: a dead/stalled peer surfaces as
        TimeoutError on every survivor (the contract the SGD resize path
        keys on); programmer errors (dtype/shape mismatch) pass through."""
        if isinstance(e, (ConnectionError, OSError)) and not isinstance(
                e, TimeoutError):
            raise TimeoutError(f"collective aborted: {e}") from e
        raise e

    # ---- device (ICI/XLA) data plane ----

    @staticmethod
    def _is_device_array(arr) -> bool:
        from ray_tpu.collective.types import is_jax_array

        return is_jax_array(arr)

    def _to_host(self, arr) -> np.ndarray:
        if not isinstance(arr, np.ndarray):
            arr = np.asarray(arr)  # device arrays fall back to host here
        return np.ascontiguousarray(arr)

    def _quantize_mode(self, quantize):
        """Per-op override (False forces exact) else the group default."""
        return (self.quantize if quantize is None
                else normalize_quantize(quantize))

    def _compute_device_shaped(self) -> bool:
        """Whether this GROUP enters the per-op device vote round. Only
        stable, group-uniform facts are read — the multihost runtime
        being active and sized to the group is the same on every rank
        at group creation by contract, so every rank enters (or skips)
        the ctl round together. Volatile, rank-local facts
        (rank/process_index alignment, a one-sided device failure)
        express themselves as a 0 VOTE inside the round instead, so
        they degrade to a clean host-tier fallback rather than a
        ctl-kind mismatch. Free for plain host groups — the multihost
        flag check short-circuits before jax is touched."""
        if self.world_size <= 1:
            return False
        try:
            from ray_tpu.parallel import multihost

            if not multihost.is_initialized():
                return False
            import jax

            return jax.process_count() == self.world_size
        except Exception:
            return False

    def _device_group_shaped(self) -> bool:
        return bool(self._device_shaped) and not self._destroyed

    def _ensure_device(self):
        if self._device is None:
            from ray_tpu.collective.backends.xla_backend import (
                DeviceTransport)

            # raises when rank != process_index — surfaces as a 0 vote
            self._device = DeviceTransport(self.world_size, self.rank)
        return self._device

    def _device_route(self, arr) -> bool:
        """Per-op DEVICE-tier agreement. True when EVERY rank voted
        device (its payload is a jax.Array of a device-safe dtype, or
        the tier is forced). The vote rides a 1-byte hub ctl round with
        its own kind tag — like the shm ok-flag exchange — so a rank
        whose route diverged pairs as a loud kind mismatch, never a
        silent payload swap. Only multihost-shaped groups pay the
        round; any host-array (or device-incapable) rank vetoes and
        every rank falls back together."""
        forced = self._forced()
        # a PALLAS pin is a refinement of the device plane: ops above
        # pallas_max_bytes and op kinds the kernel tier does not carry
        # fall through HERE, so the pin behaves like a device pin for
        # them instead of raising
        device_like = (Transport.DEVICE.value, Transport.PALLAS.value)
        if forced is not None and forced not in device_like:
            return False
        if not self._device_group_shaped():
            if forced in device_like:
                # the shape gate is decided once at construction and is
                # group-uniform by contract, so a derived-pin demotion
                # here happens on every rank together
                self._tier_unavailable(forced)
            return False
        self._dbg["phase"] = "device_vote"
        self._probe_rounds += 1
        if _fp.ARMED:
            # fires BEFORE the agreement round: a rank hard-killed here
            # leaves every survivor timing out in the hub exchange
            # (abort-not-hang). Once ranks enter the XLA dispatch the op
            # inherits the device runtime's own failure detection.
            _fp.fire_strict("collective.device_dispatch")
        vote = 0
        if not self._device_disabled and (
                forced in device_like
                or self._is_device_array(arr)):
            try:
                dev = self._ensure_device()
                vote = 1 if dev.dtype_ok(arr.dtype) else 0
            except Exception:
                self._device_disabled = True
        flags = self._hub_allgather(np.array([vote], np.uint8),
                                    kind="allgather_ctl_device")
        agreed = all(int(f[0]) for f in flags)
        if not agreed and forced == Transport.DEVICE.value:
            if self._transport_derived:
                # the vote result is an allgather — identical on every
                # rank, so a derived pin demotes in unison here
                self._demote_derived()
                return False
            raise RuntimeError(
                f"forced collective transport 'device' is unavailable "
                f"for group {self.group_name!r}: the placement/dtype "
                f"vote was not unanimous")
        return agreed

    def _device_op(self, fn):
        from ray_tpu.collective import metrics  # noqa: F401 (register)

        self._dbg["phase"] = "device"
        try:
            return fn()
        except Exception as e:
            # a failed/interrupted device op leaves the runtime's
            # collective state unknown: stop routing this group to the
            # device plane and surface abort-not-hang semantics
            self._device_disabled = True
            self._abort_not_hang(e)

    def _ensure_pallas(self):
        if self._pallas is None:
            from ray_tpu.collective.backends.pallas_backend import (
                PallasTransport)

            # raises when rank != process_index — surfaces as a 0 vote
            self._pallas = PallasTransport(self.world_size, self.rank)
        return self._pallas

    def _pallas_route(self, arr) -> bool:
        """Per-op PALLAS-tier agreement, mirroring _device_route: a
        1-byte hub ctl round with its own kind tag decides whether
        EVERY rank runs the fused kernel. Ops above PALLAS_MAX_BYTES
        skip the round entirely and fall through to _device_route —
        the threshold reads only the local payload size, which is
        group-uniform for collectives by contract, so every rank skips
        (or votes) together."""
        forced = self._forced()
        if forced is not None and forced != Transport.PALLAS.value:
            return False
        if not self._device_group_shaped():
            if forced == Transport.PALLAS.value:
                self._tier_unavailable(forced)
            return False
        if getattr(arr, "nbytes", 0) > self.PALLAS_MAX_BYTES:
            # large ops fall through to the DEVICE tier (a forced
            # pallas pin is device-like there), keeping the kernel
            # tier on the latency-critical small-op path it was built
            # for
            return False
        self._dbg["phase"] = "pallas_vote"
        self._probe_rounds += 1
        if _fp.ARMED:
            # fires BEFORE the agreement round, like
            # collective.device_dispatch: a rank hard-killed here
            # leaves every survivor timing out in the hub exchange
            # (abort-not-hang)
            _fp.fire_strict("collective.pallas_dispatch")
        vote = 0
        if not self._pallas_disabled and (
                forced == Transport.PALLAS.value
                or self._is_device_array(arr)):
            try:
                pal = self._ensure_pallas()
                vote = 1 if pal.dtype_ok(arr.dtype) else 0
            except Exception:
                self._pallas_disabled = True
        flags = self._hub_allgather(np.array([vote], np.uint8),
                                    kind="allgather_ctl_pallas")
        agreed = all(int(f[0]) for f in flags)
        if not agreed and forced == Transport.PALLAS.value:
            if self._transport_derived:
                # the vote result is an allgather — identical on every
                # rank, so a derived pin demotes in unison here
                self._demote_derived()
                return False
            raise RuntimeError(
                f"forced collective transport 'pallas' is unavailable "
                f"for group {self.group_name!r}: the placement/dtype "
                f"vote was not unanimous")
        return agreed

    def _pallas_op(self, fn):
        from ray_tpu.collective import metrics  # noqa: F401 (register)

        self._dbg["phase"] = "pallas"
        try:
            return fn()
        except Exception as e:
            # the kernel tier fails independently of the device plane:
            # disable only pallas so the next op can still vote device
            self._pallas_disabled = True
            self._abort_not_hang(e)

    def _shm_op(self, fn):
        self._dbg["phase"] = "shm"
        try:
            return fn()
        except Exception as e:
            # any failure mid-op leaves ranks at different barrier phases:
            # poison the segment (peers abort, not hang) and never reuse it
            t, self._shm = self._shm, None
            if t is not None:
                try:
                    t.abort()
                finally:
                    # EVERY survivor unlinks, not just rank 0: if the
                    # crash that tripped this op was rank 0 dying between
                    # segment map and its post-fence unlink, nobody else
                    # would ever remove the file and the tmpfs bytes leak
                    # forever (unlink is idempotent; live mappings keep
                    # their pages until released)
                    t.close(unlink=True)
            self._shm_disabled = True
            self._abort_not_hang(e)

    def _ring_op(self, fn):
        self._dbg["phase"] = "ring"
        try:
            return fn()
        except Exception as e:
            # a failed ring op leaves peers at different steps: the
            # connections are unusable, rebuild from scratch next op
            self._ring_teardown()
            self._abort_not_hang(e)

    # ---- shm data plane ----

    @staticmethod
    def _node_token() -> str | None:
        try:
            from ray_tpu._private import global_state

            cw = global_state.get_core_worker()
            if cw is not None and cw.node_id is not None:
                return cw.node_id.hex()
        except Exception:
            pass
        return None

    def _ensure_shm(self, need_bytes: int):
        """Map (or grow) the group's shared segment. Every rank computes
        the same need (collective contract), so rebuild generations stay
        aligned without extra coordination; the ok-flag allgather through
        the hub makes enable/disable unanimous."""
        if self._shm_disabled or self.world_size == 1 or self._destroyed:
            return None
        if (need_bytes > self.SHM_MAX_SLOT_BYTES
                and self._forced() != Transport.SHM.value):
            # result-dtype promotion (e.g. int8 MEAN -> float64) can
            # inflate the slot well past the routed nbytes; enforce the
            # tmpfs budget on the real slot need (forced shm overrides)
            return None
        if self._shm is not None and self._shm.slot_bytes >= need_bytes:
            return self._shm
        from ray_tpu.collective.backends.shm_transport import ShmTransport
        from ray_tpu.experimental import internal_kv
        from ray_tpu.native.store import is_shared_memory_path

        if self._shm is not None:  # grow: all ranks rebuild together
            self._shm.close()
            self._shm = None
        slot = max(1 << 20, 1 << (need_bytes - 1).bit_length())
        if self._forced() != Transport.SHM.value:
            # auto-routing discovery: the ok-flag exchange below is a
            # probe round (a placement-derived/forced shm group pays
            # the segment setup but not a *probe* — the tier was known)
            self._probe_rounds += 1
        self._shm_gen += 1
        key = f"{self._key}/shm{self._shm_gen}"
        seg, ok = None, 0
        if self.rank == 0:
            self._shm_keys.append(key)  # destroy() clears even fail markers
        try:
            if self.rank == 0:
                cookie = os.urandom(16)
                name = (f"{self.group_name}_g{self._shm_gen}_"
                        f"{cookie.hex()[:8]}.seg")
                try:
                    seg = ShmTransport.create(name, cookie, self.world_size,
                                              0, slot, self._timeout)
                    token = self._node_token()
                    if token is None and not is_shared_memory_path(seg.path):
                        # without a node id, only /dev/shm placement
                        # proves the mapping is node-local memory
                        raise RuntimeError(
                            "no node identity and segment not on /dev/shm")
                    internal_kv._kv_put(key, msgpack.packb(
                        {"path": seg.path, "cookie": cookie, "slot": slot,
                         "node": token}, use_bin_type=True))
                except Exception:
                    internal_kv._kv_put(key, msgpack.packb(
                        {"fail": True}, use_bin_type=True))
                    raise
            else:
                deadline = time.monotonic() + self._timeout
                info = None
                while time.monotonic() < deadline:
                    data = internal_kv._kv_get(key)
                    if data:
                        info = msgpack.unpackb(data, raw=False)
                        break
                    time.sleep(0.02)
                if info is None:
                    raise TimeoutError("shm segment rendezvous timed out")
                if info.get("fail"):
                    raise RuntimeError("rank 0 could not create the segment")
                token = self._node_token()
                if info["node"] is not None and token is not None:
                    if info["node"] != token:
                        raise RuntimeError(
                            "rank is on a different node than rank 0")
                elif not is_shared_memory_path(info["path"]):
                    raise RuntimeError(
                        "cannot prove node locality for shm segment")
                seg = ShmTransport.open(info["path"], info["cookie"],
                                        self.world_size, self.rank,
                                        info["slot"], self._timeout)
            ok = 1
        except Exception:
            ok = 0
        try:
            flags = self._hub_allgather(np.array([ok], np.uint8),
                                        kind="allgather_ctl_shm")
        except BaseException:
            if seg is not None:
                # every survivor unlinks: rank 0 (the owner) may be the
                # peer that just died mid-exchange
                seg.close(unlink=True)
            raise
        if all(int(f[0]) for f in flags):
            try:
                seg.barrier()  # join fence: everyone mapped before first op
            except BaseException:
                # a peer died between the flag exchange and the fence:
                # every survivor unlinks (rank 0 may BE the dead peer —
                # its segment file must not outlive the group)
                seg.close(unlink=True)
                self._shm_disabled = True
                raise
            if self.rank == 0:
                # every rank is mapped (the fence proves it) and nothing
                # reopens this generation: unlink NOW so the tmpfs bytes
                # die with the last mapping even if rank 0 is SIGKILLed
                try:
                    os.unlink(seg.path)
                except OSError:
                    pass
            self._shm = seg
            return seg
        if seg is not None:
            seg.close()
        self._shm_disabled = True  # unanimous: don't pay the probe again
        return None

    def _shm_need(self, arr: np.ndarray, op: ReduceOp | None) -> int:
        """Slot bytes that fit both the contribution and half the result
        region (the result region is 2 slots; MEAN promotes integers to
        float64, which can outgrow the input slot)."""
        from ray_tpu.collective.backends.shm_transport import result_dtype

        need = arr.nbytes
        if op is not None:
            need = max(need, (arr.size * result_dtype(arr.dtype, op).itemsize
                              + 1) // 2)
        return max(need, 1)

    # ---- ring data plane ----

    def _ensure_ring(self) -> bool:
        if self.world_size <= 2:
            return False  # ring degenerates to pairwise; hub is fine
        if getattr(self, "_ring_next", None) is not None:
            return True
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(2)
        port = listener.getsockname()[1]
        addr = f"127.0.0.1:{port}".encode().ljust(32, b"\0")
        addrs = self._hub_allgather(np.frombuffer(addr, np.uint8),
                                    kind="allgather_ctl_ring")
        nxt = bytes(addrs[(self.rank + 1) % self.world_size]
                    ).rstrip(b"\0").decode()
        host, p = nxt.rsplit(":", 1)

        out: dict = {}

        lock = threading.Lock()

        def _connect():
            try:
                sock = socket.create_connection(
                    (host, int(p)), timeout=self._timeout)
            except OSError as e:  # surfaced by the join below
                out["err"] = e
                return
            with lock:
                if out.get("abandoned"):  # caller already gave up
                    sock.close()
                else:
                    out["sock"] = sock

        t = threading.Thread(target=_connect, daemon=True)
        t.start()
        prev_sock = None
        try:
            listener.settimeout(self._timeout)
            prev_sock, _ = listener.accept()
            # keep the configured timeout on both ring sockets so a
            # stalled (connected but silent) peer raises socket.timeout
            # instead of hanging recv forever — abort-not-hang applies
            # to the data plane
            prev_sock.settimeout(self._timeout)
            t.join(self._timeout)
            with lock:
                if "sock" not in out:
                    out["abandoned"] = True  # late connect self-closes
                    raise ConnectionError(
                        f"ring connect to rank "
                        f"{(self.rank + 1) % self.world_size}"
                        f" failed: {out.get('err')}")
        except BaseException:
            if prev_sock is not None:
                prev_sock.close()
            sock = out.get("sock")
            if sock is not None:
                sock.close()
            raise
        finally:
            listener.close()
        out["sock"].settimeout(self._timeout)
        # pipelined slices are small; don't let Nagle hold the tail
        for s in (out["sock"], prev_sock):
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._ring_next = out["sock"]
        self._ring_prev = prev_sock
        return True

    def _ring_teardown(self):
        """Close and forget both ring sockets. A failed ring op leaves
        peers at different steps, so the connections are unusable; the
        next large allreduce rebuilds the ring from scratch (or fails the
        collective setup, which the caller handles)."""
        for name in ("_ring_next", "_ring_prev"):
            sock = getattr(self, name, None)
            if sock is not None:
                try:
                    sock.close()
                except Exception:
                    pass
            setattr(self, name, None)

    # -- legacy (unpipelined) ring: the A/B control arm ----------------

    @staticmethod
    def _ring_send(sock: socket.socket, data: bytes):
        sock.sendall(_HDR.pack(len(data)) + data)

    @staticmethod
    def _ring_recv(sock: socket.socket) -> bytes:
        (n,) = _HDR.unpack(_recv_exact(sock, 4))
        return _recv_exact(sock, n)

    def _ring_step(self, send_bytes: bytes) -> bytes:
        """Full-duplex: push to next while pulling from prev (the send
        rides a thread so neither side can deadlock on full buffers;
        socket timeouts bound both directions)."""
        err: list = []

        def _send():
            try:
                self._ring_send(self._ring_next, send_bytes)
            except Exception as e:
                err.append(e)

        t = threading.Thread(target=_send, daemon=True)
        t.start()
        data = self._ring_recv(self._ring_prev)
        t.join(self._timeout)
        if t.is_alive() or err:
            # a lingering send thread would interleave with the next
            # step's frames — the ring is no longer trustworthy
            raise TimeoutError(
                f"ring send stalled/failed: {err or 'timeout'}")
        return data

    def _ring_allreduce(self, arr: np.ndarray, op: ReduceOp) -> np.ndarray:
        """Unpipelined ring allreduce — one tobytes frame per step.
        Preserved as the control arm of the pipelined-ring perf A/B
        (force_transport='ring_unpipelined')."""
        w = self.world_size
        flat = arr.reshape(-1)
        pad = (-len(flat)) % w
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, arr.dtype)])
        # MEAN matches the hub's np.mean semantics: float64 accumulate
        # and a float result for integer inputs (also dodges overflow)
        if op == ReduceOp.MEAN and not np.issubdtype(arr.dtype,
                                                     np.floating):
            flat = flat.astype(np.float64)
        work = flat.copy()
        chunk = len(work) // w
        combine = getattr(
            np, _NUMPY_REDUCE[ReduceOp.SUM if op == ReduceOp.MEAN
                              else ReduceOp(op)])

        def view(i):
            i %= w
            return work[i * chunk:(i + 1) * chunk]

        for step in range(w - 1):  # reduce-scatter
            send_idx = self.rank - step
            recv_idx = self.rank - step - 1
            incoming = self._ring_step(view(send_idx).tobytes())
            recv = view(recv_idx)
            # parse with the wire dtype (work.dtype): for integer MEAN the
            # work buffer — and therefore every frame on the ring — is
            # float64, not arr.dtype
            np.copyto(recv, combine(
                recv, np.frombuffer(incoming, work.dtype)))
        for step in range(w - 1):  # allgather of reduced chunks
            send_idx = self.rank + 1 - step
            recv_idx = self.rank - step
            incoming = self._ring_step(view(send_idx).tobytes())
            np.copyto(view(recv_idx), np.frombuffer(incoming, work.dtype))
        if op == ReduceOp.MEAN:
            work = work / w  # float result, like the hub's np.mean
        out = work[:arr.size].reshape(arr.shape)
        if op == ReduceOp.MEAN:
            return out
        return out.astype(arr.dtype, copy=False)

    # -- pipelined zero-copy ring --------------------------------------

    def _ring_recv_into(self, mv: memoryview):
        sock = self._ring_prev
        got, n = 0, len(mv)
        while got < n:
            r = sock.recv_into(mv[got:], n - got)
            if not r:
                raise ConnectionError("collective peer disconnected")
            got += r

    def _ring_send_async(self, send_mv: memoryview):
        """Stream a work-buffer slice to the next rank in _PIPE_BYTES
        pieces (memoryview slices — no tobytes copy), off-thread so the
        caller can consume the previous rank's stream concurrently.
        Small steps send inline: a <=16KB sendall into a peer buffer
        that the previous step fully drained cannot block (SO_SNDBUF
        floors are far larger), and skipping the thread keeps
        just-over-threshold collectives from paying thread churn per
        step."""
        if not len(send_mv):
            return None, []
        if len(send_mv) <= (1 << 14):
            self._ring_next.sendall(send_mv)
            return None, []
        err: list = []

        def _send():
            try:
                off, n = 0, len(send_mv)
                while off < n:
                    self._ring_next.sendall(
                        send_mv[off:off + self._PIPE_BYTES])
                    off += self._PIPE_BYTES
            except Exception as e:
                err.append(e)

        t = threading.Thread(target=_send, daemon=True)
        t.start()
        return t, err

    def _ring_join(self, t, err):
        if t is None:
            return
        t.join(self._timeout)
        if t.is_alive() or err:
            raise TimeoutError(
                f"ring send stalled/failed: {err or 'timeout'}")

    def _ring_step_reduce(self, send_mv: memoryview, dst: np.ndarray,
                          scratch: np.ndarray, combine):
        """One pipelined reduce step: stream `send_mv` out while pulling
        dst.nbytes from prev in slices; each slice is combined into `dst`
        the moment it lands, so the reduce of slice k overlaps the
        receive of slice k+1 (the peer keeps filling the socket buffer
        while we compute). No frame headers: both sides derive the same
        chunk schedule, so the stream is self-describing."""
        t, err = self._ring_send_async(send_mv)
        smv = memoryview(scratch).cast("B")
        isz = dst.itemsize
        total, off = dst.nbytes, 0
        while off < total:
            n = min(self._PIPE_BYTES, total - off)
            self._ring_recv_into(smv[:n])
            k = n // isz
            lo = off // isz
            combine(dst[lo:lo + k], scratch[:k], out=dst[lo:lo + k])
            off += n
        self._ring_join(t, err)

    def _ring_step_gather(self, send_mv: memoryview, recv_mv: memoryview):
        """One pipelined gather step: stream out while receiving straight
        into the destination region (recv_into — zero-copy)."""
        t, err = self._ring_send_async(send_mv)
        self._ring_recv_into(recv_mv)
        self._ring_join(t, err)

    def _prep_ring_work(self, arr: np.ndarray, op: ReduceOp):
        flat = arr.reshape(-1)
        # MEAN matches hub np.mean semantics: float64 accumulate and a
        # float result for integer inputs (also dodges overflow), f32
        # intermediates for f16 (np.mean does the same; a raw f16 add
        # chain loses whole units at a few thousand)
        if op == ReduceOp.MEAN and not np.issubdtype(arr.dtype,
                                                     np.floating):
            work = flat.astype(np.float64)
        elif op == ReduceOp.MEAN and arr.dtype == np.float16:
            work = flat.astype(np.float32)
        else:
            work = flat.copy()
        combine = getattr(
            np, _NUMPY_REDUCE[ReduceOp.SUM if op == ReduceOp.MEAN
                              else ReduceOp(op)])
        return work, combine

    def _ring_scratch(self, work: np.ndarray, bounds: list[int]):
        maxel = max((bounds[i + 1] - bounds[i] for i in range(len(bounds) - 1)),
                    default=0)
        n = min(maxel, self._PIPE_BYTES // work.itemsize)
        return np.empty(max(n, 1), work.dtype)

    def _ring_reduce_scatter_phase(self, work, bounds, combine, scratch,
                                   delta: int):
        """w-1 pipelined reduce steps; with delta=0 rank r ends holding
        reduced chunk r+1 (the allreduce schedule), with delta=-1 it ends
        holding chunk r (the reducescatter schedule)."""
        w = self.world_size
        wv = memoryview(work).cast("B")
        isz = work.itemsize

        def mv(i):
            i %= w
            return wv[bounds[i] * isz:bounds[i + 1] * isz]

        def el(i):
            i %= w
            return work[bounds[i]:bounds[i + 1]]

        for step in range(w - 1):
            send_i = self.rank - step + delta
            recv_i = send_i - 1
            self._ring_step_reduce(mv(send_i), el(recv_i), scratch, combine)

    def _ring_allreduce_pipelined(self, arr: np.ndarray,
                                  op: ReduceOp) -> np.ndarray:
        from ray_tpu.collective.backends.shm_transport import split_bounds

        w = self.world_size
        work, combine = self._prep_ring_work(arr, op)
        bounds = split_bounds(work.size, w)
        scratch = self._ring_scratch(work, bounds)
        self._ring_reduce_scatter_phase(work, bounds, combine, scratch, 0)
        wv = memoryview(work).cast("B")
        isz = work.itemsize

        def mv(i):
            i %= w
            return wv[bounds[i] * isz:bounds[i + 1] * isz]

        for step in range(w - 1):  # allgather of reduced chunks
            self._ring_step_gather(mv(self.rank + 1 - step),
                                   mv(self.rank - step))
        if op == ReduceOp.MEAN:
            work = work / w  # float result, like the hub's np.mean
            if arr.dtype == np.float16:
                work = work.astype(np.float16)  # f32 accumulate, f16 out
        return work.reshape(arr.shape)

    def _ring_reducescatter_pipelined(self, arr: np.ndarray,
                                      op: ReduceOp) -> np.ndarray:
        from ray_tpu.collective.backends.shm_transport import split_bounds

        w = self.world_size
        work, combine = self._prep_ring_work(arr, op)
        # hub semantics: np.array_split along axis 0 — row blocks are
        # contiguous element ranges in C order
        rows = arr.shape[0] if arr.ndim else 1
        rowsz = arr.size // rows if rows else 0
        rb = split_bounds(rows, w)
        bounds = [r * rowsz for r in rb]
        scratch = self._ring_scratch(work, bounds)
        self._ring_reduce_scatter_phase(work, bounds, combine, scratch, -1)
        res = work[bounds[self.rank]:bounds[self.rank + 1]]
        if op == ReduceOp.MEAN:
            res = res / w
            if arr.dtype == np.float16:
                res = res.astype(np.float16)  # f32 accumulate, f16 out
        return res.reshape((rb[self.rank + 1] - rb[self.rank],)
                           + arr.shape[1:]).copy()

    def _ring_allgather_pipelined(self, arr: np.ndarray):
        """Block-rotation allgather over uniform-shape contributions
        (the caller's meta round guarantees uniformity)."""
        w = self.world_size
        n = arr.nbytes
        out = np.empty(w * arr.size, arr.dtype)
        ov = memoryview(out).cast("B")
        ov[self.rank * n:(self.rank + 1) * n] = memoryview(arr).cast("B")

        def mv(i):
            i %= w
            return ov[i * n:(i + 1) * n]

        for step in range(w - 1):
            self._ring_step_gather(mv(self.rank - step),
                                   mv(self.rank - step - 1))
        return [out[i * arr.size:(i + 1) * arr.size].reshape(arr.shape)
                for i in range(w)]

    def _ring_broadcast_pipelined(self, arr: np.ndarray,
                                  src_rank: int) -> np.ndarray:
        """Pipelined relay chain src → src+1 → … → src-1: each slice is
        forwarded the moment it lands, so after the w-hop fill the whole
        chain streams concurrently. Acyclic per slice — no deadlock."""
        w = self.world_size
        out = arr if self.rank == src_rank else np.empty_like(arr)
        ov = memoryview(out).cast("B")
        do_recv = self.rank != src_rank
        do_send = (self.rank + 1) % w != src_rank
        total, off = out.nbytes, 0
        while off < total:
            n = min(self._PIPE_BYTES, total - off)
            if do_recv:
                self._ring_recv_into(ov[off:off + n])
            if do_send:
                self._ring_next.sendall(ov[off:off + n])
            off += n
        # fresh writable result on every rank/tier, like the hub
        return out.copy() if out is arr else out

    # -- quantized (int8 block-scaled) pipelined ring ------------------

    def _fire_quantize(self):
        if _fp.ARMED:
            _fp.fire_strict("collective.quantize")

    def _ring_send_seq_async(self, parts: list[memoryview]):
        """Stream a sequence of buffers (scales header, then payload) to
        the next rank in order. Like _ring_send_async, tiny totals send
        inline; anything larger rides one thread — the HEADER must not
        be a blocking main-thread sendall, or every rank can sit in it
        simultaneously once scales outgrow the socket buffers (circular
        stall, spurious timeout) while nobody drains its peer."""
        if sum(len(p) for p in parts) <= (1 << 14):
            for p in parts:
                self._ring_next.sendall(p)
            return None, []
        err: list = []

        def _send():
            try:
                for p in parts:
                    off, n = 0, len(p)
                    while off < n:
                        self._ring_next.sendall(
                            p[off:off + self._PIPE_BYTES])
                        off += self._PIPE_BYTES
            except Exception as e:
                err.append(e)

        t = threading.Thread(target=_send, daemon=True)
        t.start()
        return t, err

    def _ring_step_qreduce(self, send_chunk: np.ndarray, dst: np.ndarray,
                           combine):
        """One quantized ring step: quantize and stream the outgoing
        chunk (per-block f32 scales ride ahead of the int8 payload)
        while receiving the peer's, dequantizing and combining
        pipeline-slice by slice into `dst` (float32). Wire bytes per
        chunk: elems * (1 + 4/QUANT_BLOCK) instead of elems * 4."""
        self._fire_quantize()
        q, scales = _quant_np(send_chunk)
        t, err = self._ring_send_seq_async(
            [memoryview(scales).cast("B"), memoryview(q).cast("B")])
        n = dst.size  # elements == int8 payload bytes
        rscales = np.empty(n // QUANT_BLOCK, np.float32)
        self._ring_recv_into(memoryview(rscales).cast("B"))
        rq = np.empty(min(self._PIPE_BYTES, n), np.int8)
        off = 0
        while off < n:  # slices stay QUANT_BLOCK-aligned (2^18 % 256 == 0)
            k = min(self._PIPE_BYTES, n - off)
            self._ring_recv_into(memoryview(rq).cast("B")[:k])
            deq = (rq[:k].reshape(-1, QUANT_BLOCK).astype(np.float32)
                   * rscales[off // QUANT_BLOCK:
                             (off + k) // QUANT_BLOCK, None]).reshape(-1)
            combine(dst[off:off + k], deq, out=dst[off:off + k])
            off += k
        self._ring_join(t, err)

    def _ring_allreduce_quantized(self, arr: np.ndarray,
                                  op: ReduceOp) -> np.ndarray:
        """EQuARX-style quantized pipelined ring allreduce: every hop of
        the reduce-scatter phase re-quantizes the partial chunk to
        int8 + per-block f32 scales and combines on the dequantized
        float32 values; the allgather phase quantizes the reduced chunk
        ONCE and relays the same bytes, so every rank dequantizes
        identical data and the (lossy) result agrees bitwise across
        ranks. Analytic error bound: each of the <= world quantization
        steps that touch an output element perturbs it by at most
        scale/2 <= absmax/254 of the partial it quantized."""
        w = self.world_size
        in_dt = arr.dtype
        n = arr.size
        # uniform block-aligned chunks (zero padding never inflates a
        # block's absmax, and the pad region is sliced off at the end)
        per_rank = -(-n // w)
        C = -(-per_rank // QUANT_BLOCK) * QUANT_BLOCK
        work = np.zeros(w * C, np.float32)
        work[:n] = arr.reshape(-1)
        combine = getattr(np, _NUMPY_REDUCE[
            ReduceOp.SUM if op == ReduceOp.MEAN else ReduceOp(op)])

        def chunk(i):
            i %= w
            return work[i * C:(i + 1) * C]

        # reduce-scatter (delta=0 schedule): w-1 quantized hops — rank r
        # ends holding the fully-reduced chunk (r+1) % w
        for step in range(w - 1):
            send_i = self.rank - step
            self._ring_step_qreduce(chunk(send_i), chunk(send_i - 1),
                                    combine)
        # allgather: quantize the reduced chunk once, relay the same
        # bytes around the ring; the own chunk goes through the same
        # dequant so all ranks hold bit-identical results
        self._fire_quantize()
        own = (self.rank + 1) % w
        q, scales = _quant_np(chunk(own))
        work[own * C:(own + 1) * C] = _dequant_np(q, scales)
        rq = np.empty(C, np.int8)
        rscales = np.empty(C // QUANT_BLOCK, np.float32)
        for step in range(w - 1):
            t, err = self._ring_send_seq_async(
                [memoryview(scales).cast("B"), memoryview(q).cast("B")])
            self._ring_recv_into(memoryview(rscales).cast("B"))
            self._ring_recv_into(memoryview(rq).cast("B"))
            self._ring_join(t, err)
            idx = (self.rank - step) % w
            work[idx * C:(idx + 1) * C] = _dequant_np(rq, rscales)
            q, scales = rq.copy(), rscales.copy()  # relay onward
        # socket bytes saved vs the exact tier's wire dtype
        wire_elems = 2 * (w - 1) * C
        exact_item = (4 if (op == ReduceOp.MEAN and in_dt == np.float16)
                      else in_dt.itemsize)
        saved = wire_elems * exact_item - wire_elems * (
            1 + 4 / QUANT_BLOCK)
        if saved > 0:
            from ray_tpu.collective import metrics as _cm

            _cm.QUANT_SAVED.inc(int(saved))
        out = work[:n]
        if op == ReduceOp.MEAN:
            out = out / w
        return out.astype(in_dt, copy=False).reshape(arr.shape).copy()

    def _ring_reducescatter_quantized(self, arr: np.ndarray,
                                      op: ReduceOp) -> np.ndarray:
        """Quantized pipelined ring reduce-scatter — the reduce half of
        _ring_allreduce_quantized on the delta=-1 schedule, so rank r
        ends holding reduced chunk r (hub/np.array_split semantics).
        The dispatch admits only flat buckets whose size is a multiple
        of world * QUANT_BLOCK — exactly the sharded trainer's padded
        grad bucket (train/sharding.py layout) — so chunks are uniform
        and block-aligned with no re-marshalling. Lossy, but each output
        element is perturbed by <= scale/2 per hop that touched it, and
        the result is rank-local (no cross-rank divergence to agree
        on)."""
        w = self.world_size
        in_dt = arr.dtype
        C = arr.size // w
        work = arr.reshape(-1).astype(np.float32)  # fresh f32 accumulator
        combine = getattr(np, _NUMPY_REDUCE[
            ReduceOp.SUM if op == ReduceOp.MEAN else ReduceOp(op)])

        def chunk(i):
            i %= w
            return work[i * C:(i + 1) * C]

        for step in range(w - 1):
            send_i = self.rank - step - 1
            self._ring_step_qreduce(chunk(send_i), chunk(send_i - 1),
                                    combine)
        # socket bytes saved vs the exact pipelined tier's wire dtype
        wire_elems = (w - 1) * C
        saved = wire_elems * in_dt.itemsize - wire_elems * (
            1 + 4 / QUANT_BLOCK)
        if saved > 0:
            from ray_tpu.collective import metrics as _cm

            _cm.QUANT_SAVED.inc(int(saved))
        res = chunk(self.rank)
        if op == ReduceOp.MEAN:
            res = res / w
        return res.astype(in_dt, copy=False).reshape(
            (arr.shape[0] // w,) + arr.shape[1:]).copy()

    # ---- collectives (routed) ----

    def _run_routed(self, arr: np.ndarray, shm_need: int, shm_fn, ring_fn,
                    hub_fn):
        """One route/fallback/poison dispatch for the uniform-geometry
        collectives (allgather is bespoke: its geometry may be ragged).
        shm_fn(transport), ring_fn(pipelined: bool), hub_fn(). A
        placement-derived pin whose tier can't be built demotes
        (group-uniformly — shm's ok-flag exchange / the uniform ring
        build result) and re-routes, instead of raising like a
        user-forced one."""
        while True:
            rerouted = False
            for tr in self._route(arr):
                if tr == Transport.SHM.value:
                    t = self._ensure_shm(shm_need)
                    if t is None:
                        if self._tier_unavailable(tr):
                            rerouted = True
                            break
                        continue
                    return self._shm_op(lambda: shm_fn(t))
                if tr in (Transport.RING.value,
                          Transport.RING_UNPIPELINED.value):
                    if not self._ring_op(self._ensure_ring):
                        if self._tier_unavailable(tr):
                            rerouted = True
                            break
                        continue
                    pipelined = tr == Transport.RING.value
                    return self._ring_op(lambda: ring_fn(pipelined))
                return hub_fn()
            if not rerouted:
                raise RuntimeError("no collective transport available")

    @_op_entry("allreduce")
    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM,
                  quantize=None):
        op = ReduceOp(op)
        q = self._quantize_mode(quantize)
        if self._pallas_route(arr):
            return self._pallas_op(
                lambda: self._pallas.allreduce(arr, op, quantize=q))
        if self._device_route(arr):
            return self._device_op(
                lambda: self._device.allreduce(arr, op, quantize=q))
        arr = self._to_host(arr)

        def hub():
            reply, data = self._collective(
                "allreduce", {**_arr_meta(arr), "op": op.value},
                arr.tobytes())
            return _arr_from(reply["meta"], data)

        def ring(pipelined):
            # the quantized wire format lives on the pipelined ring (the
            # unpipelined arm is the exact A/B control); int payloads
            # and PRODUCT stay exact by definition
            if (pipelined and q and op in _QUANT_OPS
                    and np.issubdtype(arr.dtype, np.floating)):
                return self._ring_allreduce_quantized(arr, op)
            return (self._ring_allreduce_pipelined(arr, op) if pipelined
                    else self._ring_allreduce(arr, op))

        return self._run_routed(
            arr, self._shm_need(arr, op),
            lambda t: t.allreduce(arr, op),
            ring, hub)

    @_op_entry("reduce")
    def reduce(self, arr: np.ndarray, dst_rank: int = 0,
               op: ReduceOp = ReduceOp.SUM):
        arr = self._to_host(arr)
        reply, data = self._collective(
            "reduce", {**_arr_meta(arr), "op": op.value, "dst": dst_rank},
            arr.tobytes())
        if self.rank == dst_rank:
            return _arr_from(reply["meta"], data)
        return arr

    @_op_entry("broadcast")
    def broadcast(self, arr: np.ndarray, src_rank: int = 0):
        if self._device_route(arr):
            return self._device_op(
                lambda: self._device.broadcast(arr, src_rank))
        arr = self._to_host(arr)

        def hub():
            payload = arr.tobytes() if self.rank == src_rank else b""
            meta = {**_arr_meta(arr), "src": src_rank}
            reply, data = self._collective("broadcast", meta, payload)
            return _arr_from(reply["meta"], data)

        return self._run_routed(
            arr, self._shm_need(arr, None),
            lambda t: t.broadcast(arr, src_rank),
            lambda pipelined: self._ring_broadcast_pipelined(arr, src_rank),
            hub)

    @_op_entry("allgather")
    def allgather(self, arr: np.ndarray) -> list[np.ndarray]:
        # allgather is the one op whose per-rank GEOMETRY may
        # legitimately differ, so local-size routing can diverge (ragged
        # sizes straddling RING_MIN_BYTES). Every rank therefore opens
        # with the SAME metadata-only hub round and routes on the union:
        # fast tiers only for uniform shapes, the hub (which supports
        # ragged gathers natively) otherwise. One extra control
        # round-trip, paid once, instead of per-tier probing — and no
        # route divergence is possible.
        if not self._is_device_array(arr):
            arr = np.ascontiguousarray(arr)
        if self.world_size == 1 or self._destroyed:
            return self._hub_allgather(self._to_host(arr))
        metas = self._hub_allgather_meta(arr)
        uniform = all(m == metas[0] for m in metas[1:])
        # the pallas/device votes only happen on the uniform path, so
        # every rank enters (or skips) the ctl rounds together
        if uniform and self._pallas_route(arr):
            return self._pallas_op(lambda: self._pallas.allgather(arr))
        if uniform and self._device_route(arr):
            return self._device_op(lambda: self._device.allgather(arr))
        arr = self._to_host(arr)
        for tr in self._route(arr) if uniform else [Transport.HUB.value]:
            if tr == Transport.SHM.value:
                t = self._ensure_shm(self._shm_need(arr, None))
                if t is None:
                    # derived pin demotes (uniform) and this op falls
                    # through to the unconditional hub below
                    self._tier_unavailable(tr)
                    continue
                out = self._shm_op(lambda: t.allgather(arr))
                if out is not None:
                    return out
                continue  # defense-in-depth: shm saw ragged metas
            if tr in (Transport.RING.value, Transport.RING_UNPIPELINED.value):
                if not self._ring_op(self._ensure_ring):
                    self._tier_unavailable(tr)
                    continue
                return self._ring_op(
                    lambda: self._ring_allgather_pipelined(arr))
            return self._hub_allgather(arr)
        # pinned non-hub transport exhausted (e.g. forced shm + ragged):
        # the hub is the only tier that can express it
        return self._hub_allgather(arr)

    @_op_entry("reducescatter")
    def reducescatter(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM,
                      quantize=None):
        op = ReduceOp(op)
        q = self._quantize_mode(quantize)
        if self._pallas_route(arr):
            return self._pallas_op(
                lambda: self._pallas.reducescatter(arr, op, quantize=q))
        if self._device_route(arr):
            return self._device_op(
                lambda: self._device.reducescatter(arr, op, quantize=q))
        arr = self._to_host(arr)

        def hub():
            reply, data = self._collective(
                "reducescatter", {**_arr_meta(arr), "op": op.value},
                arr.tobytes())
            return _arr_from(reply["meta"], data)

        def ring(pipelined):
            # quantized wire only on the pipelined ring, and only for
            # flat world*QUANT_BLOCK-aligned float buckets (uniform
            # block-aligned chunks — the sharded-trainer grad layout);
            # anything else takes the exact tier
            if (pipelined and q and op in _QUANT_OPS
                    and np.issubdtype(arr.dtype, np.floating)
                    and arr.ndim == 1
                    and arr.size % (self.world_size * QUANT_BLOCK) == 0):
                return self._ring_reducescatter_quantized(arr, op)
            return self._ring_reducescatter_pipelined(arr, op)

        return self._run_routed(
            arr, self._shm_need(arr, op),
            lambda t: t.reducescatter(arr, op),
            ring, hub)

    @_op_entry("barrier")
    def barrier(self):
        self._collective("barrier", {}, b"")

    # ---- p2p ----
    # The hub mailbox always carries the rendezvous/control message;
    # payloads above RING_MIN_BYTES go peer-direct (one rank-to-rank
    # connection) instead of double-copying through rank 0.

    def send(self, arr: np.ndarray, dst_rank: int, tag: int = 0):
        arr = np.ascontiguousarray(arr)
        if (arr.nbytes >= self.RING_MIN_BYTES and self.world_size > 1
                and dst_rank != self.rank and not self._destroyed):
            return self._send_direct(arr, dst_rank, tag)
        if self.rank == 0:
            self._state.post(0, dst_rank, tag, _arr_meta(arr), arr.tobytes())
            return
        _send_msg(self._sock, {"kind": "p2p_send", "dst": dst_rank,
                               "tag": tag, "meta": _arr_meta(arr)},
                  arr.tobytes())
        _recv_msg(self._sock)  # ack

    def _send_direct(self, arr: np.ndarray, dst_rank: int, tag: int):
        """Post the rendezvous control message to the hub mailbox, then
        serve the payload from a background thread — send() keeps the
        hub path's buffered semantics (returns without waiting for the
        receiver, so symmetric send/send-then-recv/recv patterns can't
        deadlock). The payload is snapshotted first, so mutating the
        tensor after send() returns cannot corrupt the transfer. The
        listener has NO deadline of its own: like a hub mailbox entry,
        the buffered payload stays claimable until the receiver takes it
        or the group is destroyed (destroy() closes the listener, which
        frees the thread and the snapshot) — recv-side timeouts still
        bound every blocking reader, so there is no expiry cliff at the
        RING_MIN_BYTES threshold."""
        arr = arr.copy()
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        key = (dst_rank, tag)
        with self._p2p_lock:
            stale = self._p2p_direct.pop(key, None)
            self._p2p_direct[key] = listener
        if stale is not None:
            try:  # overwrite the unclaimed predecessor, like the mailbox
                stale.close()  # (an in-flight transfer keeps its conn fd)
            except Exception:
                pass
        port = listener.getsockname()[1]
        ctrl = {**_arr_meta(arr), "peer_direct": f"127.0.0.1:{port}"}

        def _serve():
            conn = None
            try:
                conn, _ = listener.accept()  # until taken/overwritten/
                conn.settimeout(self._timeout)  # destroyed
                conn.sendall(memoryview(arr).cast("B"))
                conn.recv(1)  # receiver ack bounds arr's lifetime
            except OSError:
                pass  # abort-not-hang: the receiver sees a short read
            finally:
                if conn is not None:
                    conn.close()
                listener.close()
                with self._p2p_lock:
                    if self._p2p_direct.get(key) is listener:
                        del self._p2p_direct[key]

        t = threading.Thread(target=_serve, daemon=True)
        t.start()
        try:
            if self.rank == 0:
                self._state.post(0, dst_rank, tag, ctrl, b"")
            else:
                _send_msg(self._sock, {"kind": "p2p_send", "dst": dst_rank,
                                       "tag": tag, "meta": ctrl})
                _recv_msg(self._sock)  # hub ack
        except BaseException:
            listener.close()  # unblocks the serve thread
            raise

    def recv(self, src_rank: int, tag: int = 0) -> np.ndarray:
        if self.rank == 0:
            meta, data = self._state.take(src_rank, 0, tag,
                                          timeout=self._timeout)
        else:
            _send_msg(self._sock, {"kind": "p2p_recv", "src": src_rank,
                                   "tag": tag})
            reply, data = _recv_msg(self._sock)
            if "error" in reply:
                raise TimeoutError(reply["error"])
            meta = reply["meta"]
        if meta and meta.get("peer_direct"):
            return self._recv_direct(meta)
        return _arr_from(meta, data)

    def _recv_direct(self, meta: dict) -> np.ndarray:
        host, port = meta["peer_direct"].rsplit(":", 1)
        out = np.empty(meta["shape"], np.dtype(meta["dtype"]))
        try:
            sock = socket.create_connection((host, int(port)),
                                            timeout=self._timeout)
        except OSError as e:
            raise TimeoutError(
                f"peer-direct recv: sender unreachable: {e}") from e
        try:
            sock.settimeout(self._timeout)
            mv = memoryview(out).cast("B")
            got, n = 0, out.nbytes
            while got < n:
                r = sock.recv_into(mv[got:], n - got)
                if not r:
                    raise TimeoutError(  # abort-not-hang: peer died
                        "peer-direct sender disconnected mid-transfer")
                got += r
            sock.sendall(b"\x01")
        finally:
            sock.close()
        return out

    def destroy(self):
        if self._destroyed:
            return
        self._destroyed = True
        self._ring_teardown()
        if self._pallas is not None:
            try:
                self._pallas.destroy()  # drops the pallas jit cache
            except Exception:
                pass
            self._pallas = None
        if self._device is not None:
            try:
                self._device.destroy()  # drops the jit cache; the jax
            except Exception:           # runtime itself outlives groups
                pass
            self._device = None
        with self._p2p_lock:
            pending = list(self._p2p_direct.values())
            self._p2p_direct.clear()
        for listener in pending:
            try:
                listener.close()  # frees the serve thread + snapshot
            except Exception:
                pass
        if self._shm is not None:
            try:
                # unlink from every rank (idempotent): rank 0 may already
                # be gone, and group destroy is the last chance to keep
                # the segment's tmpfs bytes from outliving the group
                self._shm.close(unlink=True)
            except Exception:
                pass
            self._shm = None
        if self.rank == 0 and self.world_size > 1:
            try:
                self._listener.close()
            except Exception:
                pass
            from ray_tpu.experimental import internal_kv

            for key in [self._key, *self._shm_keys]:
                try:
                    internal_kv._kv_del(key)
                except Exception:
                    pass
        if self._sock is not None:
            try:
                self._sock.close()
            except Exception:
                pass
