"""HOST backend: cross-process CPU collectives over TCP.

The gloo-equivalent of the reference's collective backends (reference:
python/ray/util/collective/collective_group/ — NCCLGroup :115 and the MPI
stub). Rendezvous goes through the GCS KV (the reference used a named
"Info" actor, util.py) — rank 0 binds a TCP hub, publishes its address
under `collective/<group>`, and every other rank connects.

Topology: star (hub at rank 0). Every collective is served by a shared
contribution table guarded by a condition variable: the last arriving rank
computes the reduction, everyone picks up their slice of the result. P2P
send/recv routes through per-destination mailboxes on the hub. This favors
correctness and portability; the ICI-bandwidth path on TPU is the XLA
backend, not this one — HOST carries control-plane-sized tensors (metrics,
broadcast configs, rendezvous barriers) and stands in for DCN in tests.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any

import msgpack
import numpy as np

from ray_tpu.collective.types import _NUMPY_REDUCE, ReduceOp

_HDR = struct.Struct(">I")


def _send_msg(sock: socket.socket, header: dict, payload: bytes = b""):
    h = msgpack.packb(header, use_bin_type=True)
    sock.sendall(_HDR.pack(len(h)) + h + _HDR.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("collective peer disconnected")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (hlen,) = _HDR.unpack(_recv_exact(sock, 4))
    header = msgpack.unpackb(_recv_exact(sock, hlen), raw=False)
    (plen,) = _HDR.unpack(_recv_exact(sock, 4))
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


def _arr_meta(arr: np.ndarray) -> dict:
    return {"dtype": arr.dtype.str, "shape": list(arr.shape)}


def _arr_from(meta: dict, payload: bytes) -> np.ndarray:
    return np.frombuffer(payload, dtype=np.dtype(meta["dtype"])).reshape(
        meta["shape"]).copy()


def _reduce(arrays: list[np.ndarray], op: ReduceOp) -> np.ndarray:
    if op == ReduceOp.MEAN:
        return np.mean(np.stack(arrays), axis=0)
    ufunc = getattr(np, _NUMPY_REDUCE[ReduceOp(op)])
    out = arrays[0].copy()
    for arr in arrays[1:]:
        out = ufunc(out, arr)
    return out


class _CollectiveState:
    """Hub-side shared op table. contribute() blocks until the op's result
    is ready; the last contributor computes it."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.ops: dict[int, dict] = {}
        self.mailboxes: dict[tuple[int, int, int], tuple[dict, bytes]] = {}

    def contribute(self, op_id: int, kind: str, rank: int, meta: dict,
                   payload: bytes, timeout: float = 300.0):
        with self.cv:
            op = self.ops.setdefault(op_id, {"arrivals": {}, "result": None,
                                             "done": False})
            op["arrivals"][rank] = (kind, meta, payload)
            if len(op["arrivals"]) == self.world_size:
                op["result"] = self._compute(kind, op["arrivals"])
                op["done"] = True
                self.cv.notify_all()
            else:
                deadline = time.monotonic() + timeout
                while not op["done"]:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        # Withdraw this rank's contribution so a late
                        # straggler can't complete the op with data the
                        # timed-out ranks already abandoned (silent
                        # divergence); last withdrawer frees the op.
                        op["arrivals"].pop(rank, None)
                        if not op["arrivals"]:
                            self.ops.pop(op_id, None)
                        raise TimeoutError(
                            f"collective op {op_id} ({kind}) timed out: "
                            f"{len(op['arrivals'])}/{self.world_size} arrived")
                    self.cv.wait(remaining)
            result = op["result"]
            # last reader cleans up
            op.setdefault("readers", set()).add(rank)
            if len(op["readers"]) == self.world_size:
                del self.ops[op_id]
        return result

    def _compute(self, kind: str, arrivals: dict):
        ranks = sorted(arrivals)
        kinds = {arrivals[r][0] for r in ranks}
        assert len(kinds) == 1, f"mismatched collective kinds: {kinds}"
        metas = {r: arrivals[r][1] for r in ranks}
        payloads = {r: arrivals[r][2] for r in ranks}
        if kind == "barrier":
            return {"kind": "barrier"}
        if kind == "broadcast":
            src = metas[ranks[0]]["src"]
            return {"kind": "bcast", "meta": metas[src],
                    "payload": payloads[src]}
        if kind in ("allreduce", "reduce"):
            op = ReduceOp(metas[ranks[0]]["op"])
            arrays = [_arr_from(metas[r], payloads[r]) for r in ranks]
            out = _reduce(arrays, op)
            return {"kind": kind, "meta": _arr_meta(out),
                    "payload": out.tobytes(),
                    "dst": metas[ranks[0]].get("dst", -1)}
        if kind == "allgather":
            return {"kind": "allgather",
                    "metas": [metas[r] for r in ranks],
                    "payloads": [payloads[r] for r in ranks]}
        if kind == "reducescatter":
            op = ReduceOp(metas[ranks[0]]["op"])
            arrays = [_arr_from(metas[r], payloads[r]) for r in ranks]
            out = _reduce(arrays, op)
            chunks = np.array_split(out, len(ranks), axis=0)
            return {"kind": "reducescatter",
                    "metas": [_arr_meta(c) for c in chunks],
                    "payloads": [np.ascontiguousarray(c).tobytes()
                                 for c in chunks]}
        raise ValueError(f"unknown collective kind {kind!r}")

    # p2p
    def post(self, src: int, dst: int, tag: int, meta: dict, payload: bytes):
        with self.cv:
            self.mailboxes[(src, dst, tag)] = (meta, payload)
            self.cv.notify_all()

    def take(self, src: int, dst: int, tag: int, timeout: float = 300.0):
        deadline = time.monotonic() + timeout
        with self.cv:
            while (src, dst, tag) not in self.mailboxes:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"recv from {src} tag {tag} timed out")
                self.cv.wait(remaining)
            return self.mailboxes.pop((src, dst, tag))


class HostGroup:
    def __init__(self, group_name: str, world_size: int, rank: int,
                 timeout: float = 60.0):
        from ray_tpu.experimental import internal_kv

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        # Rendezvous AND per-op timeout: ops abort (not hang) when a peer
        # dies mid-collective, so the SGD layer can resize the group.
        self._timeout = timeout
        self._op_id = 0
        self._key = f"collective/{group_name}"
        self._sock: socket.socket | None = None
        self._destroyed = False
        if world_size == 1:
            self._state = _CollectiveState(1)
            return
        if rank == 0:
            self._state = _CollectiveState(world_size)
            self._listener = socket.socket()
            self._listener.bind(("127.0.0.1", 0))
            self._listener.listen(world_size)
            port = self._listener.getsockname()[1]
            internal_kv._kv_put(
                self._key,
                msgpack.packb({"addr": f"127.0.0.1:{port}",
                               "world_size": world_size}))
            self._conn_threads = []
            accept_thread = threading.Thread(target=self._accept_loop,
                                             daemon=True)
            accept_thread.start()
        else:
            deadline = time.monotonic() + timeout
            info = None
            while time.monotonic() < deadline:
                data = internal_kv._kv_get(self._key)
                if data:
                    info = msgpack.unpackb(data, raw=False)
                    break
                time.sleep(0.05)
            if info is None:
                raise TimeoutError(
                    f"rendezvous for group {group_name!r} timed out")
            if info["world_size"] != world_size:
                raise ValueError("world_size mismatch at rendezvous")
            host, port = info["addr"].rsplit(":", 1)
            self._sock = socket.create_connection((host, int(port)),
                                                  timeout=timeout)
            self._sock.settimeout(None)
            _send_msg(self._sock, {"hello": rank})

    # ---- hub side ----
    def _accept_loop(self):
        joined = 0
        while joined < self.world_size - 1:
            conn, _ = self._listener.accept()
            hello, _ = _recv_msg(conn)
            t = threading.Thread(target=self._serve_conn,
                                 args=(conn, hello["hello"]), daemon=True)
            t.start()
            self._conn_threads.append(t)
            joined += 1

    def _serve_conn(self, conn: socket.socket, peer_rank: int):
        try:
            while True:
                header, payload = _recv_msg(conn)
                kind = header["kind"]
                if kind == "p2p_send":
                    self._state.post(peer_rank, header["dst"], header["tag"],
                                     header["meta"], payload)
                    _send_msg(conn, {"ok": True})
                elif kind == "p2p_recv":
                    meta, data = self._state.take(header["src"], peer_rank,
                                                  header["tag"])
                    _send_msg(conn, {"meta": meta}, data)
                else:
                    try:
                        result = self._state.contribute(
                            header["op_id"], kind, peer_rank, header["meta"],
                            payload, timeout=self._timeout)
                    except TimeoutError as e:
                        _send_msg(conn, {"error": str(e)})
                        continue
                    reply, data = self._slice_result(result, peer_rank, kind)
                    _send_msg(conn, reply, data)
        except (ConnectionError, OSError):
            pass

    @staticmethod
    def _slice_result(result: dict, rank: int, kind: str):
        if result["kind"] == "barrier":
            return {"barrier": True}, b""
        if result["kind"] in ("bcast", "allreduce"):
            return {"meta": result["meta"]}, result["payload"]
        if result["kind"] == "reduce":
            if rank == result["dst"]:
                return {"meta": result["meta"]}, result["payload"]
            return {"meta": None}, b""
        if result["kind"] == "allgather":
            return ({"metas": result["metas"],
                     "sizes": [len(p) for p in result["payloads"]]},
                    b"".join(result["payloads"]))
        if result["kind"] == "reducescatter":
            return {"meta": result["metas"][rank]}, result["payloads"][rank]
        raise ValueError(result["kind"])

    # ---- participant ----
    def _next_op(self) -> int:
        self._op_id += 1
        return self._op_id

    def _collective(self, kind: str, meta: dict, payload: bytes):
        op_id = self._next_op()
        if self.rank == 0 or self.world_size == 1:
            result = self._state.contribute(op_id, kind, 0, meta, payload,
                                            timeout=self._timeout)
            return self._slice_result(result, 0, kind)
        _send_msg(self._sock, {"kind": kind, "op_id": op_id, "meta": meta},
                  payload)
        reply, data = _recv_msg(self._sock)
        if "error" in reply:
            raise TimeoutError(reply["error"])
        return reply, data

    # ---- ring data plane (large tensors) ----
    # The hub is latency-optimal for control-sized tensors but serializes
    # all-to-hub bandwidth through one socket — wrong for gradients
    # (reference role: gloo's ring algorithms behind torch.distributed).
    # Large allreduces use a bidirectional ring of direct rank-to-rank
    # TCP connections: reduce-scatter + allgather, 2*(w-1) steps, each
    # rank moving 2*(w-1)/w of the tensor total.

    RING_MIN_BYTES = 1 << 16

    def _ensure_ring(self) -> bool:
        if self.world_size <= 2:
            return False  # ring degenerates to pairwise; hub is fine
        if getattr(self, "_ring_next", None) is not None:
            return True
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(2)
        port = listener.getsockname()[1]
        addr = f"127.0.0.1:{port}".encode().ljust(32, b"\0")
        addrs = self.allgather(np.frombuffer(addr, np.uint8))
        nxt = bytes(addrs[(self.rank + 1) % self.world_size]
                    ).rstrip(b"\0").decode()
        host, p = nxt.rsplit(":", 1)

        out: dict = {}

        lock = threading.Lock()

        def _connect():
            try:
                sock = socket.create_connection(
                    (host, int(p)), timeout=self._timeout)
            except OSError as e:  # surfaced by the join below
                out["err"] = e
                return
            with lock:
                if out.get("abandoned"):  # caller already gave up
                    sock.close()
                else:
                    out["sock"] = sock

        t = threading.Thread(target=_connect, daemon=True)
        t.start()
        prev_sock = None
        try:
            listener.settimeout(self._timeout)
            prev_sock, _ = listener.accept()
            # keep the configured timeout on both ring sockets so a
            # stalled (connected but silent) peer raises socket.timeout
            # instead of hanging recv forever — abort-not-hang applies
            # to the data plane
            prev_sock.settimeout(self._timeout)
            t.join(self._timeout)
            with lock:
                if "sock" not in out:
                    out["abandoned"] = True  # late connect self-closes
                    raise ConnectionError(
                        f"ring connect to rank "
                        f"{(self.rank + 1) % self.world_size}"
                        f" failed: {out.get('err')}")
        except BaseException:
            if prev_sock is not None:
                prev_sock.close()
            sock = out.get("sock")
            if sock is not None:
                sock.close()
            raise
        finally:
            listener.close()
        out["sock"].settimeout(self._timeout)
        self._ring_next = out["sock"]
        self._ring_prev = prev_sock
        return True

    def _ring_teardown(self):
        """Close and forget both ring sockets. A failed ring op leaves
        peers at different steps, so the connections are unusable; the
        next large allreduce rebuilds the ring from scratch (or fails the
        collective setup, which the caller handles)."""
        for name in ("_ring_next", "_ring_prev"):
            sock = getattr(self, name, None)
            if sock is not None:
                try:
                    sock.close()
                except Exception:
                    pass
            setattr(self, name, None)

    @staticmethod
    def _ring_send(sock: socket.socket, data: bytes):
        sock.sendall(_HDR.pack(len(data)) + data)

    @staticmethod
    def _ring_recv(sock: socket.socket) -> bytes:
        (n,) = _HDR.unpack(_recv_exact(sock, 4))
        return _recv_exact(sock, n)

    def _ring_step(self, send_bytes: bytes) -> bytes:
        """Full-duplex: push to next while pulling from prev (the send
        rides a thread so neither side can deadlock on full buffers;
        socket timeouts bound both directions)."""
        err: list = []

        def _send():
            try:
                self._ring_send(self._ring_next, send_bytes)
            except Exception as e:
                err.append(e)

        t = threading.Thread(target=_send, daemon=True)
        t.start()
        data = self._ring_recv(self._ring_prev)
        t.join(self._timeout)
        if t.is_alive() or err:
            # a lingering send thread would interleave with the next
            # step's frames — the ring is no longer trustworthy
            raise TimeoutError(
                f"ring send stalled/failed: {err or 'timeout'}")
        return data

    def _ring_allreduce(self, arr: np.ndarray, op: ReduceOp) -> np.ndarray:
        w = self.world_size
        flat = arr.reshape(-1)
        pad = (-len(flat)) % w
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, arr.dtype)])
        # MEAN matches the hub's np.mean semantics: float64 accumulate
        # and a float result for integer inputs (also dodges overflow)
        if op == ReduceOp.MEAN and not np.issubdtype(arr.dtype,
                                                     np.floating):
            flat = flat.astype(np.float64)
        work = flat.copy()
        chunk = len(work) // w
        combine = getattr(
            np, _NUMPY_REDUCE[ReduceOp.SUM if op == ReduceOp.MEAN
                              else ReduceOp(op)])

        def view(i):
            i %= w
            return work[i * chunk:(i + 1) * chunk]

        for step in range(w - 1):  # reduce-scatter
            send_idx = self.rank - step
            recv_idx = self.rank - step - 1
            incoming = self._ring_step(view(send_idx).tobytes())
            recv = view(recv_idx)
            # parse with the wire dtype (work.dtype): for integer MEAN the
            # work buffer — and therefore every frame on the ring — is
            # float64, not arr.dtype
            np.copyto(recv, combine(
                recv, np.frombuffer(incoming, work.dtype)))
        for step in range(w - 1):  # allgather of reduced chunks
            send_idx = self.rank + 1 - step
            recv_idx = self.rank - step
            incoming = self._ring_step(view(send_idx).tobytes())
            np.copyto(view(recv_idx), np.frombuffer(incoming, work.dtype))
        if op == ReduceOp.MEAN:
            work = work / w  # float result, like the hub's np.mean
        out = work[:arr.size].reshape(arr.shape)
        if op == ReduceOp.MEAN:
            return out
        return out.astype(arr.dtype, copy=False)

    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM):
        arr = np.ascontiguousarray(arr)
        if (arr.nbytes >= self.RING_MIN_BYTES and self.world_size > 2
                and not self._destroyed):
            if self._ensure_ring():  # collective all-or-nothing setup
                try:
                    return self._ring_allreduce(arr, ReduceOp(op))
                except Exception:
                    # abort-not-hang invariant: surface the failure (the
                    # SGD layer resizes); the broken ring never reused.
                    # Any exception mid-ring (transport OR dtype/shape
                    # mismatch) leaves peers desynced — always tear down.
                    self._ring_teardown()
                    raise
        reply, data = self._collective(
            "allreduce", {**_arr_meta(arr), "op": op.value}, arr.tobytes())
        return _arr_from(reply["meta"], data)

    def reduce(self, arr: np.ndarray, dst_rank: int = 0,
               op: ReduceOp = ReduceOp.SUM):
        arr = np.ascontiguousarray(arr)
        reply, data = self._collective(
            "reduce", {**_arr_meta(arr), "op": op.value, "dst": dst_rank},
            arr.tobytes())
        if self.rank == dst_rank:
            return _arr_from(reply["meta"], data)
        return arr

    def broadcast(self, arr: np.ndarray, src_rank: int = 0):
        arr = np.ascontiguousarray(arr)
        payload = arr.tobytes() if self.rank == src_rank else b""
        meta = {**_arr_meta(arr), "src": src_rank}
        reply, data = self._collective("broadcast", meta, payload)
        return _arr_from(reply["meta"], data)

    def allgather(self, arr: np.ndarray) -> list[np.ndarray]:
        arr = np.ascontiguousarray(arr)
        reply, data = self._collective("allgather", _arr_meta(arr),
                                       arr.tobytes())
        if "payloads" in reply:  # rank-0 local path
            return [_arr_from(m, p)
                    for m, p in zip(reply["metas"], reply["payloads"])]
        out, offset = [], 0
        for m, size in zip(reply["metas"], reply["sizes"]):
            out.append(_arr_from(m, data[offset:offset + size]))
            offset += size
        return out

    def reducescatter(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM):
        arr = np.ascontiguousarray(arr)
        reply, data = self._collective(
            "reducescatter", {**_arr_meta(arr), "op": op.value},
            arr.tobytes())
        return _arr_from(reply["meta"], data)

    def barrier(self):
        self._collective("barrier", {}, b"")

    def send(self, arr: np.ndarray, dst_rank: int, tag: int = 0):
        arr = np.ascontiguousarray(arr)
        if self.rank == 0:
            self._state.post(0, dst_rank, tag, _arr_meta(arr), arr.tobytes())
            return
        _send_msg(self._sock, {"kind": "p2p_send", "dst": dst_rank,
                               "tag": tag, "meta": _arr_meta(arr)},
                  arr.tobytes())
        _recv_msg(self._sock)  # ack

    def recv(self, src_rank: int, tag: int = 0) -> np.ndarray:
        if self.rank == 0:
            meta, data = self._state.take(src_rank, 0, tag)
            return _arr_from(meta, data)
        _send_msg(self._sock, {"kind": "p2p_recv", "src": src_rank,
                               "tag": tag})
        reply, data = _recv_msg(self._sock)
        return _arr_from(reply["meta"], data)

    def destroy(self):
        if self._destroyed:
            return
        self._destroyed = True
        self._ring_teardown()
        if self.rank == 0 and self.world_size > 1:
            try:
                self._listener.close()
            except Exception:
                pass
            from ray_tpu.experimental import internal_kv

            try:
                internal_kv._kv_del(self._key)
            except Exception:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except Exception:
                pass
