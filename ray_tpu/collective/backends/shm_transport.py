"""Intra-node shared-memory collective transport.

Every rank of a group that lands on one node maps the same tmpfs
segment (native/store segment alloc — the arena directory that makes
the object store do multi-GB/s).  A collective is then pure memory
traffic: each rank memcpys its contribution into its slot, synchronizes
through a counter barrier living in the segment header, reduces its
1/w stripe of the element range in place, and memcpys the result out —
zero socket syscalls, zero serialization, zero per-step copies.

Layout (one file, created zero-filled by rank 0):

    [0:32)            magic u64 | version u32 | world u32 | slot u64
                      | abort u64
    [32:48)           group cookie (16 random bytes, rendezvous check)
    [64 + r*64)       per-rank barrier counter (u64, cacheline stride)
    meta0 + r*256     per-rank op meta (u32 len | u64 seq | msgpack)
    data0 + r*slot    per-rank contribution slot
    res0  = data0 + w*slot, 2*slot bytes: reduction output stripes

Synchronization is a monotonic counter barrier: phase k of the group's
op stream is "every counter >= k".  Ranks execute the same collective
sequence by contract, so the phase numbers line up without any central
coordinator.  Abort-not-hang: a rank that times out (peer died) or hits
a hard error stamps the abort word; every other rank's barrier spin
sees it and raises TimeoutError instead of waiting out its full
deadline.  A tripped segment is never reused — the owning HostGroup
tears it down and rebuilds (or falls back to the TCP tiers).

Reduction order is fixed at rank 0..w-1 for every stripe, matching the
hub's sequential reduce, so SUM/MAX/MIN results are bit-identical to
the hub path even for non-associative float addition.  MEAN matches hub
np.mean semantics: float64 accumulate + float64 result for integer
inputs, float32 intermediates for float16, native-dtype accumulate for
wider floats.
"""

from __future__ import annotations

import struct
import time

import msgpack
import numpy as np

from ray_tpu._private import failpoints as _fp
from ray_tpu.collective.types import _NUMPY_REDUCE, ReduceOp

_MAGIC = 0x52545053484D5347  # "RTPSHMSG"
_VERSION = 1
_HDR = struct.Struct("<QIIQQ")  # magic, version, world, slot_bytes, abort
_ABORT_OFF = 24
_COOKIE_OFF = 32
_CTR0 = 64
_CTR_STRIDE = 64
_META_BYTES = 256


def _align(n: int, a: int = 4096) -> int:
    return (n + a - 1) // a * a


def segment_size(world_size: int, slot_bytes: int) -> int:
    return _data0(world_size) + (world_size + 2) * slot_bytes


def _meta0(world_size: int) -> int:
    return _CTR0 + world_size * _CTR_STRIDE


def _data0(world_size: int) -> int:
    return _align(_meta0(world_size) + world_size * _META_BYTES)


def split_bounds(n: int, w: int) -> list[int]:
    """np.array_split partition points: first n%w chunks get the extra
    element.  Shared by the shm stripes and the ring chunk schedule so
    reducescatter output matches the hub's array_split exactly."""
    base, extra = divmod(n, w)
    bounds = [0]
    for i in range(w):
        bounds.append(bounds[-1] + base + (1 if i < extra else 0))
    return bounds


def result_dtype(dtype: np.dtype, op: ReduceOp) -> np.dtype:
    """Reduction output dtype under hub semantics (np.mean promotes
    integer inputs to float64; everything else keeps the input dtype)."""
    if op == ReduceOp.MEAN and not np.issubdtype(dtype, np.floating):
        return np.dtype(np.float64)
    return np.dtype(dtype)


class ShmTransport:
    """One rank's handle on the group's shared segment."""

    def __init__(self, seg, world_size: int, rank: int, slot_bytes: int,
                 timeout: float):
        self._seg = seg
        self._view = seg.view
        self.world_size = world_size
        self.rank = rank
        self.slot_bytes = slot_bytes
        self._timeout = timeout
        self._seq = 0
        self._meta0 = _meta0(world_size)
        self._data0 = _data0(world_size)
        self._res0 = self._data0 + world_size * slot_bytes

    # -- setup ----------------------------------------------------------

    @classmethod
    def create(cls, name: str, cookie: bytes, world_size: int, rank: int,
               slot_bytes: int, timeout: float) -> "ShmTransport":
        from ray_tpu.native.store import create_segment

        if _fp.ARMED:
            # map seam: `raise` fails this rank's mapping -> the ok-flag
            # vote vetoes shm unanimously and the group falls back to the
            # socket tiers; `exit` kills the rank between create and the
            # join fence (the tmpfs-leak window the survivors must cover)
            _fp.fire_strict("shm.map")
        seg = create_segment(name, segment_size(world_size, slot_bytes))
        _HDR.pack_into(seg.view, 0, _MAGIC, _VERSION, world_size,
                       slot_bytes, 0)
        seg.view[_COOKIE_OFF:_COOKIE_OFF + 16] = cookie[:16]
        return cls(seg, world_size, rank, slot_bytes, timeout)

    @classmethod
    def open(cls, path: str, cookie: bytes, world_size: int, rank: int,
             slot_bytes: int, timeout: float) -> "ShmTransport":
        from ray_tpu.native.store import open_segment

        if _fp.ARMED:
            _fp.fire_strict("shm.map")
        seg = open_segment(path, segment_size(world_size, slot_bytes))
        magic, version, world, slot, _ = _HDR.unpack_from(seg.view, 0)
        if (magic != _MAGIC or version != _VERSION or world != world_size
                or slot != slot_bytes
                or bytes(seg.view[_COOKIE_OFF:_COOKIE_OFF + 16])
                != cookie[:16]):
            seg.close(unlink=False)
            raise ValueError(f"segment {path} failed the rendezvous check")
        return cls(seg, world_size, rank, slot_bytes, timeout)

    def close(self, unlink: bool | None = None):
        """Release the mapping. `unlink=None` keeps the creator-only
        default; survivors of a crashed peer pass unlink=True (idempotent)
        so the segment file cannot outlive the group when rank 0 — the
        owner — is the rank that died."""
        seg, self._seg, self._view = self._seg, None, None
        if seg is not None:
            seg.close(unlink=unlink)

    @property
    def path(self) -> str:
        return self._seg.path

    # -- barrier --------------------------------------------------------

    def _counter(self, r: int) -> int:
        return struct.unpack_from("<Q", self._view,
                                  _CTR0 + r * _CTR_STRIDE)[0]

    def _abort_word(self) -> int:
        return struct.unpack_from("<Q", self._view, _ABORT_OFF)[0]

    def abort(self):
        """Stamp the segment so every rank's barrier fails fast."""
        if self._view is not None:
            struct.pack_into("<Q", self._view, _ABORT_OFF, 1)

    def barrier(self, deadline: float | None = None,
                coarse: bool = False):
        """Advance to the next phase and wait for every rank to reach it.

        Spin-then-sleep: on an oversubscribed box (all ranks timeshare
        one core here) a pure spin would starve the very peers being
        waited on, so after a short yield phase the wait backs off to
        millisecond sleeps. `coarse` skips the yield phase entirely —
        for multi-MB ops the expected wait is tens of ms of peer
        memcpy, and every yield spin steals the core those memcpys
        need; a 1ms sleep costs nothing against that baseline.
        Timeout stamps the abort word (so peers abort too, not hang)
        and raises."""
        if deadline is None:
            deadline = time.monotonic() + self._timeout
        if _fp.ARMED:
            # barrier seam: `exit` kills this rank mid-phase (survivors
            # must abort within the group timeout, not hang); `raise`
            # models a rank erroring between post and fence — stamp the
            # abort word first so peers fail fast either way
            try:
                _fp.fire_strict("shm.barrier")
            except _fp.FailpointError:
                self.abort()
                raise
        self._seq += 1
        seq = self._seq
        struct.pack_into("<Q", self._view, _CTR0 + self.rank * _CTR_STRIDE,
                         seq)
        spins = 0
        while True:
            if all(self._counter(r) >= seq for r in range(self.world_size)):
                return
            if self._abort_word():
                raise TimeoutError(
                    "shm collective aborted by a peer (rank died or timed "
                    "out mid-op)")
            if time.monotonic() > deadline:
                self.abort()
                lag = [r for r in range(self.world_size)
                       if self._counter(r) < seq]
                raise TimeoutError(
                    f"shm barrier (phase {seq}) timed out waiting for "
                    f"ranks {lag}")
            spins += 1
            if coarse:
                time.sleep(0.0005)
            elif spins < 500:
                time.sleep(0)  # yield: peers share these cores
            else:
                time.sleep(min(0.001, 1e-5 * (spins - 500)))

    # -- per-op meta + payload ------------------------------------------

    def _slot(self, r: int) -> int:
        return self._data0 + r * self.slot_bytes

    _COARSE_BYTES = 1 << 20  # above this, barrier waits sleep coarsely

    def _post(self, meta: dict, payload: np.ndarray | None,
              deadline: float, coarse: bool = False):
        packed = msgpack.packb({**meta, "_seq": self._seq + 1},
                               use_bin_type=True)
        if len(packed) > _META_BYTES - 12:
            raise ValueError("collective meta too large for shm transport")
        off = self._meta0 + self.rank * _META_BYTES
        struct.pack_into("<IQ", self._view, off, len(packed), self._seq + 1)
        self._view[off + 12:off + 12 + len(packed)] = packed
        if payload is not None and payload.nbytes:
            # dtype-wide copy: measurably faster than a byte-view memcpy
            dst = np.frombuffer(self._view, payload.dtype, payload.size,
                                self._slot(self.rank))
            np.copyto(dst, payload.reshape(-1))
        self.barrier(deadline, coarse)

    def _read_metas(self, deadline: float) -> list[dict]:
        metas = []
        for r in range(self.world_size):
            off = self._meta0 + r * _META_BYTES
            while True:
                mlen, mseq = struct.unpack_from("<IQ", self._view, off)
                if mseq == self._seq and 0 < mlen <= _META_BYTES - 12:
                    meta = msgpack.unpackb(
                        bytes(self._view[off + 12:off + 12 + mlen]),
                        raw=False)
                    if meta.get("_seq") == self._seq:
                        metas.append(meta)
                        break
                # barrier ordering makes this unreachable on TSO hardware;
                # retry covers weaker memory models
                if time.monotonic() > deadline:
                    self.abort()
                    raise TimeoutError(f"shm meta from rank {r} not visible")
                time.sleep(0.0002)
        return metas

    def _validate(self, metas: list[dict], keys: tuple[str, ...]):
        head = {k: metas[0].get(k) for k in keys}
        for r, m in enumerate(metas[1:], 1):
            got = {k: m.get(k) for k in keys}
            if got != head:
                self.abort()
                raise ValueError(
                    f"mismatched shm collective: rank 0 {head} vs "
                    f"rank {r} {got}")

    def _in_view(self, r: int, dtype: np.dtype, lo: int, hi: int):
        isz = dtype.itemsize
        off = self._slot(r)
        return np.frombuffer(self._view, dtype, hi - lo, off + lo * isz)

    # -- collectives ----------------------------------------------------

    def _reduce_stripe(self, dtype: np.dtype, op: ReduceOp, lo: int,
                       hi: int, out: np.ndarray):
        """Reduce [lo, hi) of the flat element range across all slots
        into `out`, rank order 0..w-1 (hub-identical bits). Blocked into
        cache-sized chunks so the accumulator stays resident across the
        w passes — ~2.5x less memory traffic than streaming the full
        stripe through RAM once per rank."""
        if _fp.ARMED:
            # reduce seam: a rank dying (or erroring) with its stripe
            # half-written — peers must abort, and the poisoned segment
            # is never reused
            try:
                _fp.fire_strict("shm.reduce")
            except _fp.FailpointError:
                self.abort()
                raise
        if hi <= lo:
            return
        combine = getattr(np, _NUMPY_REDUCE[
            ReduceOp.SUM if op == ReduceOp.MEAN else op])
        # f16 MEAN accumulates in f32 like np.mean's intermediates (a
        # raw f16 add chain loses whole units at a few thousand)
        wide16 = op == ReduceOp.MEAN and dtype == np.float16
        blk = max(1, (1 << 16) // dtype.itemsize)
        for blo in range(lo, hi, blk):
            bhi = min(hi, blo + blk)
            ob = out[blo - lo:bhi - lo]
            acc = (self._in_view(0, dtype, blo, bhi).astype(np.float32)
                   if wide16 else ob)
            if not wide16:
                np.copyto(ob, self._in_view(0, dtype, blo, bhi),
                          casting="same_kind")
            for r in range(1, self.world_size):
                combine(acc, self._in_view(r, dtype, blo, bhi), out=acc,
                        casting="same_kind")
            if op == ReduceOp.MEAN:
                np.divide(acc, self.world_size, out=acc,
                          casting="same_kind")
            if wide16:
                np.copyto(ob, acc, casting="same_kind")

    def allreduce(self, arr: np.ndarray, op: ReduceOp) -> np.ndarray:
        deadline = time.monotonic() + self._timeout
        w = self.world_size
        rdt = result_dtype(arr.dtype, op)
        coarse = arr.nbytes >= self._COARSE_BYTES
        self._post({"k": "allreduce", "o": op.value, "d": arr.dtype.str,
                    "s": list(arr.shape)}, arr, deadline, coarse)
        self._validate(self._read_metas(deadline), ("k", "o", "d", "s"))
        bounds = split_bounds(arr.size, w)
        lo, hi = bounds[self.rank], bounds[self.rank + 1]
        res = np.frombuffer(self._view, rdt, hi - lo,
                            self._res0 + lo * rdt.itemsize)
        self._reduce_stripe(arr.dtype, op, lo, hi, res)
        self.barrier(deadline, coarse)  # all stripes written
        out = np.empty(arr.size, rdt)
        np.copyto(out, np.frombuffer(self._view, rdt, arr.size, self._res0))
        # No read-done barrier: a rank only posts the NEXT op after this
        # copy returns, and result-region writes for that op happen only
        # after its post barrier — which waits for every rank's post. The
        # slot-reading ops below do need their read fence (their slots
        # are overwritten by the very next post).
        return out.reshape(arr.shape)

    def reducescatter(self, arr: np.ndarray, op: ReduceOp) -> np.ndarray:
        deadline = time.monotonic() + self._timeout
        w = self.world_size
        rdt = result_dtype(arr.dtype, op)
        coarse = arr.nbytes >= self._COARSE_BYTES
        self._post({"k": "reducescatter", "o": op.value, "d": arr.dtype.str,
                    "s": list(arr.shape)}, arr, deadline, coarse)
        self._validate(self._read_metas(deadline), ("k", "o", "d", "s"))
        # hub semantics: np.array_split along axis 0 — row blocks are
        # contiguous element ranges in C order
        rows = arr.shape[0] if arr.ndim else 1
        rowsz = arr.size // rows if rows else 0
        rb = split_bounds(rows, w)
        lo, hi = rb[self.rank] * rowsz, rb[self.rank + 1] * rowsz
        out = np.empty(hi - lo, rdt)
        self._reduce_stripe(arr.dtype, op, lo, hi, out)
        self.barrier(deadline, coarse)  # reads done; segment reusable
        return out.reshape((rb[self.rank + 1] - rb[self.rank],)
                           + arr.shape[1:])

    def allgather(self, arr: np.ndarray) -> list[np.ndarray]:
        deadline = time.monotonic() + self._timeout
        coarse = arr.nbytes >= self._COARSE_BYTES
        self._post({"k": "allgather", "d": arr.dtype.str,
                    "s": list(arr.shape), "n": arr.nbytes}, arr, deadline,
                   coarse)
        metas = self._read_metas(deadline)
        self._validate(metas, ("k",))
        if any(m["n"] != metas[0]["n"] for m in metas[1:]):
            # ragged gather: every rank sees the same metas, so all fall
            # back to the hub together. The fence keeps barrier phases
            # aligned with the normal path (post + one more = 2).
            self.barrier(deadline, coarse)
            return None
        out = []
        for r, m in enumerate(metas):
            dt = np.dtype(m["d"])
            a = np.empty(m["n"] // dt.itemsize, dt)
            np.copyto(a, np.frombuffer(self._view, dt, a.size,
                                       self._slot(r)))
            out.append(a.reshape(m["s"]))
        self.barrier(deadline, coarse)
        return out

    def broadcast(self, arr: np.ndarray, src_rank: int) -> np.ndarray:
        deadline = time.monotonic() + self._timeout
        coarse = arr.nbytes >= self._COARSE_BYTES
        self._post({"k": "broadcast", "src": src_rank, "n": arr.nbytes},
                   arr if self.rank == src_rank else None, deadline, coarse)
        metas = self._read_metas(deadline)
        self._validate(metas, ("k", "src", "n"))
        if self.rank == src_rank:
            out = arr.copy()  # fresh writable result on every rank/tier
        else:
            out = np.empty(arr.size, arr.dtype)
            np.copyto(out, np.frombuffer(self._view, arr.dtype, arr.size,
                                         self._slot(src_rank)))
            out = out.reshape(arr.shape)
        self.barrier(deadline, coarse)
        return out
