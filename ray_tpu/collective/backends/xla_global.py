"""Global-mesh collectives: group ops across actor PROCESSES on the
accelerator plane.

When N actors have joined one jax.distributed runtime
(parallel/multihost.py), `collective.allreduce` from each of them should
ride XLA collectives over the global device mesh (ICI/DCN) — the
reference's NCCL-across-actors capability (reference:
python/ray/util/collective/collective.py:226 allreduce over
nccl_collective_group.py:115) — not the HOST TCP hub. Each process is
one collective RANK; its tensor becomes one row of a [world, ...] global
array sharded process-major, and every op is a tiny jitted reduction
whose cross-host traffic XLA lowers to the right collective.

Selected automatically: GroupManager routes backend="xla" here whenever
the multihost runtime is active and the group spans all its processes.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.collective.types import ReduceOp

_JNP_REDUCE = {
    ReduceOp.SUM: "sum",
    ReduceOp.PRODUCT: "prod",
    ReduceOp.MIN: "min",
    ReduceOp.MAX: "max",
    ReduceOp.MEAN: "mean",
}


class GlobalMeshGroup:
    """One rank per PROCESS of the active jax.distributed runtime."""

    def __init__(self, group_name: str, world_size: int, rank: int):
        import jax
        from jax.sharding import Mesh

        n_proc = jax.process_count()
        if world_size != n_proc:
            raise ValueError(
                f"global-mesh collective group needs one rank per joined "
                f"process: world_size={world_size} but "
                f"jax.process_count()={n_proc}")
        if rank != jax.process_index():
            raise ValueError(
                f"rank {rank} must equal jax.process_index() "
                f"{jax.process_index()} — the global runtime fixes rank "
                "order")
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        # row p MUST be process p's devices — jax.devices() is sorted by
        # id, and on 3-D TPU slices (v4/v5p) ids follow topology
        # coordinates, so one host's chips need not be contiguous; group
        # explicitly by process_index
        by_proc: dict[int, list] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, []).append(d)
        counts = {len(v) for v in by_proc.values()}
        if len(by_proc) != n_proc or len(counts) != 1:
            raise ValueError(
                f"unequal device counts per process: "
                f"{ {p: len(v) for p, v in by_proc.items()} }")
        rows = [by_proc[p] for p in sorted(by_proc)]
        self.mesh = Mesh(np.array(rows), ("proc", "local"))
        self._jits: dict = {}

    # -- plumbing --------------------------------------------------------

    def _global_rows(self, arr: np.ndarray):
        """This rank's tensor -> one row of a [world, ...] global array
        sharded along 'proc' (host data never leaves its process)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(
            self.mesh, P("proc", *([None] * arr.ndim)))
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(arr)[None])

    def _jit(self, key, fn):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if key not in self._jits:
            self._jits[key] = jax.jit(
                fn, out_shardings=NamedSharding(self.mesh, P()))
        return self._jits[key]

    def _reduce_rows(self, garr, op: ReduceOp):
        import jax.numpy as jnp

        name = _JNP_REDUCE[ReduceOp(op)]

        def fn(g):
            return getattr(jnp, name)(g, axis=0)

        return self._jit(("reduce", name, garr.shape, str(garr.dtype)),
                         fn)(garr)

    # -- op surface (mirrors host_backend) -------------------------------

    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM):
        out = self._reduce_rows(self._global_rows(arr), op)
        return np.asarray(out)

    def reduce(self, arr: np.ndarray, dst_rank: int = 0,
               op: ReduceOp = ReduceOp.SUM):
        out = self.allreduce(arr, op)
        return out if self.rank == dst_rank else arr

    def broadcast(self, arr: np.ndarray, src_rank: int = 0):
        import jax.numpy as jnp

        garr = self._global_rows(arr)
        out = self._jit(("bcast", src_rank, garr.shape, str(garr.dtype)),
                        lambda g: jnp.take(g, src_rank, axis=0))(garr)
        return np.asarray(out)

    def allgather(self, arr: np.ndarray) -> list[np.ndarray]:
        garr = self._global_rows(arr)
        out = self._jit(("gather", garr.shape, str(garr.dtype)),
                        lambda g: g)(garr)
        rows = np.asarray(out)
        return [rows[i] for i in range(self.world_size)]

    def reducescatter(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM):
        # HOST-backend semantics exactly (host_backend.py hub path):
        # reduce, then np.array_split along axis 0 — uneven leading dims
        # allowed, rank r gets chunk r with trailing dims intact
        total = self.allreduce(arr, op)
        return np.array_split(total, self.world_size, axis=0)[self.rank]

    def barrier(self):
        self.allreduce(np.zeros(1, np.float32))

    def send(self, arr, dst_rank: int, tag: int = 0):
        raise NotImplementedError(
            "point-to-point ops are HOST-backend only; the global mesh "
            "expresses transfers as collectives")

    recv = send

    def destroy(self):
        self._jits.clear()
