"""Transport.PALLAS: fused ICI ring collectives as single Pallas kernels.

The DEVICE tier (xla_backend._DeviceOps) expresses the EQuARX-style
quantized ring as a shard_map graph: one XLA op per quantize /
ppermute / dequantize / combine step, re-dispatched per hop. That is
the right shape for bandwidth-bound payloads, but a decode-step
allreduce (KBs, every token) pays the whole dispatch stack per op.
This tier fuses the ENTIRE schedule — quantize, `make_async_remote_copy`
DMA to the ICI ring neighbor, dequantize+combine, repeat for the
reduce-scatter phase, then quantize-once relay-gather — into ONE
`pallas_call`, so a small collective is a single kernel launch.

Kernel schedule (w ranks, per-rank flat payload split into w chunks of
C elements):

  reduce-scatter: acc := own chunk; for s in 1..w-1:
      [quantize acc ->] stage in a write-once send slot -> DMA to the
      right neighbor's recv slot for THIS hop -> wait on that slot's
      recv semaphore -> acc := combine(recv [dequantized], chunk
      (rank - s) mod w).  After w-1 hops rank r holds the reduced
      chunk (r+1) mod w (delta=0 schedule, same as the DEVICE qring).
  relay-gather: [quantize acc ONCE ->] w-1 relay hops forwarding the
      SAME bytes, every rank writes the received chunk into its output
      row — so in the quantized arm all ranks dequantize identical
      data and outputs agree bitwise across ranks.

Comm-slot discipline: every hop sends from one slot and receives into
a DIFFERENT slot, and no slot is written twice within one kernel
invocation (recv slot == hop index; staged sends are write-once).
A single slot serving as both DMA src and dst — or a 2-slot double
buffer reused across hops — races on real hardware: hop-lockstep is
enforced only by each rank's own recv wait, so an upstream neighbor
can run several hops ahead and its inbound DMA would overwrite bytes
the local outbound send engine is still reading. Unique slots make
that impossible by construction (the payloads here are small — this
is the latency tier — so O(world) slots of chunk size are cheap).

Neighbor ids ride scalar prefetch (`PrefetchScalarGridSpec`): the ring
position comes from `jax.lax.axis_index` OUTSIDE the kernel — a traced
value cannot be closure-captured by the kernel body.

Interpreter-mode contract: with `interpret=True` the remote-DMA
primitive discharges to `lax.all_gather` + dynamic indexing over the
mapped axis — real XLA collectives — so the IDENTICAL kernel runs on
CPU (including across jax.distributed process groups over gloo) and is
bit-exactness- and chaos-tested in tier-1; on a live TPU backend the
same schedule compiles through Mosaic. `interpret` is chosen per
process from `jax.default_backend()`.

PallasTransport subclasses DeviceTransport so every host-semantics
guarantee (integer MEAN promoting to float64 on the host, f16 MEAN
accumulating in f32, hub-style reducescatter splits, quantized-ring
padding) is inherited verbatim — only the op bodies change. Ops the
kernel tier does not carry (broadcast, shift_right, uneven
reducescatter fallbacks) delegate to an embedded _DeviceOps, which is
also the documented fallthrough for payloads above the routing layer's
`pallas_max_bytes` threshold.
"""

from __future__ import annotations

import functools

import numpy as np

from ray_tpu.collective.types import QUANT_BLOCK, ReduceOp

try:  # pragma: no cover - import guard mirrors xla_backend
    import jax
    import jax.numpy as jnp
except Exception:  # noqa: BLE001 - jax missing: the vote never turns 1
    jax = None
    jnp = None

from ray_tpu.collective.backends.xla_backend import (  # noqa: E402
    DeviceTransport, _DeviceOps, _shard_map, dequantize_blocks,
    quantize_blocks)

# combine step per reduce op inside the fused kernel (MEAN accumulates
# with add; the wrapper divides by world afterwards — DeviceTransport
# semantics)
_PALLAS_COMBINE = {
    ReduceOp.SUM: "add",
    ReduceOp.MEAN: "add",
    ReduceOp.MAX: "max",
    ReduceOp.MIN: "min",
    ReduceOp.PRODUCT: "mul",
}

_COMBINE_FNS = {
    "add": (lambda a, b: a + b),
    "max": (lambda a, b: jnp.maximum(a, b)),
    "min": (lambda a, b: jnp.minimum(a, b)),
    "mul": (lambda a, b: a * b),
}


def _interpret_mode() -> bool:
    """interpret=True everywhere but a real TPU backend: the pure-JAX
    reference path IS the tier on CPU test rigs (tier-1 runs the same
    kernel the TPU compiles through Mosaic)."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # noqa: BLE001
        return True


def _compiler_params(collective_id: int):
    """Mosaic compiler params for the non-interpret path (the kernel
    performs remote DMAs, so it must be marked side-effecting and carry
    a collective id); None under interpret where they are unused."""
    if _interpret_mode():
        return None
    from jax.experimental.pallas import tpu as pltpu

    try:
        return pltpu.TPUCompilerParams(has_side_effects=True,
                                       collective_id=collective_id)
    except TypeError:  # older field set: stay with defaults
        return None


def _ring_ids(axis: str, world: int):
    """(me, right-neighbor) as the int32 scalar-prefetch operand."""
    me = jax.lax.axis_index(axis).astype(jnp.int32)
    return jnp.stack([me, (me + 1) % world])


def _remote_copy(src_buf, src_slot, dst_buf, dst_slot, sem_s, sem_r,
                 right):
    """One ring hop: send src_buf[src_slot] into the right neighbor's
    dst_buf[dst_slot]. Src and dst are ALWAYS distinct slots and the
    semaphores are indexed by the dst slot, so `.wait()` waits on the
    recv semaphore of the slot the inbound DMA actually wrote."""
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.make_async_remote_copy(
        src_ref=src_buf.at[src_slot], dst_ref=dst_buf.at[dst_slot],
        send_sem=sem_s.at[dst_slot], recv_sem=sem_r.at[dst_slot],
        device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)


def _make_allreduce_kernel(world: int, chunk: int, combine: str):
    """Fused exact ring allreduce: reduce-scatter + relay-gather, w-1
    hops each. Sends are staged in write-once slots (`stage`), every
    hop receives into its own dedicated slot (`rbuf[hop]`) — no slot
    is reused, so no inbound DMA can overwrite bytes an outbound send
    is still reading."""
    import jax.experimental.pallas as pl

    cmb = _COMBINE_FNS[combine]

    def kernel(ids_ref, x_ref, o_ref, stage, rbuf, sem_s, sem_r):
        my, right = ids_ref[0], ids_ref[1]
        acc = x_ref[0, pl.ds(my * chunk, chunk)]
        for s in range(1, world):
            hop = s - 1
            stage[hop] = acc
            rdma = _remote_copy(stage, hop, rbuf, hop, sem_s, sem_r,
                                right)
            rdma.start()
            rdma.wait()
            acc = cmb(rbuf[hop],
                      x_ref[0, pl.ds(((my - s) % world) * chunk, chunk)])
        o_ref[0, pl.ds(((my + 1) % world) * chunk, chunk)] = acc
        stage[world - 1] = acc
        for s in range(1, world):
            hop = world - 1 + (s - 1)
            # hop 1 relays the staged reduced chunk; later hops relay
            # the previous hop's recv slot (written once, final)
            src_buf, src_slot = ((stage, world - 1) if s == 1
                                 else (rbuf, hop - 1))
            rdma = _remote_copy(src_buf, src_slot, rbuf, hop, sem_s,
                                sem_r, right)
            rdma.start()
            rdma.wait()
            o_ref[0, pl.ds(((my - s + 1) % world) * chunk, chunk)] = \
                rbuf[hop]

    return kernel


def _make_reducescatter_kernel(world: int, chunk: int):
    """Reduce-scatter phase only (SUM), delta=-1 schedule so rank r
    finishes holding reduced chunk r (psum_scatter tiled semantics).
    Same write-once slot discipline as the allreduce kernel."""
    import jax.experimental.pallas as pl

    def kernel(ids_ref, x_ref, o_ref, stage, rbuf, sem_s, sem_r):
        my, right = ids_ref[0], ids_ref[1]
        acc = x_ref[0, pl.ds(((my - 1) % world) * chunk, chunk)]
        for s in range(1, world):
            hop = s - 1
            stage[hop] = acc
            rdma = _remote_copy(stage, hop, rbuf, hop, sem_s, sem_r,
                                right)
            rdma.start()
            rdma.wait()
            acc = rbuf[hop] + x_ref[
                0, pl.ds(((my - 1 - s) % world) * chunk, chunk)]
        o_ref[0, :] = acc

    return kernel


def _make_allgather_kernel(world: int, width: int):
    """Relay ring allgather: own row copied out, then w-1 relay hops.
    `comm` has one slot per ring position — slot 0 holds the local
    row, hop s receives into slot s and forwards slot s-1 — so every
    slot is written exactly once."""
    import jax.experimental.pallas as pl

    def kernel(ids_ref, x_ref, o_ref, comm, sem_s, sem_r):
        my, right = ids_ref[0], ids_ref[1]
        o_ref[0, pl.ds(my * width, width)] = x_ref[0, :]
        comm[0] = x_ref[0, :]
        for s in range(1, world):
            rdma = _remote_copy(comm, s - 1, comm, s, sem_s, sem_r,
                                right)
            rdma.start()
            rdma.wait()
            o_ref[0, pl.ds(((my - s) % world) * width, width)] = comm[s]

    return kernel


def _make_quantized_allreduce_kernel(world: int, chunk: int, combine: str):
    """The fused EQuARX ring: every reduce hop re-quantizes the partial
    to int8 + per-block f32 scales (two DMAs per hop, payload+scales);
    the gather phase quantizes ONCE and relays the same bytes."""
    import jax.experimental.pallas as pl

    cmb = _COMBINE_FNS[combine]
    nblocks = chunk // QUANT_BLOCK

    def kernel(ids_ref, x_ref, o_ref, qstage, sstage, qrbuf, srbuf,
               qsem_s, qsem_r, ssem_s, ssem_r):
        my, right = ids_ref[0], ids_ref[1]

        def hop_dma(qsrc_buf, qsrc, ssrc_buf, ssrc, hop):
            r1 = _remote_copy(qsrc_buf, qsrc, qrbuf, hop,
                              qsem_s, qsem_r, right)
            r2 = _remote_copy(ssrc_buf, ssrc, srbuf, hop,
                              ssem_s, ssem_r, right)
            r1.start()
            r2.start()
            r1.wait()
            r2.wait()

        acc = x_ref[0, pl.ds(my * chunk, chunk)]
        for s in range(1, world):
            hop = s - 1
            q, sc = quantize_blocks(acc)
            qstage[hop] = q
            sstage[hop] = sc
            hop_dma(qstage, hop, sstage, hop, hop)
            acc = cmb(dequantize_blocks(qrbuf[hop], srbuf[hop]),
                      x_ref[0, pl.ds(((my - s) % world) * chunk, chunk)])
        q, sc = quantize_blocks(acc)
        qstage[world - 1] = q
        sstage[world - 1] = sc
        o_ref[0, pl.ds(((my + 1) % world) * chunk, chunk)] = \
            dequantize_blocks(q, sc)
        for s in range(1, world):
            hop = world - 1 + (s - 1)
            if s == 1:  # relay the staged quantized reduced chunk...
                hop_dma(qstage, world - 1, sstage, world - 1, hop)
            else:  # ...then forward the previous hop's recv slots
                hop_dma(qrbuf, hop - 1, srbuf, hop - 1, hop)
            o_ref[0, pl.ds(((my - s + 1) % world) * chunk, chunk)] = \
                dequantize_blocks(qrbuf[hop], srbuf[hop])

    assert nblocks * QUANT_BLOCK == chunk
    return kernel


class _PallasOps:
    """Cached jitted pallas_call collectives over one mesh axis — the
    fused-kernel mirror of xla_backend._DeviceOps (same [world, B] flat
    layout, same cache-key discipline: every compile-relevant input —
    op kind, combine fn, dtype, shape-class, axis name, world size — is
    in the key). Ops without a fused kernel delegate to an embedded
    _DeviceOps, the same bodies the DEVICE tier runs."""

    def __init__(self, mesh, axis: str, world: int):
        self.mesh = mesh
        self.axis = axis
        self.world = world
        self.interpret = _interpret_mode()
        self._cache: dict = {}
        self._fallback = _DeviceOps(mesh, axis, world)

    # -- plumbing -------------------------------------------------------

    def _pallas_call(self, kernel, out_len: int, dtype, scratch,
                     collective_id: int):
        import jax.experimental.pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        kwargs = {}
        params = _compiler_params(collective_id)
        if params is not None:
            kwargs["compiler_params"] = params
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1, out_len), dtype),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                in_specs=[pl.BlockSpec(
                    memory_space=pltpu.TPUMemorySpace.ANY)],
                out_specs=pl.BlockSpec(
                    memory_space=pltpu.TPUMemorySpace.ANY),
                scratch_shapes=scratch),
            interpret=self.interpret,
            **kwargs)

    def _jit(self, key, wrapper, out_specs=None):
        """First-call compile-recording cache, same contract as
        _DeviceOps._jit (the persistent compile cache hooks the same
        seam there; fused kernels re-trace per process — they are the
        latency tier, their compiles are small)."""
        fn = self._cache.get(key)
        if fn is None:
            from jax.sharding import PartitionSpec as P

            from ray_tpu._private import profiling as _profiling

            jitted = jax.jit(_shard_map(
                wrapper, self.mesh, P(self.axis, None),
                out_specs if out_specs is not None
                else P(self.axis, None)))

            def first_call(*args, _jitted=jitted, _key=key):
                import time as _time

                t0 = _time.time()
                out = _jitted(*args)
                _profiling.record_compile(
                    "pallas:" + ":".join(map(str, _key)),
                    t0, _time.time())
                self._cache[_key] = _jitted
                return out

            fn = self._cache[key] = first_call
        return fn

    @staticmethod
    def _pad_to_chunks(B: int, w: int) -> int:
        return w * (-(-B // w))

    # -- fused op surface (same signatures as _DeviceOps) --------------

    def allreduce(self, garr, op: ReduceOp):
        op = ReduceOp(op)
        kind = ReduceOp.SUM if op == ReduceOp.MEAN else op
        combine = _PALLAS_COMBINE.get(kind)
        if combine is None:  # op without a fused combine: DEVICE bodies
            return self._fallback.allreduce(garr, op)
        w, axis = self.world, self.axis
        B = garr.shape[1]
        Bp = self._pad_to_chunks(B, w)
        C = Bp // w
        key = ("par", combine, garr.dtype.name, B, axis, w)
        kernel = _make_allreduce_kernel(w, C, combine)

        def wrapper(x):
            ids = _ring_ids(axis, w)
            xp = jnp.pad(x, ((0, 0), (0, Bp - B))) if Bp > B else x
            out = self._pallas_call(
                kernel, Bp, x.dtype,
                self._scratch_allreduce(C, x.dtype),
                collective_id=1)(ids, xp)
            return out[:, :B]

        return self._jit(key, wrapper)(garr)

    def allgather(self, garr):
        from jax.sharding import PartitionSpec as P

        w, axis = self.world, self.axis
        B = garr.shape[1]
        key = ("pag", garr.dtype.name, B, axis, w)
        kernel = _make_allgather_kernel(w, B)

        def wrapper(x):
            ids = _ring_ids(axis, w)
            out = self._pallas_call(
                kernel, w * B, x.dtype,
                self._scratch_allgather(B, x.dtype),
                collective_id=2)(ids, x)
            return out.reshape(1, w, B)

        return self._jit(key, wrapper, P(axis, None, None))(garr)

    def reducescatter_even(self, garr):
        w, axis = self.world, self.axis
        P_len = garr.shape[1]
        if P_len % w:  # caller guarantees divisibility; stay safe
            return self._fallback.reducescatter_even(garr)
        C = P_len // w
        key = ("prs", garr.dtype.name, P_len, axis, w)
        kernel = _make_reducescatter_kernel(w, C)

        def wrapper(x):
            ids = _ring_ids(axis, w)
            return self._pallas_call(
                kernel, C, x.dtype,
                self._scratch_reducescatter(C, x.dtype),
                collective_id=3)(ids, x)

        return self._jit(key, wrapper)(garr)

    def allreduce_quantized(self, garr, op: ReduceOp):
        """garr: [w, w*C] float32, C % QUANT_BLOCK == 0 (the caller
        pads with _qring_pad — identical layout to the DEVICE qring)."""
        op = ReduceOp(op)
        combine = _PALLAS_COMBINE[op]
        w, axis = self.world, self.axis
        B = garr.shape[1]
        C = B // w
        key = ("pqar", combine, garr.dtype.name, B, axis, w, QUANT_BLOCK)
        kernel = _make_quantized_allreduce_kernel(w, C, combine)

        def wrapper(x):
            ids = _ring_ids(axis, w)
            return self._pallas_call(
                kernel, B, jnp.float32,
                self._scratch_quantized(C), collective_id=4)(ids, x)

        return self._jit(key, wrapper)(garr)

    # -- unfused ops: the documented DEVICE fallthrough ----------------

    def broadcast(self, garr, src: int):
        return self._fallback.broadcast(garr, src)

    def shift_right(self, garr):
        return self._fallback.shift_right(garr)

    # -- scratch shapes -------------------------------------------------
    #
    # Slot counts follow the write-once discipline: `stage` holds one
    # slot per staged send (w-1 reduce-scatter sends + 1 gather stage),
    # recv buffers one slot per hop, DMA semaphores one pair per recv
    # slot. max(1, ...) keeps world==1 (no hops at all) allocatable.

    def _scratch_allreduce(self, chunk: int, dtype):
        from jax.experimental.pallas import tpu as pltpu

        hops = max(1, 2 * (self.world - 1))
        return [pltpu.VMEM((self.world, chunk), jnp.dtype(dtype)),
                pltpu.VMEM((hops, chunk), jnp.dtype(dtype)),
                pltpu.SemaphoreType.DMA((hops,)),
                pltpu.SemaphoreType.DMA((hops,))]

    def _scratch_reducescatter(self, chunk: int, dtype):
        from jax.experimental.pallas import tpu as pltpu

        hops = max(1, self.world - 1)
        return [pltpu.VMEM((hops, chunk), jnp.dtype(dtype)),
                pltpu.VMEM((hops, chunk), jnp.dtype(dtype)),
                pltpu.SemaphoreType.DMA((hops,)),
                pltpu.SemaphoreType.DMA((hops,))]

    def _scratch_allgather(self, width: int, dtype):
        from jax.experimental.pallas import tpu as pltpu

        return [pltpu.VMEM((self.world, width), jnp.dtype(dtype)),
                pltpu.SemaphoreType.DMA((self.world,)),
                pltpu.SemaphoreType.DMA((self.world,))]

    def _scratch_quantized(self, chunk: int):
        from jax.experimental.pallas import tpu as pltpu

        hops = max(1, 2 * (self.world - 1))
        return [pltpu.VMEM((self.world, chunk), jnp.int8),
                pltpu.VMEM((self.world, chunk // QUANT_BLOCK),
                           jnp.float32),
                pltpu.VMEM((hops, chunk), jnp.int8),
                pltpu.VMEM((hops, chunk // QUANT_BLOCK), jnp.float32),
                pltpu.SemaphoreType.DMA((hops,)),
                pltpu.SemaphoreType.DMA((hops,)),
                pltpu.SemaphoreType.DMA((hops,)),
                pltpu.SemaphoreType.DMA((hops,))]


class PallasTransport(DeviceTransport):
    """Transport.PALLAS: DeviceTransport's host-parity op surface over
    _PallasOps fused kernels. Rank/mesh validation, payload lifting,
    MEAN/dtype promotion rules and quantized-ring padding are inherited
    — the tiers differ only in what one op costs, never in what it
    returns."""

    def __init__(self, world_size: int, rank: int):
        super().__init__(world_size, rank)
        self._ops = _PallasOps(self.mesh, self.AXIS, world_size)

    def _counted(self):
        from ray_tpu.collective import metrics as _cm

        _cm.PALLAS_OPS.inc()


@functools.lru_cache(maxsize=1)
def pallas_supported() -> bool:
    """Whether this process can build the fused-kernel tier at all
    (pallas importable; jax present). Cheap group-uniform fact for the
    topology deriver and the routing vote."""
    if jax is None:
        return False
    try:
        import importlib

        importlib.import_module("jax.experimental.pallas")
        importlib.import_module("jax.experimental.pallas.tpu")
        return True
    except Exception:  # noqa: BLE001
        return False
