"""Declarative collective groups across actors/tasks (API parity with the
reference: python/ray/util/collective/collective.py — GroupManager :29,
init_collective_group :93, create_collective_group :126, allreduce :226,
barrier :266, reduce :279, broadcast :340, allgather :391, reducescatter,
send :496, recv :550)."""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from ray_tpu.collective.types import (Backend, ReduceOp, Transport,
                                      is_jax_array, normalize_quantize)


class GroupManager:
    """Per-process registry of collective groups (reference:
    collective.py:29)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: dict[str, Any] = {}

    def create_group(self, group_name: str, world_size: int, rank: int,
                     backend: Backend, timeout: float = 60.0,
                     transport: str = "auto", quantize=None,
                     placement_plan: dict | None = None):
        backend = Backend(backend)
        quantize = normalize_quantize(quantize)
        if backend == Backend.AUTO:
            backend = Backend.XLA if world_size == 1 else Backend.HOST
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(f"group {group_name!r} already exists")
        if backend == Backend.HOST:
            from ray_tpu.collective.backends.host_backend import HostGroup

            group = HostGroup(group_name, world_size, rank, timeout=timeout,
                              transport=Transport(transport).value,
                              quantize=quantize,
                              placement_plan=placement_plan)
        else:
            from ray_tpu.parallel import multihost

            def _spans_processes() -> bool:
                if world_size <= 1 or not multihost.is_initialized():
                    return False
                import jax

                # only a one-rank-per-process group rides the global
                # mesh; other sizes are single-controller device groups
                return world_size == jax.process_count()

            # both device-group flavors live in xla_backend.py (the
            # former xla_global.GlobalMeshGroup is unified there)
            from ray_tpu.collective.backends.xla_backend import (
                ProcessMeshGroup, XlaGroup)

            if _spans_processes():
                # N actor processes joined one jax.distributed runtime:
                # group ops ride XLA collectives over the global mesh
                # (the NCCL-across-actors capability)
                group = ProcessMeshGroup(group_name, world_size, rank,
                                         quantize=quantize)
            else:
                group = XlaGroup(group_name, quantize=quantize)
        with self._lock:
            self._groups[group_name] = group
        return group

    def get_group(self, group_name: str):
        with self._lock:
            group = self._groups.get(group_name)
        if group is None:
            raise ValueError(
                f"collective group {group_name!r} is not initialized in "
                f"this process; call init_collective_group first")
        return group

    def destroy_group(self, group_name: str):
        with self._lock:
            group = self._groups.pop(group_name, None)
        if group is not None:
            group.destroy()

    def debug_state(self) -> list[dict]:
        """Live rows for every group in this process (debug_state.py /
        `ray-tpu state collectives`): backends exposing their own
        debug_state (HostGroup: current op + phase + age) use it; the
        rest report membership only."""
        with self._lock:
            groups = list(self._groups.items())
        out = []
        for name, group in groups:
            fn = getattr(group, "debug_state", None)
            if callable(fn):
                try:
                    out.append(fn())
                    continue
                except Exception:
                    pass
            out.append({"group": name,
                        "rank": int(getattr(group, "rank", 0)),
                        "world_size": int(getattr(group, "world_size", 1)),
                        "backend": type(group).__name__,
                        "op": "", "phase": "idle", "age_s": 0.0})
        return out


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default",
                          timeout: float = 60.0,
                          transport: str = "auto",
                          quantize=None,
                          placement_plan: dict | None = None):
    """Initialize this process's membership in a collective group
    (reference: collective.py:93). Call from inside each participating
    actor/task with its rank. `transport` pins the HOST data plane to
    one tier (hub/ring/ring_unpipelined/shm/device); "auto" routes per
    op. `quantize="int8"` makes this group's default allreduce wire
    format block-scaled int8 (EQuARX-style, lossy) on the tiers that
    have a wire (ring/device); per-op `allreduce(..., quantize=...)`
    overrides it. `placement_plan` (topology.transport_plan output)
    pins the tier FROM the gang's placement record instead of the
    probe round — see create_collective_group(placement_group=...)."""
    return _manager.create_group(group_name, world_size, rank,
                                 Backend(backend), timeout=timeout,
                                 transport=transport, quantize=quantize,
                                 placement_plan=placement_plan)


def placement_transport_plan(pg) -> dict | None:
    """Resolve a PlacementGroup (or its id bytes) to the topology
    transport plan its record carries, or None for ad-hoc/fallback
    groups (which keep the probe round)."""
    from ray_tpu._private import global_state
    from ray_tpu._private import topology as _topo

    cw = global_state.get_core_worker()
    if pg is None or cw is None:
        return None
    pg_id = pg if isinstance(pg, bytes) else pg.id.binary()
    try:
        record = cw.get_placement_group(pg_id)
    except Exception:
        return None
    return _topo.transport_plan(record)


def create_collective_group(actors, world_size: int, ranks: list[int],
                            backend: str = "host",
                            group_name: str = "default",
                            timeout: float = 60.0,
                            quantize=None,
                            transport: str = "auto",
                            placement_group=None):
    """Driver-side declarative setup (reference: collective.py:126): tells
    every actor in `actors` to init the group with its rank.

    `placement_group`: the gang's reservation. When its record carries
    an ICI_RING topology plan and `transport` is "auto", every rank's
    tier is DERIVED from the placement (shm when the ring landed on one
    host, device/ring/hub otherwise) and the per-op probe rounds are
    skipped — counted by `collective.transport_derived_total`. Records
    without a plan (PACK fallback, ad-hoc groups) keep probing."""
    import ray_tpu

    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("actors/ranks/world_size mismatch")
    plan = None
    if placement_group is not None and transport == "auto":
        plan = placement_transport_plan(placement_group)
    refs = [
        actor.__ray_collective_init__.remote(world_size, rank, backend,
                                             group_name, timeout, quantize,
                                             transport, plan)
        for actor, rank in zip(actors, ranks)
    ]
    return ray_tpu.get(refs, timeout=120)


def declare_collective_group(actors, world_size: int, ranks: list[int],
                             backend: str = "host",
                             group_name: str = "default"):
    return create_collective_group(actors, world_size, ranks, backend,
                                   group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _manager.get_group(group_name)
        return True
    except ValueError:
        return False


def destroy_collective_group(group_name: str = "default"):
    _manager.destroy_group(group_name)


def get_rank(group_name: str = "default") -> int:
    group = _manager.get_group(group_name)
    return getattr(group, "rank", 0)


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get_group(group_name).world_size


def _as_numpy(tensor) -> np.ndarray:
    if isinstance(tensor, np.ndarray):
        return tensor
    return np.asarray(tensor)


def _prep(tensor):
    """Normalize an op payload WITHOUT forcing device arrays to host:
    jax.Arrays pass through untouched (the DEVICE tier and the XLA
    backend consume them in place — pulling them to numpy here would
    defeat the whole ICI plane), everything else becomes numpy."""
    if isinstance(tensor, np.ndarray) or is_jax_array(tensor):
        return tensor
    return np.asarray(tensor)


def _traced_op(name: str, group_name: str, fn, nbytes: int | None = None):
    """Collective trace entry point (tracing.py): continues an ambient
    trace (op inside a traced task/replica call) or head-samples a fresh
    root, recording one `collective.<op>` span over the op. The
    `collective.op_s` histogram observes EVERY call (sampled or not),
    with the sampled caller's trace id as its exemplar."""
    import time as _time

    from ray_tpu._private import tracing
    from ray_tpu.collective import metrics as _metrics

    ctx = tracing.maybe_trace()
    t0 = _time.time()
    if ctx is None:
        try:
            return fn()
        finally:
            _metrics.OP_S.observe(_time.time() - t0)
    extra = {"group": group_name}
    if nbytes is not None:
        extra["bytes"] = nbytes
    try:
        with tracing.span(name, ctx, extra, ambient=True):
            return fn()
    finally:
        _metrics.OP_S.observe(_time.time() - t0,
                              exemplar=tracing.exemplar_of(ctx))


def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM, quantize=None):
    """`quantize` (None = the group's default; "int8" = block-scaled
    int8 wire format; False = force exact) applies on the tiers that
    have a wire to compress — the DEVICE ppermute ring and the host
    TCP ring. hub/shm always carry exact payloads."""
    group = _manager.get_group(group_name)
    t = _prep(tensor)
    return _traced_op("collective.allreduce", group_name,
                      lambda: group.allreduce(t, op, quantize=quantize),
                      t.nbytes)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    group = _manager.get_group(group_name)
    t = _prep(tensor)
    return _traced_op("collective.reduce", group_name,
                      lambda: group.reduce(t, dst_rank, op), t.nbytes)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    group = _manager.get_group(group_name)
    t = _prep(tensor)
    return _traced_op("collective.broadcast", group_name,
                      lambda: group.broadcast(t, src_rank), t.nbytes)


def allgather(tensor, group_name: str = "default"):
    group = _manager.get_group(group_name)
    t = _prep(tensor)
    return _traced_op("collective.allgather", group_name,
                      lambda: group.allgather(t), t.nbytes)


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM, quantize=None):
    """quantize: per-op wire codec override ("int8" / None), same
    semantics as the group-construction default — the sharded trainer's
    grad bucket rides this knob."""
    group = _manager.get_group(group_name)
    t = _prep(tensor)
    return _traced_op("collective.reducescatter", group_name,
                      lambda: group.reducescatter(t, op, quantize=quantize),
                      t.nbytes)


def barrier(group_name: str = "default"):
    group = _manager.get_group(group_name)
    _traced_op("collective.barrier", group_name, group.barrier)


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0):
    _manager.get_group(group_name).send(_as_numpy(tensor), dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    return _manager.get_group(group_name).recv(src_rank, tag)


class CollectiveActorMixin:
    """Mixin giving an actor class the __ray_collective_init__ hook used by
    create_collective_group."""

    def __ray_collective_init__(self, world_size, rank, backend, group_name,
                                timeout=60.0, quantize=None,
                                transport="auto", placement_plan=None):
        init_collective_group(world_size, rank, backend, group_name,
                              timeout=timeout, quantize=quantize,
                              transport=transport,
                              placement_plan=placement_plan)
        return rank
