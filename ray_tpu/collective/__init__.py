from ray_tpu.collective.collective import (
    CollectiveActorMixin,
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    declare_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.collective.types import Backend, ReduceOp, Transport

__all__ = [
    "Backend", "CollectiveActorMixin", "ReduceOp", "Transport",
    "allgather", "allreduce",
    "barrier", "broadcast", "create_collective_group",
    "declare_collective_group", "destroy_collective_group",
    "get_collective_group_size", "get_rank", "init_collective_group",
    "is_group_initialized", "recv", "reduce", "reducescatter", "send",
]
