"""Raylet — the per-node manager process.

Capability parity with the reference raylet (reference: src/ray/raylet/
node_manager.h:133): grants worker leases (HandleRequestWorkerLease,
node_manager.cc:1318), runs the local scheduler with spillback
(src/ray/raylet/scheduling/cluster_resource_scheduler.cc:217 hybrid policy),
manages the pool of Python worker processes (worker_pool.h:92), tracks and
transfers local objects (object_manager.h:107 + local_object_manager.h:38
spilling), and executes the GCS's actor-creation and placement-group bundle
requests (placement_group_resource_manager.h:51 2PC prepare/commit).

Differences by design: task *data* never flows through the raylet — owners
push tasks directly to leased workers over their own connections (same
direct-call architecture as the reference's CoreWorkerDirectTaskSubmitter);
the raylet is control-plane plus bulk object transfer only.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import subprocess
import sys
import time

from ray_tpu._private import debug_state as _debug
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import rpc
from ray_tpu._private import sampling_profiler as _sprof
from ray_tpu._private import topology as _topo
from ray_tpu._private import tracing
from ray_tpu._private.common import InsufficientResources, ResourceSet
from ray_tpu._private.config import Config, get_config, set_config
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.object_store import make_store
from ray_tpu.raylet import transfer

logger = logging.getLogger("ray_tpu.raylet")


class WorkerHandle:
    def __init__(self, worker_id: bytes, address: str, pid: int, conn):
        self.worker_id = worker_id
        self.address = address
        self.pid = pid
        self.conn = conn
        self.actor_id: bytes | None = None
        self.lease_id: bytes | None = None
        self.lease_resources: ResourceSet | None = None
        self.lease_pg: tuple[bytes, int] | None = None
        self.flavor: str = "cpu"  # "cpu" | "tpu" — which env it spawned with
        self.task_channel: str = ""  # same-node direct task UDS ("" = none)


class Raylet:
    def __init__(self, *, node_id: NodeID, session_dir: str, gcs_address: str,
                 resources: dict[str, float], store_root: str,
                 is_head: bool, labels: dict[str, str], config: Config,
                 tpu_slice: dict | None = None,
                 topology: dict | None = None):
        self.node_id = node_id
        self.session_dir = session_dir
        self.gcs_address = gcs_address
        self.config = config
        self.is_head = is_head
        self.labels = labels
        # this node's position in the pod's physical shape (topology.py):
        # explicit (--topology / cluster_utils), RAY_TPU_TOPOLOGY env, or
        # derived from the slice descriptor — deterministic, so a raylet
        # restart lands on the same coord. None = unlocated (ICI_RING
        # counts the fallback; spillback ordering stays random).
        self.topology = _topo.derive_coord(
            node_id_hex=node_id.hex(), tpu_slice=tpu_slice,
            labels=labels, explicit=topology)
        # TPU slice membership (util/accelerators.TpuSliceDescriptor as a
        # dict): declares this host's ICI domain. Implies TPU chips and
        # the accelerator_type:<gen> constraint resource if absent.
        self.tpu_slice = tpu_slice
        if tpu_slice:
            from ray_tpu.util.accelerators import accelerator_resource

            resources = dict(resources)
            resources.setdefault("TPU",
                                 float(tpu_slice["chips_per_host"]))
            resources.setdefault(
                accelerator_resource(tpu_slice["generation"]), 1.0)
        self.total = ResourceSet(resources)
        self.available = self.total.copy()
        self.store = make_store(store_root, config)
        self.store_root = store_root

        # worker pool — two flavors: plain CPU workers (TPU-plugin env
        # stripped) and TPU workers (plugin env restored). A worker's
        # flavor is fixed at spawn; leases route to the matching pool so
        # only leases that declare TPU resources ever run in a process
        # that can claim the chip.
        self.workers: dict[bytes, WorkerHandle] = {}  # registered, by worker_id
        self.idle: list[WorkerHandle] = []
        self.idle_tpu: list[WorkerHandle] = []
        self.starting = 0
        self.starting_tpu = 0
        self._worker_waiters: list[tuple[asyncio.Future, bool]] = []
        # Spawned-but-unregistered worker processes, so a worker that dies
        # during startup (plugin import error, chip already claimed, OOM)
        # is reaped and its `starting` slot released instead of wedging
        # _pop_worker forever.
        self._starting_procs: list = []  # [(Popen, flavor)]
        self._warned_infeasible: set[tuple] = set()
        self._metric_merge_logged: set[str] = set()

        # metrics (reference: src/ray/stats/metric_defs.cc raylet set)
        from ray_tpu._private import stats

        self.m_leases_granted = stats.Count(
            "raylet.leases_granted_total", "worker leases granted")
        self.m_spillbacks = stats.Count(
            "raylet.spillbacks_total", "lease requests redirected away")
        self.m_workers_started = stats.Count(
            "raylet.workers_started_total", "worker processes spawned")
        self.m_objects_pulled = stats.Count(
            "raylet.objects_pulled_total", "objects pulled from peers")
        self.m_locality_spillbacks = stats.Count(
            "raylet.locality_spillbacks_total",
            "lease requests redirected to the node holding their args")
        self.m_spillback_forwards = stats.Count(
            "raylet.spillback_forwards_total",
            "lease requests forwarded raylet->raylet instead of bounced "
            "back to the owner")
        self.m_spillback_grants = stats.Count(
            "raylet.spillback_grants_total",
            "leases granted here for a forwarded (spillback-chain) request")
        self.m_topo_reroutes = stats.Count(
            "raylet.spillback_topo_reroutes_total",
            "spillback/locality decisions where the topology distance "
            "metric differentiated the candidates and picked a nearer "
            "node than a blind choice could guarantee")
        self.m_lease_grant_s = stats.Histogram(
            "raylet.lease_grant_s", stats.LATENCY_BOUNDARIES_S,
            "lease request arrival -> grant (queue + worker startup)")
        self.m_drains = stats.Count(
            "raylet.drains_total", "graceful drains started on this raylet")
        self.m_drain_migrated_bytes = stats.Count(
            "raylet.drain_migrated_bytes_total",
            "plasma bytes pushed to survivors during drain")
        self.num_cpus = int(resources.get("CPU", os.cpu_count() or 1))

        # trace spans (tracing.py) recorded by this raylet — lease grants
        # and object-transfer hops — flushed to the GCS on the heartbeat
        # cadence (~2s)
        from ray_tpu._private.profiling import ProfileBuffer

        self._profile = ProfileBuffer("raylet")
        tracing.bind_buffer(self._profile)
        self._last_profile_flush = 0.0
        self._beat_n = 0

        # scheduling
        self._lease_seq = 0
        self.pending_leases: list[tuple[dict, asyncio.Future]] = []
        # lease_id -> monotonic deadline for grants made on behalf of a
        # FORWARDED request (spillback chain): the true holder (the task
        # owner) claims them via adopt_leases over its own connection;
        # one that never does (owner died between grant and adoption) is
        # reclaimed by the reap loop at the deadline.
        self._unadopted: dict[bytes, float] = {}

        # placement group bundles: (pg_id, index) -> {"resources", "available",
        # "state"}
        self.bundles: dict[tuple[bytes, int], dict] = {}
        # pg_id -> resources leased out of bundles that were since removed;
        # returned to self.available as those leases end.
        self._removed_bundles: dict[bytes, ResourceSet] = {}

        # object manager
        self.local_objects: dict[bytes, dict] = {}  # oid -> {size, pinned, spilled}
        self.object_waiters: dict[bytes, list[asyncio.Future]] = {}
        self.store_used = 0
        self.spill_dir = os.path.join(session_dir, "spill")
        self._pulls_inflight: set[bytes] = set()
        self._pull_sem_obj = None

        # bulk transfer data plane (raylet/transfer.py): dedicated
        # streaming channel for object bytes, sender-side transfer pins,
        # and the A/B switch back to the round-8 stop-and-wait path
        self.transfer_pins = transfer.TransferPins()
        self.bulk = transfer.BulkTransferServer(self)
        self.bulk_address = ""
        self._loop: asyncio.AbstractEventLoop | None = None
        self._pull_mode_legacy: bool | None = None  # None = env-driven
        # arg-id set -> monotonic expiry of a NO-redirect locality
        # decision: repeated lease requests for the SAME pending task's
        # args (the retry/escalation pattern) skip the per-request GCS
        # directory round trip. Keyed by the args — not the scheduling
        # key — so one small-arg call can't suppress redirects for a
        # later call of the same function with different, remote-resident
        # args. Positive redirects are never cached (must see fresh
        # locations).
        self._locality_negcache: dict[tuple, float] = {}

        # cluster view (from GCS pubsub)
        self.cluster_nodes: dict[bytes, dict] = {}

        self.gcs: rpc.Connection | None = None
        self.server = rpc.Server(self._handlers(),
                                 on_disconnect=self._on_disconnect,
                                 name="raylet")
        self.address = ""  # tcp address, set in run()
        self._raylet_conns: dict[str, rpc.Connection] = {}
        self._raylet_dial_locks: dict[str, asyncio.Lock] = {}
        self._shutting_down = False
        # Elastic membership: set by h_drain (GCS-initiated or a
        # preemption notice). A draining raylet grants no new leases,
        # reserves no bundles, and is skipped as a spillback/locality
        # target by peers (they read state=DRAINING off the nodes
        # channel); the background _drain task migrates plasma to
        # survivors, waits out in-flight leases, checkpoints actors,
        # then exits through node_drained — never the crash path.
        self._draining = False
        self._drain_task: asyncio.Task | None = None

    def _handlers(self):
        return {
            # worker/driver-facing
            "register_client": self.h_register_client,
            "request_worker_lease": self.h_request_worker_lease,
            "adopt_leases": self.h_adopt_leases,
            "return_worker": self.h_return_worker,
            "notify_object_sealed": self.h_notify_object_sealed,
            "wait_object_local": self.h_wait_object_local,
            "hint_pull_purpose": self.h_hint_pull_purpose,
            "free_objects": self.h_free_objects,
            "pin_object": self.h_pin_object,
            "spill_now": self.h_spill_now,
            "get_logs": self.h_get_logs,
            "cluster_info": self.h_cluster_info,
            "get_metrics": self.h_get_metrics,
            "set_resource": self.h_set_resource,
            "actor_exiting": self.h_actor_exiting,
            # gcs-facing
            "drain": self.h_drain,
            "create_actor": self.h_create_actor,
            "kill_actor_worker": self.h_kill_actor_worker,
            "prepare_bundle": self.h_prepare_bundle,
            "commit_bundle": self.h_commit_bundle,
            "cancel_bundle": self.h_cancel_bundle,
            "return_bundle": self.h_return_bundle,
            # raylet-to-raylet object transfer
            "object_info": self.h_object_info,
            "fetch_chunk": self.h_fetch_chunk,
            "push_hint": self.h_push_hint,
            "push_objects_to": self.h_push_objects_to,
            "transfer_done": self.h_transfer_done,
            "set_transfer_mode": self.h_set_transfer_mode,
            "peer_ping": self.h_peer_ping,
            "debug_state": self.h_debug_state,
            "debug_stacks": lambda conn, d: _debug.collect_stacks(),
            "ping": lambda conn, d: "pong",
        }

    # ------------------------------------------------------------------
    # worker pool (reference: src/ray/raylet/worker_pool.h)
    # ------------------------------------------------------------------

    def _start_worker_process(self, tpu: bool = False):
        if _fp.ARMED:
            # spawn seam: `raise` -> the pending lease request errors
            # (owner maps it to WorkerCrashedError or backs off)
            _fp.fire_strict("raylet.spawn")
        if tpu:
            self.starting_tpu += 1
        else:
            self.starting += 1
        log_file = os.path.join(
            self.session_dir, "logs",
            f"worker-{self.node_id.hex()[:8]}-{self.starting + self.starting_tpu}"
            f"-{time.time():.0f}.log")
        env = dict(os.environ)
        env.update(self.config.child_env())
        # Only workers serving TPU-resource leases get the TPU-plugin env
        # (process-exclusive chip claim + ~2s jax import at python start);
        # everyone else runs with it stripped.
        from ray_tpu._private.node import (restore_tpu_plugin_env,
                                           strip_tpu_plugin_env)

        if tpu:
            restore_tpu_plugin_env(env)
            # Tells worker/main.py not to pin JAX_PLATFORMS=cpu, and the
            # worker echoes the flavor back at registration.
            env["RAY_TPU_WORKER_TPU"] = "1"
            env["RAY_TPU_WORKER_FLAVOR"] = "tpu"
        else:
            strip_tpu_plugin_env(env)
            env.pop("RAY_TPU_TPU_ENV", None)
            env.pop("RAY_TPU_WORKER_TPU", None)
            env["RAY_TPU_WORKER_FLAVOR"] = "cpu"
        cmd = [
            sys.executable, "-m", "ray_tpu.worker.main",
            "--raylet-address", self.address,
            "--gcs-address", self.gcs_address,
            "--node-id", self.node_id.hex(),
            "--session-dir", self.session_dir,
            "--store-root", self.store_root,
            "--log-file", log_file,
        ]
        # stderr lands in the worker's log file so crashes (uncaught
        # tracebacks, aborts) are diagnosable post-mortem.
        errf = open(log_file + ".err", "ab") if log_file else subprocess.DEVNULL
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=subprocess.DEVNULL, stderr=errf,
            start_new_session=True)
        if errf is not subprocess.DEVNULL:
            errf.close()
        self._starting_procs.append((proc, "tpu" if tpu else "cpu"))
        self.m_workers_started.inc()
        logger.info("started %s worker process pid=%d",
                    "tpu" if tpu else "cpu", proc.pid)
        return proc

    def _reap_starting_workers(self):
        """Release `starting` slots held by worker processes that exited
        before registering, and re-wake waiters so they respawn."""
        alive, died = [], []
        for proc, flavor in self._starting_procs:
            (alive if proc.poll() is None else died).append((proc, flavor))
        self._starting_procs = alive
        for proc, flavor in died:
            logger.warning("%s worker pid=%d exited (rc=%s) before "
                           "registering", flavor, proc.pid, proc.returncode)
            if flavor == "tpu":
                self.starting_tpu = max(0, self.starting_tpu - 1)
            else:
                self.starting = max(0, self.starting - 1)
        if died:
            # Wake every waiter; each re-runs its loop and respawns now
            # that the stuck `starting` slot is free.
            for fut, _tpu in self._worker_waiters:
                if not fut.done():
                    fut.set_result(None)
            self._worker_waiters = []

    async def _pop_worker(self, ignore_cap: bool = False,
                          tpu: bool = False) -> WorkerHandle:
        while True:
            pool = self.idle_tpu if tpu else self.idle
            if pool:
                return pool.pop()
            if tpu:
                # TPU workers are dedicated and rare — no cap games.
                if self.starting_tpu == 0:
                    self._start_worker_process(tpu=True)
            else:
                max_workers = (self.config.max_workers_per_node
                               or max(self.num_cpus, 4))
                active = len(self.workers) + self.starting
                if ignore_cap or active < max_workers or self.starting == 0:
                    self._start_worker_process()
            fut = asyncio.get_running_loop().create_future()
            self._worker_waiters.append((fut, tpu))
            await fut

    def _push_worker(self, worker: WorkerHandle):
        worker.lease_id = None
        worker.lease_resources = None
        worker.lease_pg = None
        if worker.conn.closed:
            return
        (self.idle_tpu if worker.flavor == "tpu" else self.idle).append(worker)
        self._wake_worker_waiters()

    def _wake_worker_waiters(self):
        remaining = []
        for fut, tpu in self._worker_waiters:
            pool = self.idle_tpu if tpu else self.idle
            if pool and not fut.done():
                fut.set_result(None)
            elif not fut.done():
                remaining.append((fut, tpu))
        self._worker_waiters = remaining

    async def h_register_client(self, conn, d):
        kind = d["kind"]
        if kind == "worker":
            worker = WorkerHandle(d["worker_id"], d["address"], d["pid"], conn)
            worker.flavor = d.get("flavor", "cpu")
            worker.task_channel = d.get("task_channel") or ""
            self._starting_procs = [(p, f) for p, f in self._starting_procs
                                    if p.pid != d["pid"]]
            self.workers[d["worker_id"]] = worker
            conn.context["worker"] = worker
            if worker.flavor == "tpu":
                self.starting_tpu = max(0, self.starting_tpu - 1)
                self.idle_tpu.append(worker)
            else:
                self.starting = max(0, self.starting - 1)
                self.idle.append(worker)
            self._wake_worker_waiters()
        else:  # driver
            # truthy dict (callers only truth-test it): pid/address let
            # debug_state/doctor reach driver-owned task state from the
            # out-of-process surfaces (ray-tpu state/doctor, dashboard)
            conn.context["driver"] = {"pid": d.get("pid"),
                                      "address": d.get("address", "")}
        return {"node_id": self.node_id.binary(), "address": self.address}

    async def _on_disconnect(self, conn):
        if self._shutting_down:
            return
        # A legacy puller's transfer pins die with its connection (the
        # TTL sweep is only the backstop for pullers that wedge without
        # closing); deferred frees they were blocking run now.
        freeable = self.transfer_pins.release_token(
            self._legacy_pin_token(conn))
        if freeable:
            await self._complete_deferred_frees(freeable)
        # Lease-holder death: leases granted to this connection (a
        # driver, or a worker that owned subtasks) are returned now —
        # resources released, still-alive workers back in the idle pool —
        # instead of stranding them until node teardown.
        held = conn.context.pop("lease_ids", None)
        if held:
            reclaimed = 0
            for w in list(self.workers.values()):
                if w.lease_id in held:
                    self._release(w.lease_resources, w.lease_pg)
                    self._push_worker(w)
                    reclaimed += 1
            if reclaimed:
                logger.warning(
                    "lease holder disconnected; reclaimed %d leased "
                    "worker(s)", reclaimed)
                await self._dispatch_pending()
        worker: WorkerHandle | None = conn.context.get("worker")
        if worker is None:
            return
        self.workers.pop(worker.worker_id, None)
        if worker in self.idle:
            self.idle.remove(worker)
        if worker in self.idle_tpu:
            self.idle_tpu.remove(worker)
        # release lease resources
        if worker.lease_resources is not None:
            self._release(worker.lease_resources, worker.lease_pg)
            await self._dispatch_pending()
        intended = bool(conn.context.get("intended_exit"))
        if not intended and self.gcs is not None:
            # structured WORKER_DIED event → GCS ring (RAY_EVENT analog)
            from ray_tpu._private import events

            event = events.report_event(
                events.ERROR, "WORKER_DIED",
                f"worker {worker.worker_id.hex()[:8]} "
                f"(pid {worker.pid}) died unexpectedly",
                worker_id=worker.worker_id.hex(), pid=worker.pid)
            try:
                await self.gcs.notify("report_event", event)
            except Exception:
                pass
        if worker.actor_id is not None and self.gcs is not None:
            try:
                await self.gcs.call("report_worker_failure", {
                    "worker_id": worker.worker_id,
                    "actor_ids": [worker.actor_id],
                    "intended": intended,
                })
            except Exception:
                pass

    # ------------------------------------------------------------------
    # scheduling (reference: cluster_task_manager.cc + hybrid policy)
    # ------------------------------------------------------------------

    def _bundle_key(self, spec) -> tuple[bytes, int] | None:
        if spec.get("pg_id") is None:
            return None
        return (spec["pg_id"], spec.get("bundle_index", -1))

    def _try_acquire(self, spec) -> tuple[ResourceSet, tuple | None] | None:
        need = ResourceSet.from_raw(spec["resources"])
        key = self._bundle_key(spec)
        if key is not None:
            bundle = self._find_bundle(key)
            if bundle is None:
                return None
            if not need.is_subset_of(bundle["available"]):
                return None
            bundle["available"].subtract(need)
            return need, key
        if not need.is_subset_of(self.available):
            return None
        self.available.subtract(need)
        return need, None

    def _find_bundle(self, key):
        if key[1] != -1:
            b = self.bundles.get(key)
            return b if b and b["state"] == "COMMITTED" else None
        # wildcard bundle index: any committed bundle of this pg on this node
        for (pg, _idx), b in self.bundles.items():
            if pg == key[0] and b["state"] == "COMMITTED":
                return b
        return None

    def _release(self, res: ResourceSet, pg_key):
        if pg_key is not None:
            bundle = self.bundles.get(pg_key) or self._find_bundle(pg_key)
            if bundle is not None:
                bundle["available"].add(res)
                return
            # Bundle was cancelled/returned while this lease was out: its
            # unleased part already went back to self.available, and the
            # leased part was recorded in _removed_bundles — return it now.
            outstanding = self._removed_bundles.get(pg_key[0])
            if outstanding is not None:
                self.available.add(res)
                outstanding.subtract(res)
                if outstanding.is_empty():
                    del self._removed_bundles[pg_key[0]]
            return
        self.available.add(res)

    def _feasible_ever(self, spec) -> bool:
        need = ResourceSet.from_raw(spec["resources"])
        if self._bundle_key(spec) is not None:
            return True  # bundles are explicit placements; wait for them
        return need.is_subset_of(self.total)

    def _coord_of_node(self, node_id: bytes):
        info = self.cluster_nodes.get(node_id)
        if info is None:
            return None
        return _topo.TopologyCoord.from_dict(info.get("topology"))

    def _topo_prefer(self, node_ids: list[bytes]) -> tuple[bytes, bool]:
        """Choose among candidate nodes: the topologically NEAREST one
        when coords differentiate them (random among equals — the
        PR 5/7 tie-breaker: same-slice ICI hops beat cross-slice/DCN),
        plain random otherwise. Returns (node_id, rerouted); rerouted
        is True only when the distance metric actually changed the
        outcome class, which is what
        `raylet.spillback_topo_reroutes_total` counts."""
        import random

        if len(node_ids) <= 1:
            return node_ids[0], False
        if self.topology is None:
            return random.choice(node_ids), False
        dists = [(_topo.distance(self.topology, self._coord_of_node(n)), n)
                 for n in node_ids]
        dmin = min(d for d, _ in dists)
        dmax = max(d for d, _ in dists)
        best = [n for d, n in dists if d == dmin]
        return random.choice(best), dmax > dmin

    def _pick_spillback(self, spec, exclude=()) -> str | None:
        """Hybrid policy fallback: a remote node whose *total* resources
        fit (reference: cluster_resource_scheduler.cc:320) — the
        topologically nearest such node when coords are registered,
        random otherwise. `exclude`: addresses already visited by a
        forwarded request (cycle guard)."""
        need = ResourceSet.from_raw(spec["resources"])
        cands = []
        for node_id, info in self.cluster_nodes.items():
            if node_id == self.node_id.binary():
                continue
            if info["address"] in exclude:
                continue
            if info.get("state", "ALIVE") != "ALIVE":
                continue  # DRAINING peers accept no new leases
            if need.is_subset_of(ResourceSet.from_raw(info["resources"])):
                cands.append(node_id)
        if not cands:
            return None
        choice, rerouted = self._topo_prefer(cands)
        if rerouted:
            self.m_topo_reroutes.inc()
        return self.cluster_nodes[choice]["address"]

    async def _pick_spillback_load_aware(self, spec, exclude=()) -> str | None:
        """Local node is feasible-by-totals but saturated: find a remote
        node with the capacity available RIGHT NOW (heartbeat-fresh GCS
        view) instead of hoarding the task in the local queue
        (reference: availability-scored hybrid policy,
        cluster_resource_scheduler.cc:217-320)."""
        if self.gcs is None or len(self.cluster_nodes) <= 1:
            return None
        try:
            avail_by_node = await self.gcs.call("get_available_resources", {})
        except Exception:
            return None
        avail = {nid: ResourceSet.from_raw(raw)
                 for nid, raw in avail_by_node.items()}
        return self._pick_from_availability(spec, avail, exclude)

    def _pick_from_availability(self, spec, avail: dict,
                                exclude=()) -> str | None:
        """Synchronous selection from a fetched availability view (callers
        holding the view across multiple picks subtract as they assign).
        Topology-nearest among feasible nodes when coords are known —
        the spillback-chain next hop prefers an ICI neighbor over a
        cross-slice node with identical headroom."""
        need = ResourceSet.from_raw(spec["resources"])
        me = self.node_id.binary()
        cands = []
        for node_id, rs in avail.items():
            if node_id == me or node_id not in self.cluster_nodes:
                continue
            if self.cluster_nodes[node_id]["address"] in exclude:
                continue
            if self.cluster_nodes[node_id].get("state", "ALIVE") != "ALIVE":
                continue  # DRAINING peers accept no new leases
            if need.is_subset_of(rs):
                cands.append(node_id)
        if not cands:
            return None
        node_id, rerouted = self._topo_prefer(cands)
        if rerouted:
            self.m_topo_reroutes.inc()
        avail[node_id].subtract(need)  # so N picks don't dogpile one slot
        return self.cluster_nodes[node_id]["address"]

    async def _locality_spillback(self, spec) -> str | None:
        """Weigh lease targets by resident plasma-arg bytes from the GCS
        object directory (reference: lease_policy.h locality-aware lease
        targeting; extends the h_push_objects_to *hint* into actual
        placement). Returns the address of a remote node holding at
        least locality_min_arg_bytes MORE of this task's args than we
        do, provided its total resources can ever run the task — else
        None (normal local grant / spillback applies)."""
        cfg = self.config
        if (not cfg.locality_aware_leasing or self.gcs is None
                or len(self.cluster_nodes) <= 1
                or spec.get("pg_id") is not None):
            return None
        arg_ids = [a["id"] for a in spec.get("args") or []
                   if a.get("kind") == "ref" and a.get("plasma")]
        if not arg_ids:
            return None
        if all(a in self.local_objects for a in arg_ids):
            # every arg is resident HERE: no remote node can hold more
            # bytes than us, so skip the directory round trip on the
            # lease critical path (the steady state once tasks follow
            # their data)
            return None
        key = tuple(arg_ids)
        now = time.monotonic()
        if self._locality_negcache.get(key, 0) > now:
            return None
        if _fp.ARMED:
            # locality-targeting seam: `raise` models a failed directory
            # lookup — placement falls back to the normal local path
            try:
                await _fp.fire_async_strict("lease.locality_target")
            except _fp.FailpointError:
                return None
        try:
            recs = await self.gcs.call("get_object_locations_batch",
                                       {"object_ids": arg_ids})
        except Exception:
            return None
        by_node: dict[bytes, int] = {}
        for rec in (recs or {}).values():
            size = max(1, int(rec.get("size") or 0))
            for node_id in rec.get("nodes") or []:
                by_node[node_id] = by_node.get(node_id, 0) + size
        if not by_node:
            return None
        me = self.node_id.binary()
        need = ResourceSet.from_raw(spec["resources"])
        my_bytes = by_node.get(me, 0)
        feasible: list[tuple[int, bytes]] = []
        for node_id, nbytes in by_node.items():
            if node_id == me:
                continue
            info = self.cluster_nodes.get(node_id)
            if info is None or info.get("state", "ALIVE") != "ALIVE":
                continue  # a DRAINING holder is migrating those bytes away
            if not need.is_subset_of(ResourceSet.from_raw(info["resources"])):
                continue
            feasible.append((nbytes, node_id))
        best_bytes = max((n for n, _ in feasible), default=0)
        if (not feasible
                or best_bytes - my_bytes < cfg.locality_min_arg_bytes):
            if len(self._locality_negcache) > 1024:
                self._locality_negcache = {
                    k: v for k, v in self._locality_negcache.items()
                    if v > now}
            self._locality_negcache[key] = now + 2.0
            return None
        # byte count decides; topology breaks the byte TIE (several
        # nodes hold the same resident bytes — e.g. a broadcast arg) in
        # favor of the ICI-nearest holder
        ties = [nid for n, nid in feasible if n == best_bytes]
        best, rerouted = self._topo_prefer(ties)
        if rerouted:
            self.m_topo_reroutes.inc()
        return self.cluster_nodes[best]["address"]

    def _warn_infeasible(self, spec):
        shape = tuple(sorted(spec.get("resources", {}).items()))
        if shape not in self._warned_infeasible:
            self._warned_infeasible.add(shape)
            logger.warning(
                "task %s demands resources %s that no node in the cluster "
                "can ever satisfy; it will hang until matching nodes join "
                "(reference warns identically: cluster_task_manager.cc)",
                spec.get("name", "?"), dict(spec.get("resources", {})))

    async def _pg_spillback(self, key) -> str | None:
        """A lease targeting a bundle this node doesn't host: redirect to
        the raylet that committed it (the GCS holds bundle→node placement;
        reference: lease_policy.h locality-aware lease target)."""
        if self.gcs is None:
            return None
        try:
            rec = await self.gcs.call("get_placement_group",
                                      {"pg_id": key[0]})
        except Exception:
            return None
        if rec is None or rec.get("state") != "CREATED":
            return None
        me = self.node_id.binary()
        for b in rec["bundles"]:
            if key[1] in (-1, b["bundle_index"]) and b["node_id"] != me:
                info = self.cluster_nodes.get(b["node_id"])
                if info is not None:
                    return info["address"]
        return None

    def _pop_idle_now(self, tpu: bool):
        """Pop an idle worker if one exists RIGHT NOW — no wait, no spawn
        (the grant path for soft/prewarm lease requests and for the tail
        of a batched grant)."""
        pool = self.idle_tpu if tpu else self.idle
        return pool.pop() if pool else None

    async def h_request_worker_lease(self, conn, d):
        """Grant worker leases. Plain form (no `count`): one lease,
        waiting on worker startup if needed — unchanged round-7 behavior.
        Batched form (`count`=N): grant up to N leases in ONE round trip
        from capacity that is idle now; only a hard request with zero
        idle workers waits (and possibly spawns) for a single worker. A
        `soft` request never spawns and never queues — a dry idle pool
        returns an empty grant list immediately, so owner-side lease
        pre-warm for bursts of tiny tasks cannot spawn-storm the node."""
        spec = d["spec"]
        lease_t0 = time.time()
        if _fp.ARMED:
            # grant seam: `raise` -> RemoteError at the owner's lease
            # request (typed retry/fail path); `exit` kills the raylet
            await _fp.fire_async_strict("lease.grant")
        batched = "count" in d
        count = max(1, int(d.get("count", 1)))
        soft = bool(d.get("soft"))
        hops = int(d.get("hops", 0))
        visited = list(d.get("visited") or ())
        if self._draining:
            # A draining node grants nothing: redirect the request to a
            # survivor (the spillback pickers already exclude DRAINING
            # peers, so two departing nodes can't ping-pong a request).
            # Soft prewarm just comes back empty; with no survivor the
            # owner queues exactly like an infeasible-everywhere task.
            if soft:
                return {"grants": []}
            addr = self._pick_spillback(spec, exclude=visited)
            if addr is not None:
                self.m_spillbacks.inc()
                return await self._spill(d, addr, hops + 1)
            fut = asyncio.get_running_loop().create_future()
            spec.setdefault("_queued_at", time.time())
            self.pending_leases.append((spec, fut))
            result = await fut
            if result.get("granted"):
                self._track_holder(conn, [result])
                self._note_lease_granted(lease_t0, spec, 1)
            if batched and "spillback" not in result:
                return {"grants": [result]}
            return result
        if hops == 0 and not soft:
            # Locality-aware lease targeting (reference: lease_policy.h):
            # a task whose plasma args are resident on another node is
            # leased THERE — moving the task to the data instead of the
            # data to the task. First hop only, so a redirected request
            # can still queue/spill on the target without ping-pong.
            addr = await self._locality_spillback(spec)
            if addr is not None:
                self.m_spillbacks.inc()
                self.m_locality_spillbacks.inc()
                return await self._spill(d, addr, 1)
        tpu = self._needs_tpu(spec)
        grants: list[dict] = []
        while len(grants) < count:
            acquired = self._try_acquire(spec)
            if acquired is None:
                break
            res, pg_key = acquired
            worker = self._pop_idle_now(tpu)
            if worker is None:
                if soft or grants:
                    # soft never spawns; a batch never blocks its
                    # already-granted leases behind worker startup
                    self._release(res, pg_key)
                    break
                try:
                    worker = await self._pop_worker(tpu=tpu)
                except Exception:
                    self._release(res, pg_key)
                    raise
            grants.append(self._lease_reply(worker, res, pg_key))
        if grants:
            if d.get("forwarded"):
                # Spillback-chain grant: the conn is a PEER RAYLET, not
                # the lease holder — the owner claims these via
                # adopt_leases over its own connection; unclaimed grants
                # are reclaimed at the deadline (reap loop).
                self.m_spillback_grants.inc(len(grants))
                self._note_unadopted(grants)
            elif conn.closed:
                # The holder died while we awaited worker spawn: its
                # disconnect callback already ran, so reclaim these
                # grants now — nobody can receive the reply or ever
                # return the leases.
                ids = {g["lease_id"] for g in grants}
                for w in list(self.workers.values()):
                    if w.lease_id in ids:
                        self._release(w.lease_resources, w.lease_pg)
                        self._push_worker(w)
                await self._dispatch_pending()
            else:
                self._track_holder(conn, grants)
            self._note_lease_granted(lease_t0, spec, len(grants))
            return {"grants": grants} if batched else grants[0]
        if soft:
            return {"grants": []}
        key = self._bundle_key(spec)
        if key is not None and self._find_bundle(key) is None:
            addr = await self._pg_spillback(key)
            if addr is not None:
                return await self._spill(d, addr, hops + 1)
        max_hops = self.config.lease_spillback_max_hops
        if not self._feasible_ever(spec):
            addr = self._pick_spillback(spec, exclude=visited)
            if addr is not None:
                self.m_spillbacks.inc()
                return await self._spill(d, addr, hops + 1)
            # Infeasible everywhere: queue until the cluster changes.
            self._warn_infeasible(spec)
        elif key is None and hops < max_hops:
            # Feasible here but saturated: offer it to a node that can run
            # it now rather than hoarding it (hop-capped to stop ping-pong
            # when the whole cluster is saturated).
            addr = await self._pick_spillback_load_aware(spec,
                                                         exclude=visited)
            if addr is not None:
                self.m_spillbacks.inc()
                return await self._spill(d, addr, hops + 1)
        fut = asyncio.get_running_loop().create_future()
        # queue-arrival stamp rides the spec so debug_state/doctor can age
        # the raylet's lease queue (carried along spillback forwards too)
        spec.setdefault("_queued_at", time.time())
        self.pending_leases.append((spec, fut))
        result = await fut
        if result.get("granted"):
            if d.get("forwarded"):
                self.m_spillback_grants.inc()
                self._note_unadopted([result])
            elif conn.closed:
                # The holder died while its request sat in the queue:
                # its disconnect callback already ran (empty lease set),
                # so reclaim this grant NOW — the reply can't be
                # delivered and nobody would ever return the lease.
                for w in list(self.workers.values()):
                    if w.lease_id == result["lease_id"]:
                        self._release(w.lease_resources, w.lease_pg)
                        self._push_worker(w)
                        break
                await self._dispatch_pending()
            else:
                self._track_holder(conn, [result])
            self._note_lease_granted(lease_t0, spec, 1)
        if batched and "spillback" not in result:
            return {"grants": [result]}
        return result

    async def _spill(self, d: dict, addr: str, hops: int):
        """Redirect a lease request to the raylet at `addr`. Forwarding
        mode (lease_spillback_forwarding, the tentpole path) CHAINS the
        request raylet→raylet — this raylet relays the peer's grant back
        toward the owner, so a cross-node burst costs the owner ONE lease
        RPC instead of a redial per hop. The chain is hop-capped
        (lease_spillback_max_hops), cycle-guarded (`visited` addresses are
        never re-picked), and carries the spec unchanged — locality hints
        (args) and the PR 6 trace context ride along. Legacy mode (or a
        failed forward, or an exhausted hop budget) bounces the
        owner-visible {"spillback": addr} reply exactly as before."""
        if (not self.config.lease_spillback_forwarding
                or hops > self.config.lease_spillback_max_hops):
            return {"spillback": addr, "hops": hops}
        if _fp.ARMED:
            # forward seam: `raise` degrades to the owner-mediated bounce
            # (liveness must not depend on the chain); `exit` kills this
            # raylet mid-chain (chaos sweep)
            try:
                await _fp.fire_async_strict("lease.spillback")
            except _fp.FailpointError:
                return {"spillback": addr, "hops": hops}
        fwd = dict(d)
        fwd["hops"] = hops
        fwd["forwarded"] = True
        fwd["visited"] = list(d.get("visited") or ()) + [self.address]
        self.m_spillback_forwards.inc()
        try:
            conn = await self._raylet_conn(addr)
            reply = await conn.call("request_worker_lease", fwd)
        except Exception as e:
            # peer died / unreachable mid-chain: degrade to the legacy
            # bounce so the owner can redial (or re-spill elsewhere)
            logger.warning("lease spillback forward to %s failed (%s); "
                           "bouncing to owner", addr, e)
            return {"spillback": addr, "hops": hops}
        root = tracing.from_wire((d.get("spec") or {}).get("trace"))
        if root is not None:
            tracing.record_span("raylet.spillback", time.time(), time.time(),
                                tracing.child(root), {"to": addr,
                                                      "hops": hops})
        return reply

    def _note_unadopted(self, grants):
        # `adopt` tells the owner these grants arrived over a spillback
        # chain: it must claim them (adopt_leases at granted_by) before
        # this deadline, or the reap loop returns them to the idle pool.
        deadline = time.monotonic() + 10.0
        for g in grants:
            g["adopt"] = True
            self._unadopted[g["lease_id"]] = deadline

    async def h_adopt_leases(self, conn, d):
        """The true lease holder claims leases granted for a forwarded
        request: holder-death reclaim (_on_disconnect) now watches the
        OWNER's connection, exactly as for a directly-requested lease.
        Returns the lease_ids actually adopted — one missing means the
        unadopted deadline already reclaimed it (the owner treats that
        lease as lost and re-requests)."""
        held = conn.context.setdefault("lease_ids", set())
        adopted = []
        for lid in d["lease_ids"]:
            if self._unadopted.pop(lid, None) is None:
                continue
            held.add(lid)
            adopted.append(lid)
        return {"adopted": adopted}

    def _reap_unadopted(self):
        """Reclaim forwarded-request grants whose owner never adopted
        them (died between the relayed grant and adopt_leases)."""
        if not self._unadopted:
            return False
        now = time.monotonic()
        expired = [lid for lid, dl in self._unadopted.items() if dl < now]
        reclaimed = False
        for lid in expired:
            del self._unadopted[lid]
            for w in list(self.workers.values()):
                if w.lease_id == lid:
                    logger.warning("reclaiming never-adopted spillback "
                                   "lease %s", lid.hex())
                    self._release(w.lease_resources, w.lease_pg)
                    self._push_worker(w)
                    reclaimed = True
                    break
        return reclaimed

    def _note_lease_granted(self, t0: float, spec, count: int):
        """Raylet-side scheduling hop: histogram always, a `raylet.lease`
        span (child of the requesting task's root) when the spec carries
        a sampled trace context."""
        now = time.time()
        root = tracing.from_wire(spec.get("trace"))
        self.m_lease_grant_s.observe(now - t0,
                                     exemplar=tracing.exemplar_of(root))
        if root is not None:
            tracing.record_span("raylet.lease", t0, now,
                                tracing.child(root),
                                {"name": spec.get("name", "?"),
                                 "count": count})

    @staticmethod
    def _track_holder(conn, grants):
        """Remember which connection holds each lease, so a lease holder
        that crashes (driver killed, owner worker dies mid-pipeline)
        returns its leases instead of stranding workers+resources until
        node death (_on_disconnect reclaims)."""
        held = conn.context.setdefault("lease_ids", set())
        for g in grants:
            held.add(g["lease_id"])

    @staticmethod
    def _needs_tpu(spec) -> bool:
        return float(spec.get("resources", {}).get("TPU") or 0) > 0

    async def _grant_lease(self, spec, acquired):
        res, pg_key = acquired
        try:
            worker = await self._pop_worker(tpu=self._needs_tpu(spec))
        except Exception:
            self._release(res, pg_key)
            raise
        return self._lease_reply(worker, res, pg_key)

    def _lease_reply(self, worker, res, pg_key) -> dict:
        self._lease_seq += 1
        self.m_leases_granted.inc()
        lease_id = self._lease_seq.to_bytes(8, "big")
        worker.lease_id = lease_id
        worker.lease_resources = res
        worker.lease_pg = pg_key
        return {
            "granted": True,
            "lease_id": lease_id,
            "worker_id": worker.worker_id,
            "worker_address": worker.address,
            "task_channel": worker.task_channel,
            # which raylet granted: a forwarded (spillback-chain) grant
            # reaches the owner through its LOCAL raylet's reply, and the
            # owner must return the lease (and adopt it) HERE
            "granted_by": self.address,
        }

    async def h_return_worker(self, conn, d):
        if _fp.ARMED:
            await _fp.fire_async_strict("lease.return")
        held = conn.context.get("lease_ids")
        if held is not None:
            held.discard(d["lease_id"])
        self._unadopted.pop(d["lease_id"], None)
        worker = None
        for w in self.workers.values():
            if w.lease_id == d["lease_id"]:
                worker = w
                break
        if worker is None:
            return False
        self._release(worker.lease_resources, worker.lease_pg)
        if d.get("worker_exiting") or worker.conn.closed:
            self.workers.pop(worker.worker_id, None)
        else:
            self._push_worker(worker)
        await self._dispatch_pending()
        return True

    async def _dispatch_pending(self):
        if self._draining:
            # no grants off the queue while draining; _drain bounces the
            # queue to survivors and the exit-time conn close sends any
            # stragglers through the owner's normal retry path
            return
        if _fp.ARMED:
            # dispatch seam: `raise` leaves queued leases queued (the
            # next return/heartbeat/bundle event re-drives the queue)
            await _fp.fire_async_strict("raylet.dispatch")
        remaining = []
        for spec, fut in self.pending_leases:
            if fut.done():
                continue
            acquired = self._try_acquire(spec)
            if acquired is None:
                remaining.append((spec, fut))
                continue
            try:
                fut.set_result(await self._grant_lease(spec, acquired))
            except Exception as e:  # pragma: no cover
                if not fut.done():
                    fut.set_exception(e)
        self.pending_leases = remaining

    # ------------------------------------------------------------------
    # actors (GCS-driven)
    # ------------------------------------------------------------------

    async def h_create_actor(self, conn, d):
        spec = d["spec"]
        if self._draining:
            # looks like a stale-availability miss to the GCS: it zeroes
            # its view of this node and requeues on an ALIVE one
            raise InsufficientResources("node is draining")
        acquired = self._try_acquire(spec)
        if acquired is None:
            # GCS checked the resource snapshot, but we may have raced.
            raise InsufficientResources("insufficient resources for actor")
        res, pg_key = acquired
        try:
            worker = await asyncio.wait_for(
                self._pop_worker(ignore_cap=True, tpu=self._needs_tpu(spec)),
                self.config.worker_register_timeout_s)
        except Exception:
            self._release(res, pg_key)
            raise
        worker.actor_id = spec["actor_id"]
        worker.lease_resources = res
        worker.lease_pg = pg_key
        try:
            reply = await worker.conn.call("create_actor", {"spec": spec})
            # The worker packs constructor exceptions as an error result
            # instead of raising over RPC — surface them so the GCS records
            # a real death cause (reference: creation failures publish the
            # actor as DEAD with the error, gcs_actor_manager.h:125-127).
            if any(r.get("err") for r in (reply or {}).get("returns", [])):
                raise RuntimeError(
                    f"actor constructor failed: "
                    f"{(reply or {}).get('error_repr', 'unknown error')}")
        except Exception:
            worker.actor_id = None
            self._release(res, pg_key)
            worker.lease_resources = None
            worker.lease_pg = None
            if not worker.conn.closed:
                self._push_worker(worker)
            raise
        return {"worker_address": worker.address,
                "worker_id": worker.worker_id,
                "task_channel": worker.task_channel}

    async def h_kill_actor_worker(self, conn, d):
        worker = self.workers.get(d["worker_id"])
        if worker is None:
            return False
        worker.conn.context["intended_exit"] = True
        try:
            await worker.conn.notify("exit", {"reason": "killed"})
        except Exception:
            pass

        async def _force_kill():
            await asyncio.sleep(2.0)
            try:
                os.kill(worker.pid, 9)
            except ProcessLookupError:
                pass

        asyncio.create_task(_force_kill())
        return True

    async def h_actor_exiting(self, conn, d):
        """Actor worker announces a clean exit (exit_actor())."""
        conn.context["intended_exit"] = True
        return True

    # ------------------------------------------------------------------
    # placement group bundles (2PC; reference:
    # placement_group_resource_manager.h:51)
    # ------------------------------------------------------------------

    async def h_prepare_bundle(self, conn, d):
        if self._draining:
            return False  # a departing node reserves nothing (2PC abort)
        need = ResourceSet.from_raw(d["resources"])
        if not need.is_subset_of(self.available):
            return False
        self.available.subtract(need)
        self.bundles[(d["pg_id"], d["bundle_index"])] = {
            "resources": need,
            "available": need.copy(),
            "state": "PREPARED",
        }
        return True

    async def h_commit_bundle(self, conn, d):
        bundle = self.bundles.get((d["pg_id"], d["bundle_index"]))
        if bundle is None:
            return False
        bundle["state"] = "COMMITTED"
        await self._dispatch_pending()
        return True

    async def h_cancel_bundle(self, conn, d):
        """Remove a bundle. Only the unleased remainder goes back to
        self.available immediately; the leased portion returns as each
        lease ends (_release tracks it via _removed_bundles). Workers
        still leasing from the removed group are killed, matching the
        reference's kill-tasks-of-removed-PG behavior
        (placement_group_resource_manager.h:51)."""
        key = (d["pg_id"], d["bundle_index"])
        bundle = self.bundles.pop(key, None)
        if bundle is not None:
            self.available.add(bundle["available"])
            outstanding = bundle["resources"].copy()
            outstanding.subtract(bundle["available"])
            if not outstanding.is_empty():
                prior = self._removed_bundles.setdefault(
                    d["pg_id"], ResourceSet({}))
                prior.add(outstanding)
            for w in list(self.workers.values()):
                if w.lease_pg is not None and w.lease_pg[0] == d["pg_id"]:
                    await self.h_kill_actor_worker(
                        conn, {"worker_id": w.worker_id})
            await self._dispatch_pending()
        return True

    async def h_return_bundle(self, conn, d):
        return await self.h_cancel_bundle(conn, d)

    # ------------------------------------------------------------------
    # object manager (reference: object_manager.h, local_object_manager.h)
    # ------------------------------------------------------------------

    async def h_notify_object_sealed(self, conn, d):
        oid = d["object_id"]
        size = d["size"]
        # a deferral recorded against this id's PREVIOUS incarnation must
        # not delete the fresh copy when the old transfer's pins drop
        self.transfer_pins.cancel_deferred_free(oid)
        self.local_objects[oid] = {"size": size, "pinned": True, "spilled": None}
        self.store_used += size
        await self._wake_object_waiters(oid)
        # Location registration + spill check ride a background task: the
        # putting worker shouldn't pay a GCS round trip per large put
        # (remote pulls retry until the directory catches up anyway).
        if self.gcs is not None:
            async def _register():
                await self._register_location(oid, size)
                try:
                    await self._maybe_spill()
                except Exception:
                    # Spill failures (disk full, perms) must be visible,
                    # not an unretrieved-task exception; the next seal
                    # retries.
                    logger.exception("object spill failed")

            asyncio.create_task(_register())
        else:
            await self._maybe_spill()
        return True

    async def _register_location(self, oid: bytes, size: int):
        """Record this node as a holder of `oid` (with its size) in the
        GCS object directory — best-effort: remote pulls retry their
        lookups until the directory catches up."""
        if self.gcs is None:
            return
        try:
            await self.gcs.call("add_object_location", {
                "object_id": oid, "node_id": self.node_id.binary(),
                "size": size})
        except Exception:
            pass

    async def _wake_object_waiters(self, oid: bytes):
        for fut in self.object_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)

    async def h_wait_object_local(self, conn, d):
        """Returns True once the object is local, False on `timeout`, or
        the string \"lost\" when the pull declared typed loss (the GCS
        directory stayed empty past pull_no_location_timeout_s) — the
        caller maps \"lost\" onto recovery/ObjectLostError instead of
        re-probing."""
        oid = d["object_id"]
        timeout = d.get("timeout") or None
        rec = self.local_objects.get(oid)
        if rec is not None:
            if rec["spilled"]:
                await self._restore_spilled(oid)
            return True
        fut = asyncio.get_running_loop().create_future()
        self.object_waiters.setdefault(oid, []).append(fut)
        asyncio.create_task(self._pull_object(oid))
        if timeout:
            try:
                return await asyncio.wait_for(asyncio.shield(fut), timeout)
            except asyncio.TimeoutError:
                return False
        return await fut

    async def h_hint_pull_purpose(self, conn, d):
        """Advisory label for an upcoming pull of `object_id` (e.g.
        \"kv_warm\" before a prefix-page import): consumed by the next
        streaming pull of that object so transfer introspection can
        attribute the bytes. Best-effort — no pull ever depends on it."""
        transfer.hint_pull(d["object_id"], d.get("purpose") or "")
        return True

    @property
    def _pull_sem(self) -> asyncio.Semaphore:
        # Admission control (reference: pull_manager.h:26): bound the
        # number of concurrent inbound transfers so a burst of pulls
        # can't monopolize bandwidth/memory; queued pulls wait here.
        if self._pull_sem_obj is None:
            self._pull_sem_obj = asyncio.Semaphore(
                self.config.max_concurrent_object_pulls)
        return self._pull_sem_obj

    async def _pull_object(self, oid: bytes, hint_addr: str | None = None):
        """Pull one object from remote nodes (reference: pull_manager.h:26
        admission + object_manager chunked transfer; streaming/striping in
        raylet/transfer.py). Retries while waiters exist, with exponential
        backoff between directory lookups; a directory that stays EMPTY
        past pull_no_location_timeout_s propagates typed loss to the
        h_wait_object_local waiters instead of spinning forever.
        `hint_addr`: a node known to hold the object (push path) — tried
        immediately with NO GCS location lookup; on failure falls back to
        the normal lookup/retry loop so a concurrent demand waiter
        (deduped into this pull) is never stranded."""
        if oid in self._pulls_inflight:
            return
        self._pulls_inflight.add(oid)
        try:
            if hint_addr is not None and oid not in self.local_objects:
                try:
                    async with self._pull_sem:
                        if oid not in self.local_objects:
                            await self._pull_any(oid, [hint_addr])
                    return
                except Exception as e:
                    logger.warning("hinted pull of %s from %s failed: %s",
                                   oid[:6].hex(), hint_addr, e)
            empty_since: float | None = None
            backoff = 0.05
            while oid not in self.local_objects and oid in self.object_waiters:
                try:
                    locations = await self.gcs.call(
                        "get_object_locations", {"object_id": oid})
                except Exception:
                    locations = None  # GCS hiccup: not evidence of loss
                addresses = []
                for node_id in locations or ():
                    if node_id == self.node_id.binary():
                        continue
                    info = self.cluster_nodes.get(node_id)
                    if info is not None:
                        addresses.append(info["address"])
                if addresses:
                    empty_since = None
                    try:
                        async with self._pull_sem:
                            if oid in self.local_objects:
                                break
                            await self._pull_any(oid, addresses)
                        break
                    except Exception as e:
                        logger.warning("pull of %s failed: %s",
                                       oid[:6].hex(), e)
                elif locations is not None and not locations:
                    # NOBODY claims a copy. Give the directory a bounded
                    # window (a seal's registration is async), then fail
                    # the waiters typed so _read_plasma stops burning
                    # probe cycles on an object that is simply gone.
                    now = time.monotonic()
                    if empty_since is None:
                        empty_since = now
                    elif (now - empty_since
                          > self.config.pull_no_location_timeout_s):
                        self._fail_object_waiters(oid)
                        return
                else:
                    # copies registered on nodes we can't see (yet), or
                    # the GCS lookup failed: keep retrying, but don't
                    # run the loss clock
                    empty_since = None
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
        finally:
            self._pulls_inflight.discard(oid)

    def _fail_object_waiters(self, oid: bytes):
        """Typed loss: wake every h_wait_object_local waiter with the
        \"lost\" sentinel (the owner-side _read_plasma maps it onto its
        recovery/ObjectLostError path instead of re-probing)."""
        waiters = self.object_waiters.pop(oid, [])
        for fut in waiters:
            if not fut.done():
                fut.set_result("lost")
        if waiters:
            logger.warning(
                "object %s has no registered location after %.1fs; "
                "declared lost to %d waiter(s)", oid[:6].hex(),
                self.config.pull_no_location_timeout_s, len(waiters))

    async def _raylet_conn(self, address: str) -> rpc.Connection:
        conn = self._raylet_conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        # per-address dial lock: concurrent pulls must share ONE conn —
        # a replaced-but-live conn would strand its in-flight calls in a
        # GC-able island (same hang class as core_worker._peer)
        lock = self._raylet_dial_locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._raylet_conns.get(address)
            if conn is None or conn.closed:
                conn = await rpc.connect(
                    rpc.prefer_uds(address, os.path.join(self.session_dir,
                                                         "sock"),
                                   local_ips=("127.0.0.1",
                                              self.config.node_ip_address)),
                    name=f"raylet->{address}")
                old = self._raylet_conns.get(address)
                self._raylet_conns[address] = conn
                if old is not None and not old.closed:
                    asyncio.ensure_future(old.close())
        return conn

    def _use_legacy_pull(self) -> bool:
        """RAY_TPU_PULL_LEGACY=1 (or set_transfer_mode) re-enables the
        round-8 stop-and-wait fetch_chunk pull path — the control arm of
        the cross_node_pull microbenchmark's interleaved A/B."""
        if self._pull_mode_legacy is not None:
            return self._pull_mode_legacy
        return os.environ.get("RAY_TPU_PULL_LEGACY", "") not in ("", "0")

    def _bulk_addr(self, address: str) -> str | None:
        """Map a peer raylet's control address to its bulk channel
        (advertised via the GCS node table), preferring the same-node UDS
        twin. None when the peer predates/disabled the bulk plane."""
        for info in self.cluster_nodes.values():
            if info.get("address") == address:
                bulk = info.get("bulk_address")
                if not bulk:
                    return None
                return rpc.prefer_uds(
                    bulk, os.path.join(self.session_dir, "sock"),
                    local_ips=("127.0.0.1", self.config.node_ip_address))
        return None

    async def _pull_any(self, oid: bytes, addresses: list[str]):
        """Pull `oid` given candidate holder control addresses: the
        streaming bulk plane (striped across every source with a bulk
        channel) by default, the legacy one-source-at-a-time chunked rpc
        path under RAY_TPU_PULL_LEGACY or when no source serves a bulk
        channel."""
        if not self._use_legacy_pull():
            bulk = [b for b in (self._bulk_addr(a) for a in addresses) if b]
            if bulk:
                try:
                    await self._pull_streaming(oid, bulk)
                    return
                except Exception as e:
                    # advertised-but-unreachable bulk channels (firewalled
                    # ephemeral port, half-up peer) must degrade to the
                    # control-path pull for THIS attempt, not hang the
                    # retry loop on streaming forever
                    logger.warning(
                        "streaming pull of %s failed (%s); falling back "
                        "to the control-path pull", oid[:6].hex(), e)
        last: Exception | None = None
        for address in addresses:
            try:
                await self._pull_from_legacy(oid, address)
                return
            except Exception as e:
                logger.warning("pull of %s from %s failed: %s",
                               oid[:6].hex(), address, e)
                last = e
        raise last if last is not None else KeyError(
            f"no source for {oid[:6].hex()}")

    async def _pull_streaming(self, oid: bytes, bulk_addresses: list[str]):
        """One streaming pull over the bulk data plane, striped across
        the sources (transfer.streaming_pull) on an executor thread so
        the raylet loop keeps serving heartbeats/leases."""
        cfg = self.config
        object_id = ObjectID(oid)
        loop = asyncio.get_running_loop()
        # bulk-pull trace entry point: the wire context rides the pull
        # request so the SOURCE raylet's serve span joins this tree
        ctx = tracing.maybe_trace()
        t0 = time.time()
        purpose = transfer.take_pull_hint(oid)
        size = await loop.run_in_executor(None, lambda: transfer.streaming_pull(
            oid, object_id, self.store, bulk_addresses,
            chunk=cfg.object_transfer_chunk_size,
            stripe=cfg.object_transfer_stripe_size,
            max_sources=cfg.max_pull_sources,
            io_timeout=cfg.bulk_transfer_io_timeout_s,
            trace=tracing.to_wire(ctx) if ctx is not None else None,
            purpose=purpose))
        transfer.M_PULL_S.observe(time.time() - t0,
                                  exemplar=tracing.exemplar_of(ctx))
        if ctx is not None:
            tracing.record_span("transfer.pull", t0, time.time(), ctx,
                                {"object_id": oid[:6].hex(),
                                 "bytes": size,
                                 "sources": len(bulk_addresses)})
        self._pulled_local(oid, size)
        await self._wake_object_waiters(oid)

    async def _pull_from_legacy(self, oid: bytes, address: str):
        """Round-8 control arm: one fetch_chunk request-response at a
        time over the shared raylet<->raylet CONTROL connection — pays a
        full RTT per chunk, a bytes() copy out of the arena plus a pickle
        frame per chunk, and head-of-line-blocks control RPCs behind the
        bulk frames (quantified in PERF.md round 9)."""
        conn = await self._raylet_conn(address)
        info = await conn.call("object_info", {"object_id": oid})
        if info is None:
            raise KeyError("remote no longer has object")
        size = info["size"]
        object_id = ObjectID(oid)
        try:
            buf = self.store.create(object_id, size)
        except FileExistsError:
            # stale .build from an abandoned pull (files backend)
            self.store.abort(object_id)
            buf = self.store.create(object_id, size)
        try:
            offset = 0
            chunk = self.config.object_transfer_chunk_size
            while offset < size:
                data = await conn.call("fetch_chunk", {
                    "object_id": oid, "offset": offset,
                    "size": min(chunk, size - offset)})
                buf.view[offset : offset + len(data)] = data
                transfer.M_PULL_BYTES.inc(len(data))
                offset += len(data)
            buf.close()
            self.store.seal(object_id)
        except BaseException:
            buf.close()
            self.store.abort(object_id)
            raise
        finally:
            # release the sender-side transfer pin promptly (the shared
            # control conn never closes, so TTL would otherwise be the
            # only release)
            try:
                await conn.notify("transfer_done", {"object_id": oid})
            except Exception:
                pass  # TTL sweep is the backstop
        self._pulled_local(oid, size)
        await self._wake_object_waiters(oid)

    def _pulled_local(self, oid: bytes, size: int):
        """Bookkeeping for a completed pull: the copy is resident here,
        and the GCS directory learns about it (background — remote
        lookups retry anyway) so later pulls can stripe across us and
        locality-aware leasing can weigh this node."""
        self.transfer_pins.cancel_deferred_free(oid)  # fresh incarnation
        self.local_objects[oid] = {"size": size, "pinned": False,
                                   "spilled": None}
        self.store_used += size
        self.m_objects_pulled.inc()
        if self.gcs is not None:
            asyncio.create_task(self._register_location(oid, size))

    async def h_push_hint(self, conn, d):
        """Proactive transfer start (the PushManager analog, reference:
        push_manager.h:29): a node holding `object_id` tells us we'll
        need it (task args racing a spilled-back lease). Dedup comes for
        free from _pulls_inflight; admission from the pull semaphore."""
        oid = d["object_id"]
        if oid in self.local_objects or oid in self._pulls_inflight:
            return True
        asyncio.create_task(self._pull_object(oid, hint_addr=d["from"]))
        return True

    async def h_push_objects_to(self, conn, d):
        """Owner side: our worker is about to run a task on `target`
        whose plasma args live here — hint the target so arg transfer
        overlaps with lease/worker setup."""
        target = d["target"]
        me = self.address
        for oid in d["object_ids"]:
            if oid not in self.local_objects:
                continue
            try:
                tconn = await self._raylet_conn(target)
                await tconn.notify("push_hint", {"object_id": oid,
                                                 "from": me})
            except Exception as e:
                logger.debug("push hint to %s failed: %s", target, e)
        return True

    def _legacy_pin_token(self, conn):
        return ("rpc", id(conn))

    async def h_object_info(self, conn, d):
        """Legacy-path transfer registration: reports size AND takes a
        transfer pin (TTL-leased, refreshed by each fetch_chunk) so the
        object can't be freed/evicted between the puller's chunks — the
        old mid-pull KeyError race."""
        oid = d["object_id"]
        if _fp.ARMED:
            await _fp.fire_async_strict("transfer.register")
        rec = self.local_objects.get(oid)
        if rec is None:
            return None
        if rec["spilled"]:
            await self._restore_spilled(oid)
        self.transfer_pins.pin(oid, self._legacy_pin_token(conn),
                               self.config.transfer_pin_ttl_s)
        return {"size": rec["size"]}

    async def h_fetch_chunk(self, conn, d):
        from ray_tpu import exceptions as exc

        oid = d["object_id"]
        object_id = ObjectID(oid)
        rec = self.local_objects.get(oid)
        if rec is not None and rec["spilled"]:
            # spilled between the puller's object_info and this chunk
            await self._restore_spilled(oid)
        if rec is not None:
            # refresh the transfer-pin lease for this puller
            self.transfer_pins.pin(oid, self._legacy_pin_token(conn),
                                   self.config.transfer_pin_ttl_s)
        buf = self.store.get(object_id)
        if buf is None:
            # typed (a puller fails over to another source / retries the
            # directory) instead of the old raw KeyError
            raise exc.ObjectLostError(object_id.hex())
        try:
            return bytes(buf.view[d["offset"] : d["offset"] + d["size"]])
        finally:
            buf.close()

    async def h_transfer_done(self, conn, d):
        """Legacy puller announces its transfer finished: release the
        pin NOW instead of waiting out the TTL lease — the raylet<->raylet
        control connection the pin is keyed to is cached indefinitely, so
        disconnect-release never fires for this path, and a TTL-only
        release would block frees/spill of the object for
        transfer_pin_ttl_s after every pull."""
        freeable = self.transfer_pins.unpin(d["object_id"],
                                            self._legacy_pin_token(conn))
        if freeable:
            await self._complete_deferred_frees(freeable)
        return True

    async def h_set_transfer_mode(self, conn, d):
        """A/B switch for the pull path (microbench + tests): `legacy`
        True forces the round-8 stop-and-wait fetch_chunk path for this
        raylet's future pulls, False forces streaming, absent reverts to
        the RAY_TPU_PULL_LEGACY env default."""
        self._pull_mode_legacy = (bool(d["legacy"]) if "legacy" in d
                                  and d["legacy"] is not None else None)
        return {"legacy": self._use_legacy_pull()}

    async def h_peer_ping(self, conn, d):
        """Round-trip a ping to `address` over THIS raylet's shared
        raylet<->raylet CONTROL connection — the one legacy bulk pulls
        also ride. The cross_node_pull bench uses it to measure
        control-plane head-of-line blocking during a bulk transfer."""
        t0 = time.monotonic()
        peer = await self._raylet_conn(d["address"])
        await peer.call("ping", {})
        return time.monotonic() - t0

    async def h_get_logs(self, conn, d):
        """Node-local log access — the per-node dashboard-agent role
        (reference: dashboard/agent.py log routes): the dashboard fans
        out here instead of aggregating every node's logs centrally.
        Without 'file': list this node's log files; with 'file': tail
        the last `lines` lines (bounded read)."""
        log_dir = os.path.join(self.session_dir, "logs")
        fname = d.get("file")
        if not fname:
            try:
                entries = []
                for name in sorted(os.listdir(log_dir)):
                    path = os.path.join(log_dir, name)
                    if os.path.isfile(path):
                        entries.append({"name": name,
                                        "size": os.path.getsize(path)})
                return entries
            except FileNotFoundError:
                return []
        if os.path.basename(fname) != fname or fname in (".", ".."):
            raise ValueError(f"log file must be a bare name: {fname!r}")
        path = os.path.join(log_dir, fname)
        lines = max(1, min(int(d.get("lines", 200)), 10_000))
        try:
            if not os.path.isfile(path):
                raise FileNotFoundError(path)
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(max(0, size - 512 * lines))  # bounded tail read
                data = f.read()
        except (FileNotFoundError, IsADirectoryError):
            raise ValueError(f"no log file {fname!r} on this node")
        text = data.decode(errors="replace")
        return "\n".join(text.splitlines()[-lines:])

    async def h_spill_now(self, conn, d):
        """Synchronous spill on behalf of a worker whose store create
        failed: move residents to disk until `need_bytes` fits (plus the
        normal threshold), oldest first."""
        need = int(d.get("need_bytes", 0))
        limit = max(0, int(self.config.object_store_memory
                           * self.config.object_spilling_threshold) - need)
        for oid, rec in list(self.local_objects.items()):
            if self.store_used <= limit:
                break
            if not rec["spilled"] and not self.transfer_pins.pinned(oid):
                await self._spill_one(oid, rec)
        return True

    async def h_pin_object(self, conn, d):
        rec = self.local_objects.get(d["object_id"])
        if rec is not None:
            rec["pinned"] = bool(d.get("pinned", True))
        return True

    async def h_free_objects(self, conn, d):
        for oid in d["object_ids"]:
            # atomic check-and-defer: a registered transfer defers the
            # free until the last pin drops or its TTL lease lapses (the
            # _reap_loop sweep completes it); the one-step form cannot
            # race a concurrent last-unpin into a stranded deferral
            if self.transfer_pins.defer_free_if_pinned(oid):
                continue
            await self._free_one(oid)
        return True

    async def _free_one(self, oid: bytes):
        rec = self.local_objects.pop(oid, None)
        if rec is None:
            return
        freed = 0
        if rec["spilled"]:
            try:
                os.unlink(rec["spilled"])
            except FileNotFoundError:
                pass
        else:
            freed = self.store.delete(ObjectID(oid))
        self.store_used = max(0, self.store_used - freed)
        if self.gcs is not None:
            try:
                await self.gcs.call("remove_object_location", {
                    "object_id": oid, "node_id": self.node_id.binary()})
            except Exception:
                pass

    async def _complete_deferred_frees(self, oids):
        for oid in oids:
            await self._free_one(oid)

    def complete_deferred_frees_threadsafe(self, oids):
        """Entry point for bulk-channel threads whose connection teardown
        released the last pin on a free-deferred object."""
        if self._loop is None or not oids:
            return
        try:
            asyncio.run_coroutine_threadsafe(
                self._complete_deferred_frees(list(oids)), self._loop)
        except RuntimeError:
            pass

    async def _maybe_spill(self):
        """Spill cold unpinned objects to disk above the usage threshold
        (reference: local_object_manager.h SpillObjects). Safe on BOTH
        backends: the files store copies before unlink, and the native
        arena's delete zombifies under outstanding reader pins (store.cc
        rts_delete) — the block is only reused after the last zero-copy
        view releases, so spilling can never corrupt a live reader.
        Zombie blocks do keep arena bytes busy until released, which is
        why the threshold leaves headroom below physical capacity."""
        limit = int(self.config.object_store_memory
                    * self.config.object_spilling_threshold)
        if self.store_used <= limit:
            return
        os.makedirs(self.spill_dir, exist_ok=True)
        for oid, rec in list(self.local_objects.items()):
            if self.store_used <= limit:
                break
            # reference semantics: the pin blocks EVICTION (losing the
            # only copy), not spilling — the spill file preserves the
            # bytes, so even owner-pinned primaries may move to disk
            # under pressure (local_object_manager.h SpillObjects spills
            # pinned primaries exactly the same way)
            if rec["spilled"]:
                continue
            if self.transfer_pins.pinned(oid):
                # a registered transfer is streaming this object out of
                # the arena right now: deleting the store entry under it
                # would abort the stream (and on the files backend orphan
                # the mmap) — skip until the pin lease lapses
                continue
            await self._spill_one(oid, rec)

    async def _spill_one(self, oid: bytes, rec: dict):
        object_id = ObjectID(oid)
        buf = self.store.get(object_id)
        if buf is None:
            return
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, object_id.hex())
        with open(path, "wb") as f:
            f.write(buf.view)
        buf.close()
        self.store.delete(object_id)
        rec["spilled"] = path
        self.store_used -= rec["size"]
        logger.info("spilled %s (%d bytes)", object_id.hex()[:12],
                    rec["size"])

    async def _restore_spilled(self, oid: bytes):
        rec = self.local_objects.get(oid)
        if rec is None or not rec["spilled"]:
            return
        object_id = ObjectID(oid)
        with open(rec["spilled"], "rb") as f:
            data = f.read()
        try:
            self.store.put_bytes(object_id, data)
        except MemoryError:
            # the store is the reason this object was spilled — push
            # other residents out until this one fits, then retry once
            # (bounded: spilling everything would thrash alternating
            # restores into O(n²) disk churn)
            target = max(
                0, int(self.config.object_store_memory
                       * self.config.object_spilling_threshold)
                - rec["size"])
            for other, orec in list(self.local_objects.items()):
                if self.store_used <= target:
                    break
                if (other != oid and not orec["spilled"]
                        and not self.transfer_pins.pinned(other)):
                    await self._spill_one(other, orec)
            self.store.put_bytes(object_id, data)
        os.unlink(rec["spilled"])
        rec["spilled"] = None
        self.store_used += rec["size"]

    # ------------------------------------------------------------------
    # cluster info
    # ------------------------------------------------------------------

    async def h_set_resource(self, conn, d):
        """Dynamically resize one resource's capacity on this node
        (reference: ray.experimental.set_resource →
        node_manager.cc resource update path). Capacity 0 deletes it."""
        from ray_tpu._private.common import quantize

        name = d["resource_name"]
        new_total = quantize(float(d["capacity"]))
        old_total = self.total.raw().get(name, 0)
        delta = new_total - old_total
        t = self.total.raw()
        a = self.available.raw()
        if new_total <= 0:
            # delete from totals, but keep availability DELTA accounting:
            # leases still out will release back into `a`, and dropping
            # the entry here would let that release resurrect capacity
            # for a resource that no longer exists
            t.pop(name, None)
            a[name] = a.get(name, 0) - old_total
            if a[name] == 0:
                a.pop(name)
        else:
            t[name] = new_total
            a[name] = a.get(name, 0) + delta  # may go negative while busy
        self.total = ResourceSet.from_raw(t)
        self.available = ResourceSet.from_raw(a)
        # fresh capacity may unblock queued leases
        await self._dispatch_pending()
        return {"total": self.total.raw(), "available": self.available.raw()}

    def _gauge_snapshot(self, snap: dict) -> dict:
        """Fold this raylet's live gauges into a metrics snapshot — used
        by BOTH h_get_metrics and the heartbeat piggyback, so the GCS
        metrics-history rings (what the autoscaler's busy/idle predicate
        reads) carry the same series the direct RPC shows."""
        snap["raylet.num_workers"] = {"type": "gauge",
                                      "value": len(self.workers)}
        snap["raylet.store_used_bytes"] = {"type": "gauge",
                                           "value": self.store_used}
        snap["raylet.local_objects"] = {"type": "gauge",
                                        "value": len(self.local_objects)}
        snap["raylet.pending_leases"] = {"type": "gauge",
                                         "value": len(self.pending_leases)}
        snap["raylet.active_leases"] = {
            "type": "gauge",
            "value": sum(1 for w in self.workers.values()
                         if w.lease_id is not None
                         or w.actor_id is not None)}
        snap["raylet.transfer_pins"] = {"type": "gauge",
                                        "value": self.transfer_pins.count()}
        return snap

    async def h_get_metrics(self, conn, d):
        from ray_tpu._private import stats

        snap = self._gauge_snapshot(stats.snapshot())
        # fold in per-worker process metrics (user-defined metrics from
        # util/metrics.py live in worker processes)
        import asyncio

        async def _pull(conn):
            try:
                return await asyncio.wait_for(
                    conn.call("get_stats", {}), timeout=2.0)
            except Exception:
                return {}

        # workers AND connected drivers: the submit-side task histograms
        # (core.task_lease_wait_s etc.) live in the OWNER process, which
        # for driver-submitted work is the driver — without its fold the
        # doctor's K*p99 thresholds would never see those stages
        conns = [w.conn for w in list(self.workers.values())
                 if not w.conn.closed]
        conns += [c for c in list(self.server.connections)
                  if c.context.get("driver") and not c.closed]
        worker_snaps = await asyncio.gather(*[_pull(c) for c in conns])
        # raylet-owned names are never clobbered by a worker metric that
        # happens to share the name; incompatible merges log once
        reserved = set(snap)
        logged = self._metric_merge_logged
        for ws in worker_snaps:
            for name, m in ws.items():
                cur = snap.get(name)
                if cur is None:
                    snap[name] = dict(m)
                elif m.get("type") == "count" and cur.get("type") == "count":
                    cur["value"] = cur.get("value", 0) + m.get("value", 0)
                elif (m.get("type") == "histogram"
                      and cur.get("type") == "histogram"
                      and m.get("boundaries") == cur.get("boundaries")):
                    cur["counts"] = [a + b for a, b in
                                     zip(cur["counts"], m["counts"])]
                    cur["sum"] = cur.get("sum", 0) + m.get("sum", 0)
                    cur["count"] = cur.get("count", 0) + m.get("count", 0)
                elif (name in reserved or m.get("type") != cur.get("type")
                      or m.get("type") == "histogram"):
                    # reserved-name collision, cross-type collision, or
                    # histograms whose bucket boundaries disagree:
                    # dropping is the only merge that doesn't corrupt one
                    # side (only same-type worker gauges may overwrite)
                    if name not in logged:
                        logged.add(name)
                        logger.warning(
                            "worker metric %r (%s) conflicts with an "
                            "existing %s metric (reserved=%s); worker "
                            "values are dropped from the merged snapshot",
                            name, m.get("type"), cur.get("type"),
                            name in reserved)
                else:
                    snap[name] = dict(m)  # worker gauges: last writer wins
        return snap

    async def h_cluster_info(self, conn, d):
        return {
            "node_id": self.node_id.binary(),
            "nodes": list(self.cluster_nodes.values()),
            "total": self.total.raw(),
            "available": self.available.raw(),
            "num_workers": len(self.workers),
            "store_used": self.store_used,
            "num_local_objects": len(self.local_objects),
            # Same-host drivers attach to this store directly (zero-copy).
            "session_dir": self.session_dir,
            "store_root": self.store_root,
            "bulk_address": self.bulk_address,
            # object transfer plane counters (dashboard /api/objects)
            "transfer": {
                "pull_bytes_total": transfer.M_PULL_BYTES.snapshot()["value"],
                "pulls_striped_total":
                    transfer.M_PULLS_STRIPED.snapshot()["value"],
                "inflight_chunks":
                    transfer.M_INFLIGHT_CHUNKS.snapshot()["value"],
                "transfer_pins": self.transfer_pins.count(),
            },
        }

    async def h_debug_state(self, conn, d):
        """Live-state snapshot of this raylet: worker pool, lease queue
        with ages, spillback grants awaiting adoption, object/transfer
        plane, rpc depth. With include_workers=True, fans out to every
        registered worker's debug_state (bounded per-worker wait) so one
        call answers for the whole node."""
        t_start = time.monotonic()
        now = time.time()
        mono = time.monotonic()
        pool = []
        idle = set(id(w) for w in self.idle) | set(
            id(w) for w in self.idle_tpu)
        for w in list(self.workers.values()):
            pool.append({
                "worker_id": w.worker_id.hex()[:16],
                "pid": w.pid,
                "address": w.address,
                "flavor": w.flavor,
                "lease_id": w.lease_id.hex() if w.lease_id else "",
                "actor_id": (w.actor_id.hex()[:16]
                             if w.actor_id else ""),
                "idle": id(w) in idle,
            })
        pending = []
        for spec, fut in list(self.pending_leases):
            q = spec.get("_queued_at")
            ctx = tracing.from_wire(spec.get("trace"))
            pending.append({
                "name": spec.get("name", "?"),
                "age_s": round(now - q, 3) if q else None,
                "resources": dict(spec.get("resources") or {}),
                "trace_id": ctx.trace_id.hex() if ctx is not None else "",
            })
        spilled = sum(1 for r in self.local_objects.values()
                      if r.get("spilled"))
        snap = {
            "role": "raylet",
            "node_id": self.node_id.hex()[:8],
            "address": self.address,
            "is_head": self.is_head,
            "topology": (self.topology.to_dict()
                         if self.topology is not None else None),
            "resources": {"total": self.total.raw(),
                          "available": self.available.raw()},
            "worker_pool": pool,
            "idle_workers": len(self.idle) + len(self.idle_tpu),
            "starting_workers": self.starting + self.starting_tpu,
            "pending_leases": pending,
            "unadopted_spillback_grants": [
                {"lease_id": lid.hex(),
                 "expires_in_s": round(dl - mono, 3)}
                for lid, dl in list(self._unadopted.items())],
            "objects": {"local_objects": len(self.local_objects),
                        "store_used_bytes": self.store_used,
                        "spilled": spilled,
                        "pulls_inflight": len(self._pulls_inflight)},
            "transfers": transfer.debug_transfers(self.transfer_pins),
            "bundles": len(self.bundles),
            "rpc": {"server_conns": len(self.server.connections),
                    "gcs_depth": (_debug.conn_depth(self.gcs.director)
                                  if self.gcs is not None else 0)},
        }
        if d.get("include_workers"):
            async def one(w):
                try:
                    state = await asyncio.wait_for(
                        w.conn.call("debug_state", {}), timeout=2.0)
                except Exception as e:
                    state = {"error": f"{type(e).__name__}: {e}",
                             "pid": w.pid}
                return w.worker_id.hex()[:16], state

            got = await asyncio.gather(
                *(one(w) for w in list(self.workers.values())
                  if not w.conn.closed))
            snap["workers"] = dict(got)

            # connected DRIVERS too (duplex conns carry their handlers):
            # driver-owned task state — e.g. a task stuck in lease_wait,
            # which lives only in the owner's `submitted` table — is
            # otherwise invisible to the out-of-process surfaces
            async def one_driver(conn, info):
                pid = (info or {}).get("pid")
                try:
                    state = await asyncio.wait_for(
                        conn.call("debug_state", {}), timeout=2.0)
                except Exception as e:
                    state = {"error": f"{type(e).__name__}: {e}",
                             "pid": pid}
                return str(pid or id(conn)), state

            drivers = [(c, c.context.get("driver"))
                       for c in list(self.server.connections)
                       if c.context.get("driver") and not c.closed]
            if drivers:
                got = await asyncio.gather(
                    *(one_driver(c, info) for c, info in drivers))
                snap["drivers"] = dict(got)
        return _debug.finish_snapshot(snap, t_start)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def _handle_gcs_push(self, channel, data):
        if channel == _fp.CHANNEL:
            _fp.apply_kv_value(data)
            return
        if channel == tracing.CHANNEL:
            tracing.apply_kv_value(data)
            return
        if channel == _sprof.CHANNEL:
            _sprof.apply_kv_value(data)
            return
        if channel == "nodes":
            node = data["node"]
            if data["event"] in ("added", "updated"):
                self.cluster_nodes[node["node_id"]] = node
            else:
                self.cluster_nodes.pop(node["node_id"], None)
                await self._dispatch_pending()

    async def _reap_loop(self):
        while True:
            await asyncio.sleep(1.0)
            try:
                self._reap_starting_workers()
            except Exception:
                logger.exception("starting-worker reap failed")
            try:
                await self._respill_pending()
            except Exception:
                logger.exception("pending-lease respill failed")
            try:
                # expire transfer-pin leases left by dead pullers and run
                # the frees they were deferring
                freeable = self.transfer_pins.sweep()
                if freeable:
                    await self._complete_deferred_frees(freeable)
            except Exception:
                logger.exception("transfer-pin sweep failed")
            try:
                if self._reap_unadopted():
                    await self._dispatch_pending()
            except Exception:
                logger.exception("unadopted-lease reap failed")

    async def _respill_pending(self):
        """Queued leases get re-offered to nodes that NOW have capacity
        (a node joined or freed up since the lease queued) — without
        this, work queued before an autoscaled node arrives would wait
        on the saturated node forever (reference: the periodic
        ScheduleAndDispatchTasks in cluster_task_manager.cc)."""
        if not self.pending_leases or len(self.cluster_nodes) <= 1:
            return
        if self.gcs is None:
            return
        # ONE await up front; the scan below is synchronous, so it cannot
        # interleave with _dispatch_pending / h_request_worker_lease (both
        # mutate pending_leases on this loop) and drop their entries.
        try:
            raw = await self.gcs.call("get_available_resources", {})
        except Exception:
            return
        avail = {nid: ResourceSet.from_raw(r) for nid, r in raw.items()}
        still = []
        for spec, fut in self.pending_leases:
            if fut.done():
                continue
            if (self._bundle_key(spec) is not None
                    or not self._feasible_ever(spec)):
                still.append((spec, fut))
                continue
            addr = self._pick_from_availability(spec, avail)
            if addr is not None:
                self.m_spillbacks.inc()
                fut.set_result({"spillback": addr, "hops": 1})
            else:
                still.append((spec, fut))
        self.pending_leases = still

    # ------------------------------------------------------------------
    # elastic membership: graceful drain (planned departure)
    # ------------------------------------------------------------------

    async def h_drain(self, conn, d):
        """GCS asks this raylet to leave gracefully (autoscaler scale-down,
        `ray-tpu drain`, or our own preemption notice echoed back).
        Returns immediately; the drain itself runs in the background so
        the GCS RPC doesn't ride out the whole deadline. Idempotent: a
        second drain (e.g. a preemption notice landing mid-drain) just
        reports the in-progress state."""
        if self._draining:
            return {"state": "DRAINING"}
        self._draining = True
        self.m_drains.inc()
        deadline_s = float(d.get("deadline_s")
                           or self.config.drain_deadline_s)
        preempt = bool(d.get("preempt"))
        logger.info("drain requested (%s, deadline %.1fs): %d local "
                    "objects, %d workers",
                    "preempt" if preempt else "planned", deadline_s,
                    len(self.local_objects), len(self.workers))
        self._drain_task = asyncio.create_task(
            self._drain(deadline_s, preempt))
        return {"state": "DRAINING"}

    async def _drain(self, deadline_s: float, preempt: bool):
        """Planned departure: make the node's disappearance free.
        Normal order: bounce the lease queue, migrate plasma to
        survivors, let in-flight leases finish, checkpoint actors.
        Preemption compresses the window (TPU spot gives seconds), so
        the order flips: checkpoints first — they're small and
        irreplaceable — objects best-effort with whatever remains.
        Whatever misses the deadline takes exactly the crash path
        (typed reclaim/loss), scoped to the leftovers."""
        deadline = time.monotonic() + deadline_s
        self._drain_migrated: set[bytes] = set()
        skip_migrate = False
        if _fp.ARMED:
            # drain seam: `delay` stretches the window so chaos can kill
            # the node mid-drain; `raise` skips the migration pass
            # entirely (every object becomes a leftover)
            try:
                await _fp.fire_async_strict("raylet.drain")
            except _fp.FailpointError:
                skip_migrate = True
        try:
            self._drain_bounce_pending()
            migrated = 0
            if preempt:
                await self._drain_checkpoint_actors(deadline)
                if not skip_migrate:
                    migrated = await self._drain_migrate_objects(deadline)
            else:
                if not skip_migrate:
                    migrated = await self._drain_migrate_objects(deadline)
                await self._drain_wait_leases(deadline)
                if not skip_migrate:
                    # in-flight tasks wrote their returns to plasma AFTER
                    # the first pass — a second sweep migrates those too,
                    # so finishing-during-drain never means losing the
                    # result bytes
                    migrated = await self._drain_migrate_objects(deadline)
                await self._drain_checkpoint_actors(deadline)
            leftovers = sum(1 for oid in self.local_objects
                            if oid not in self._drain_migrated)
            logger.info("drain complete: %d objects migrated, %d left",
                        migrated, leftovers)
            try:
                await self.gcs.call("node_drained", {
                    "node_id": self.node_id.binary(),
                    "migrated": migrated,
                    "leftovers": leftovers,
                }, timeout=10.0)
            except Exception:
                # GCS unreachable: exiting anyway is correct — the
                # heartbeat checker reaps us through the crash path
                logger.warning("node_drained report failed; exiting anyway")
        except Exception:
            logger.exception("drain failed; exiting through the crash path")
            self._fail_stop("drain error")
        self._drain_exit()

    def _drain_bounce_pending(self):
        """Queued-but-ungranted leases spill to survivors via the normal
        owner-visible bounce; requests with no feasible survivor stay
        queued — the exit-time connection close routes them through the
        owner's retry machinery like any node loss."""
        still = []
        for spec, fut in self.pending_leases:
            if fut.done():
                continue
            addr = self._pick_spillback(spec)
            if addr is not None:
                self.m_spillbacks.inc()
                fut.set_result({"spillback": addr, "hops": 1})
            else:
                still.append((spec, fut))
        self.pending_leases = still

    async def _drain_migrate_objects(self, deadline: float) -> int:
        """Actively push every resident plasma object to a survivor:
        notify the target with a push_hint (it runs a normal striped
        pull over the bulk channel with us as the seed source), then
        poll the GCS directory until a survivor is listed as a holder —
        only a directory-confirmed copy counts as migrated, so the
        object stays resolvable after our locations drop. Bounded by
        drain_migrate_concurrency and the deadline."""
        me = self.node_id.binary()
        survivors = [
            info for nid, info in self.cluster_nodes.items()
            if nid != me and info.get("state", "ALIVE") == "ALIVE"
            and info.get("address")
        ]
        if not survivors or self.gcs is None:
            return 0
        sem = asyncio.Semaphore(
            max(1, self.config.drain_migrate_concurrency))

        async def _one(idx: int, oid: bytes, rec: dict):
            async with sem:
                if time.monotonic() >= deadline:
                    return
                if _fp.ARMED:
                    # migrate seam: `raise` turns THIS object into a
                    # leftover (typed loss downstream); `delay` holds an
                    # object mid-flight across the chaos kill window
                    try:
                        await _fp.fire_async_strict("transfer.migrate")
                    except _fp.FailpointError:
                        return
                target = survivors[idx % len(survivors)]
                try:
                    tconn = await self._raylet_conn(target["address"])
                    await tconn.notify("push_hint", {
                        "object_id": oid, "from": self.address})
                except Exception as e:
                    logger.warning("drain push to %s failed: %s",
                                   target["address"], e)
                    return
                while time.monotonic() < deadline:
                    try:
                        nodes = await self.gcs.call(
                            "get_object_locations", {"object_id": oid})
                    except Exception:
                        return
                    if any(n != me for n in nodes or ()):
                        self._drain_migrated.add(oid)
                        self.m_drain_migrated_bytes.inc(
                            int(rec.get("size") or 0))
                        return
                    await asyncio.sleep(0.05)

        todo = [(oid, rec) for oid, rec in self.local_objects.items()
                if oid not in self._drain_migrated]
        await asyncio.gather(
            *(_one(i, oid, rec) for i, (oid, rec) in enumerate(todo)),
            return_exceptions=True)
        return len(self._drain_migrated)

    async def _drain_wait_leases(self, deadline: float):
        """Let in-flight tasks run to completion (actors are handled by
        the checkpoint step — they never finish on their own). Leases
        still live at the deadline are reclaimed through the normal
        typed machinery when the node exits."""
        while time.monotonic() < deadline:
            if not any(w.lease_id is not None for w in self.workers.values()):
                return
            await asyncio.sleep(0.1)

    async def _drain_checkpoint_actors(self, deadline: float):
        """Snapshot restartable actor state to the control plane: each
        actor worker runs the actor's __ray_checkpoint__() hook (if
        defined) and we land the pickled state in the GCS KV — a
        survivor by construction — keyed by actor id. The GCS then
        relocates the actor (planned, no restart burned) and the new
        incarnation restores via __ray_restore__. Actors without the
        hook relocate stateless, exactly like today."""
        for w in list(self.workers.values()):
            if w.actor_id is None or w.conn.closed:
                continue
            budget = deadline - time.monotonic()
            if budget <= 0:
                return
            try:
                reply = await asyncio.wait_for(
                    w.conn.call("checkpoint_actor", {}),
                    timeout=max(0.2, budget))
                state = (reply or {}).get("state")
                if state is not None:
                    await self.gcs.call("kv_put", {
                        "key": f"actor_ckpt:{w.actor_id.hex()}",
                        "value": state})
            except Exception as e:
                logger.warning("checkpoint of actor %s failed: %s",
                               w.actor_id.hex()[:8], e)

    def _drain_exit(self):
        """Graceful twin of _fail_stop: the GCS already finalized us as
        DRAINED (or will reap us), so stop accepting work and leave with
        status 0. Workers get the intended-exit notice first so their
        owners see a clean actor exit, not a crash."""
        logger.info("raylet exiting after drain")
        self._shutting_down = True
        for w in list(self.workers.values()):
            try:
                w.conn.context["intended_exit"] = True
                os.kill(w.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        for proc, _flavor in self._starting_procs:
            try:
                proc.kill()
            except OSError:
                pass
        os._exit(0)

    def _fail_stop(self, reason: str):
        """Fail-stop this node: kill every worker and exit. A raylet the
        GCS has given up on must NOT linger as a split-brain zombie that
        still grants leases and runs tasks nobody can reach — the rest of
        the cluster already declared this node dead and rescheduled its
        actors (reference: raylets exit when disconnected from the GCS)."""
        logger.error("raylet fail-stop: %s — killing %d worker(s) and "
                     "exiting", reason, len(self.workers))
        self._shutting_down = True
        for w in list(self.workers.values()):
            try:
                os.kill(w.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        for proc, _flavor in self._starting_procs:
            try:
                proc.kill()
            except OSError:
                pass
        os._exit(1)

    def _heartbeat_metrics(self) -> dict | None:
        """Every 4th beat (~2s) the heartbeat piggybacks this raylet's
        metric snapshot for the GCS time-series ring. A fired
        metrics.push failpoint skips the sample — never the beat."""
        self._beat_n += 1
        if self._beat_n % 4:
            return None
        try:
            if _fp.ARMED:
                _fp.fire_strict("metrics.push")
        except _fp.FailpointError:
            return None
        from ray_tpu._private import stats

        return self._gauge_snapshot(stats.snapshot())

    async def _flush_profile(self):
        """Flush recorded trace spans / profile events to the GCS (~2s
        cadence off the heartbeat loop); a failed flush requeues into
        the bounded buffer like the core-worker path."""
        now = time.monotonic()
        if now - self._last_profile_flush < 2.0:
            return
        self._last_profile_flush = now
        if self.gcs is None:
            return
        await self._flush_profile_samples()
        events = self._profile.drain()
        if not events:
            return
        try:
            if _fp.ARMED:
                _fp.fire_strict("trace.flush")
            await self.gcs.notify("add_profile_events", {
                "component_type": "raylet",
                "component_id": os.getpid(),
                "node_id": self.node_id.binary(),
                "events": events,
            })
        except Exception:
            self._profile.requeue(events)

    async def _flush_profile_samples(self):
        """Flush the continuous-profiler window into the GCS profile
        ring (sampling_profiler.flush_to: the shared drain +
        `profile.flush` seam + bounded merge-back contract)."""
        await _sprof.flush_to(self.gcs, "raylet",
                              node_id=self.node_id.binary())

    async def heartbeat_loop(self):
        interval = self.config.heartbeat_interval_s
        window = max(self.config.gcs_reconnect_timeout_s, 2 * interval)
        last_ok = time.monotonic()
        while True:
            await asyncio.sleep(interval)
            if _fp.ARMED and not self._draining:
                # preemption-notice seam: stands in for the cloud
                # metadata "you have N seconds" signal (TPU spot). The
                # notice starts a COMPRESSED drain through the GCS so
                # the departure is cluster-visible — checkpoints first,
                # objects best-effort (idempotent if already draining).
                try:
                    await _fp.fire_async_strict("node.preempt_notice")
                except _fp.FailpointError:
                    logger.warning("preemption notice received: "
                                   "requesting compressed drain")
                    try:
                        await self.gcs.call("drain_node", {
                            "node_id": self.node_id.binary(),
                            "preempt": True,
                        }, timeout=5.0)
                    except Exception:
                        logger.warning("preempt drain request failed; "
                                       "retrying next beat")
            try:
                if _fp.ARMED:
                    await _fp.fire_async_strict("raylet.heartbeat")
                beat = {
                    "node_id": self.node_id.binary(),
                    "available": self.available.raw(),
                }
                metrics = self._heartbeat_metrics()
                if metrics is not None:
                    beat["metrics"] = metrics
                    beat["metrics_source"] = (
                        f"{self.node_id.hex()[:8]}/raylet")
                # Bounded per-beat: a HUNG (not dead) GCS must not park
                # this call forever — that would stop the failure clock
                # and leave exactly the zombie this loop exists to kill.
                await self.gcs.call("heartbeat", beat,
                                    timeout=max(2.0, 4 * interval))
                last_ok = time.monotonic()
                try:
                    await self._flush_profile()
                except Exception:
                    logger.exception("profile flush failed")
            except Exception:
                logger.warning("heartbeat to GCS failed")
                if time.monotonic() - last_ok > window:
                    # Continuous failure past the reconnect window: the
                    # GCS has long since declared us dead (heartbeat
                    # timeout is far shorter) — fail-stop, don't zombie.
                    self._fail_stop(
                        f"heartbeats failing for >{window:.0f}s "
                        f"(GCS reconnect window)")

    async def run(self, port: int = 0, ready_file: str | None = None):
        self._loop = asyncio.get_running_loop()
        _debug.start_loop_lag_monitor()
        _sprof.start("raylet")
        actual = await self.server.start_tcp(
            host=self.config.bind_host, port=port,
            uds_dir=os.path.join(self.session_dir, "sock"))
        self.address = f"{self.config.node_ip_address}:{actual}"
        try:
            # bulk object data plane: sibling listener, own threads —
            # object bytes never touch the control connection again
            self.bulk_address = self.bulk.start(
                self.config.bind_host, self.config.node_ip_address,
                os.path.join(self.session_dir, "sock"))
        except OSError as e:  # pragma: no cover - bind quirks
            logger.warning("bulk transfer channel disabled: %s", e)
            self.bulk_address = ""

        async def _gcs_session(conn):
            """(Re-)establish GCS session state: subscribe, refresh the
            cluster view, re-register this node. Runs on first connect and
            again after every GCS restart (reference: raylet re-registers
            via service_based_gcs_client reconnection)."""
            await conn.call("subscribe", {"channel": "nodes"})
            await conn.call("subscribe", {"channel": _fp.CHANNEL})
            armed = await conn.call("kv_get", {"key": _fp.KV_KEY})
            if armed:
                _fp.apply_kv_value(armed)
            await conn.call("subscribe", {"channel": tracing.CHANNEL})
            rate = await conn.call("kv_get", {"key": tracing.KV_KEY})
            if rate:
                tracing.apply_kv_value(rate)
            await conn.call("subscribe", {"channel": _sprof.CHANNEL})
            hz = await conn.call("kv_get", {"key": _sprof.KV_KEY})
            if hz:
                _sprof.apply_kv_value(hz)
            nodes = await conn.call("get_all_nodes", {})
            self.cluster_nodes = {n["node_id"]: n for n in nodes}
            await conn.call("register_node", {
                "node_id": self.node_id.binary(),
                "address": self.address,
                "bulk_address": self.bulk_address,
                "resources": self.total.raw(),
                "available": self.available.raw(),
                "hostname": os.uname().nodename,
                "is_head": self.is_head,
                "labels": self.labels,
                "tpu_slice": self.tpu_slice,
                "topology": (self.topology.to_dict()
                             if self.topology is not None else None),
            })

        def _gcs_gone():
            self._fail_stop("GCS unreachable past reconnect timeout")

        # Duplex: the GCS drives actor creation and bundle 2PC back over
        # this connection; it survives GCS restarts.
        uds_dir = os.path.join(self.session_dir, "sock")
        director = rpc.ReconnectingConnection(
            rpc.prefer_uds(self.gcs_address, uds_dir,
                           local_ips=("127.0.0.1",
                                      self.config.node_ip_address)),
            handlers=self._handlers(), name="raylet->gcs",
            on_reconnect=_gcs_session,
            retry_timeout=self.config.gcs_reconnect_timeout_s,
            on_give_up=_gcs_gone)
        # Sharded control plane: the object-directory ops this raylet
        # issues per seal/free/pull (the hottest steady-state stream)
        # key-route straight to the owning store shard; membership,
        # heartbeats, scheduling and pubsub stay on the director. With
        # gcs_shards=1 (default) this is a pure passthrough.
        from ray_tpu.gcs.client import GcsClient

        self.gcs = GcsClient(director, self.config, uds_dir=uds_dir)
        self.gcs.set_push_handler(self._handle_gcs_push)
        await _gcs_session(await director.ensure_connected())
        asyncio.create_task(self.heartbeat_loop())
        asyncio.create_task(self._reap_loop())
        prestart = self.config.num_initial_workers
        if prestart < 0:
            prestart = min(int(self.num_cpus), 8)
        for _ in range(prestart):
            self._start_worker_process()
        logger.info("raylet up at %s (node %s)", self.address,
                    self.node_id.hex()[:8])
        if ready_file:
            tmp = ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(self.address)
            os.rename(tmp, ready_file)
        while True:
            await asyncio.sleep(3600)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--store-root", required=True)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=0)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--tpu-slice", default="")
    parser.add_argument("--topology", default="",
                        help="explicit TopologyCoord JSON "
                             '({"slice_id","coords","dims"}); empty = '
                             "derive from RAY_TPU_TOPOLOGY / tpu-slice")
    parser.add_argument("--is-head", action="store_true")
    parser.add_argument("--ready-file", default=None)
    parser.add_argument("--log-file", default=None)
    args = parser.parse_args()

    import json

    from ray_tpu._private.log_utils import setup_process_logging

    setup_process_logging("raylet", args.log_file)
    _fp.set_role("raylet")
    from ray_tpu._private.events import init_events

    init_events("RAYLET", args.node_id or "",
                os.path.dirname(args.log_file) if args.log_file else None)
    set_config(Config.load())
    resources = dict(json.loads(args.resources))
    resources.setdefault("CPU", args.num_cpus
                         if args.num_cpus is not None else (os.cpu_count() or 1))
    if args.num_tpus:
        resources.setdefault("TPU", args.num_tpus)
    node_id = (NodeID.from_hex(args.node_id) if args.node_id
               else NodeID.from_random())
    raylet = Raylet(
        node_id=node_id,
        session_dir=args.session_dir,
        gcs_address=args.gcs_address,
        resources=resources,
        store_root=args.store_root,
        is_head=args.is_head,
        labels=json.loads(args.labels),
        config=get_config(),
        tpu_slice=json.loads(args.tpu_slice) if args.tpu_slice else None,
        topology=json.loads(args.topology) if args.topology else None,
    )
    asyncio.run(raylet.run(args.port, args.ready_file))


if __name__ == "__main__":
    main()
