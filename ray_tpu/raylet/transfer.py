"""Bulk object-transfer data plane between raylets.

The control-plane rpc layer (rpc.py) is msgpack frames multiplexed on ONE
connection per peer pair — fine for leases and heartbeats, wrong for bulk
data: a 5MB chunk rides the same socket as heartbeats (head-of-line
blocking), costs a bytes() copy out of the arena plus a msgpack copy on
each side, and the old stop-and-wait fetch_chunk loop paid a full RTT per
chunk. This module is the dedicated data plane (reference:
src/ray/object_manager/object_manager.h chunked push/pull +
pull_manager.h admission; design lineage: Ownership NSDI'21, Hoplite's
pipelined multi-source fetch):

* Each raylet serves a **bulk channel** — a sibling TCP listener (plus a
  same-node UDS twin, like the worker direct task channel) speaking the
  normal frame protocol for requests, served entirely by blocking
  threads. A pull is ONE request followed by a stream of chunk records;
  the sender `sendmsg`s memoryview slices straight out of the mmap'd
  store buffer (no bytes() copy-out, no pickle for payloads) and the
  receiver `recv_into`s directly into the `store.create`d buffer. The
  kernel socket buffer keeps chunks in flight ahead of the receiver's
  arena writes, so transmission overlaps storage — and the control
  connection never carries a bulk frame.

* **Multi-source striping**: when the GCS directory lists several
  holders, stripe ranges are pulled off a shared work-stealing queue by
  one worker thread per source — a slow source naturally moves fewer
  bytes, and a source dying mid-stream has its unfinished remainder
  resumed by survivors instead of restarting the pull.

* **Transfer pins**: the sender pins an object for the duration of a
  registered transfer (plus a TTL lease so a dead puller can't pin
  forever); free/eviction of a pinned object is deferred until the last
  pin drops or expires.

Chunk record wire format (after the REPLY_OK control frame):
    8-byte big-endian offset | 4-byte big-endian length | payload
terminated by the sentinel record (offset=2^64-1, length=0).

Note on copies: with the native arena store on Python >= 3.12 the send
side is true zero-copy (pinned arena view straight into sendmsg); on
3.10/3.11 NativeObjectStore.get() copies the payload out once (PEP-688
gate), so the win there is pipelining + no-pickle + control-plane
isolation rather than zero copies.

Failpoint seams: transfer.register (sender, per pull request),
transfer.chunk_send / transfer.chunk_recv (per chunk record),
transfer.pin_expire (sweep expiring a pin lease).
"""

from __future__ import annotations

import collections
import logging
import os
import pickle
import socket
import struct
import threading
import time
import traceback

import msgpack

from ray_tpu import exceptions as exc
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import rpc
from ray_tpu._private import stats as _stats
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.ids import ObjectID

logger = logging.getLogger("ray_tpu.transfer")

_HDR = struct.Struct(">I")        # control-frame length prefix (rpc format)
_CHUNK = struct.Struct(">QI")     # per-chunk record header: offset, length
_DONE_OFFSET = (1 << 64) - 1      # sentinel offset terminating a stream

M_PULL_BYTES = _stats.Count(
    "raylet.pull_bytes_total", "object bytes pulled from remote nodes")
M_PULLS_STRIPED = _stats.Count(
    "raylet.pulls_striped_total",
    "pulls that actually striped across >=2 sources")
M_INFLIGHT_CHUNKS = _stats.Gauge(
    "raylet.transfer_inflight_chunks",
    "bulk-transfer chunk records currently being sent/received")
M_PULL_S = _stats.Histogram(
    "transfer.pull_s", _stats.LATENCY_BOUNDARIES_S,
    "bulk pull wall time, registration -> object sealed (receiver "
    "side); exemplar links the pulling request's trace")

# ---------------------------------------------------------------------------
# live-transfer registry (debug_state / stall doctor): every in-flight
# streaming pull (receiver side) and serve stream (sender side) in this
# process, with age + progress — so `ray-tpu state transfers` can answer
# "which stream is stuck and how far did it get" for a live raylet.
# ---------------------------------------------------------------------------

import itertools as _itertools

_active_lock = threading.Lock()
_active_pulls: dict[int, dict] = {}
_active_serves: dict[int, dict] = {}
_active_ids = _itertools.count(1)


def _track(table: dict, entry: dict) -> int:
    token = next(_active_ids)
    with _active_lock:
        table[token] = entry
    return token


def _untrack(table: dict, token: int) -> None:
    with _active_lock:
        table.pop(token, None)


# Advisory purpose labels for upcoming pulls ("kv_warm", ...): a worker
# that knows WHY it is about to resolve a ref registers the label with
# its raylet (hint_pull_purpose rpc) before the get; the raylet's
# streaming pull consumes it so `ray-tpu state transfers` attributes the
# bytes instead of showing anonymous traffic. Bounded and best-effort —
# a missed hint only costs the label.
_pull_hints: dict[bytes, str] = {}


def hint_pull(oid: bytes, purpose: str) -> None:
    with _active_lock:
        if len(_pull_hints) >= 256:
            _pull_hints.clear()
        _pull_hints[oid] = str(purpose)[:64]


def take_pull_hint(oid: bytes) -> str:
    with _active_lock:
        return _pull_hints.pop(oid, "")


def debug_transfers(pins: "TransferPins | None" = None) -> dict:
    """Msgpack-safe snapshot of this process's in-flight transfers."""
    now = time.monotonic()
    out = {"pulls": [], "serves": []}
    with _active_lock:
        items = ([("pulls", e) for e in _active_pulls.values()]
                 + [("serves", e) for e in _active_serves.values()])
    for kind, e in items:
        remaining = e.get("remaining")
        size = e.get("size", 0)
        done = (size - remaining[0]) if remaining else e.get("sent", 0)
        out[kind].append({
            "object_id": e["object_id"],
            "age_s": round(now - e["t0"], 3),
            "size": size,
            "progress": f"{done}/{size}",
            "sources": e.get("sources", 1),
            "trace_id": e.get("trace_id", ""),
            "purpose": e.get("purpose", ""),
        })
    if pins is not None:
        out["pins"] = pins.debug()
    return out


class PullError(Exception):
    """Streaming pull failed on every source; carries per-source causes."""

    def __init__(self, oid: bytes, errors):
        self.errors = list(errors)
        detail = "; ".join(f"{a}: {type(e).__name__}: {e}"
                           for a, e in self.errors) or "no reachable source"
        super().__init__(f"pull of {oid[:6].hex()} failed: {detail}")


# ---------------------------------------------------------------------------
# sender-side transfer pins
# ---------------------------------------------------------------------------


class TransferPins:
    """Thread-safe registry of sender-side transfer pins with TTL leases.

    A pin names (token, oid): the bulk server uses one token per
    connection (released when the connection dies), the legacy
    object_info/fetch_chunk path uses one per rpc connection (released
    only by TTL/disconnect). While any unexpired pin exists for an oid,
    free/eviction is deferred: callers record the free via defer_free()
    and complete it when release/sweep reports the oid freeable."""

    def __init__(self):
        self._lock = threading.Lock()
        self._leases: dict[tuple, float] = {}   # (token, oid) -> expires_at
        self._count: dict[bytes, int] = {}      # oid -> live pin count
        self._deferred_free: set[bytes] = set()

    def pin(self, oid: bytes, token, ttl: float) -> None:
        """Take (or refresh) one pin lease on `oid` for `token`."""
        now = time.monotonic()
        with self._lock:
            key = (token, oid)
            if key not in self._leases:
                self._count[oid] = self._count.get(oid, 0) + 1
            self._leases[key] = now + ttl

    def pinned(self, oid: bytes) -> bool:
        with self._lock:
            return self._count.get(oid, 0) > 0

    def cancel_deferred_free(self, oid: bytes) -> None:
        """The object was re-created (re-seal by a retried producer, a
        fresh pull): a stale deferral from its PREVIOUS incarnation must
        not delete the new, legitimate copy when the old pins drop."""
        with self._lock:
            self._deferred_free.discard(oid)

    def defer_free_if_pinned(self, oid: bytes) -> bool:
        """Atomically: if `oid` is still pinned, record that it should be
        freed once its last pin drops and return True; else return False
        (the caller frees now). One atomic step — a separate
        pinned()-then-defer would race a concurrent release dropping the
        last pin in between, stranding the deferred free forever."""
        with self._lock:
            if self._count.get(oid, 0) > 0:
                self._deferred_free.add(oid)
                return True
            return False

    def unpin(self, oid: bytes, token) -> list[bytes]:
        """Release ONE (token, oid) lease — not the token's whole pin
        set. Returns [oid] if its deferred free became runnable."""
        with self._lock:
            key = (token, oid)
            if key not in self._leases:
                return []
            del self._leases[key]
            freed = self._drop(key)
            return [freed] if freed is not None else []

    def _drop(self, key) -> bytes | None:
        """Lock held. Drop one lease; returns the oid if it became
        freeable (last pin gone AND a free was deferred)."""
        oid = key[1]
        n = self._count.get(oid, 1) - 1
        if n <= 0:
            self._count.pop(oid, None)
            if oid in self._deferred_free:
                self._deferred_free.discard(oid)
                return oid
        else:
            self._count[oid] = n
        return None

    def release_token(self, token) -> list[bytes]:
        """Release every pin held by `token` (connection closed).
        Returns oids whose deferred free became runnable."""
        freeable = []
        with self._lock:
            for key in [k for k in self._leases if k[0] == token]:
                del self._leases[key]
                oid = self._drop(key)
                if oid is not None:
                    freeable.append(oid)
        return freeable

    def sweep(self, now: float | None = None) -> list[bytes]:
        """Expire stale leases (dead pullers). Returns freeable oids."""
        now = time.monotonic() if now is None else now
        freeable = []
        with self._lock:
            for key, expires in [(k, v) for k, v in self._leases.items()]:
                if expires > now:
                    continue
                if _fp.ARMED:
                    # pin-expiry seam: `raise` aborts this sweep pass
                    # (retried next tick); `delay` stretches the lease
                    _fp.fire("transfer.pin_expire")
                del self._leases[key]
                oid = self._drop(key)
                if oid is not None:
                    freeable.append(oid)
            # belt-and-braces: a deferred free whose pins are already
            # all gone (e.g. recorded after a racing release) completes
            # on the next sweep instead of stranding forever
            for oid in list(self._deferred_free):
                if self._count.get(oid, 0) <= 0:
                    self._deferred_free.discard(oid)
                    freeable.append(oid)
        return freeable

    def count(self) -> int:
        with self._lock:
            return len(self._leases)

    def debug(self) -> dict:
        """Per-object pin state for debug_state: live pin count and the
        seconds until the soonest lease expiry (negative = overdue for
        the next sweep)."""
        now = time.monotonic()
        out: dict[str, dict] = {}
        with self._lock:
            for (token, oid), expires in self._leases.items():
                rec = out.setdefault(oid.hex()[:12], {
                    "pins": 0, "expires_in_s": None, "deferred_free": False})
                rec["pins"] += 1
                left = round(expires - now, 3)
                if rec["expires_in_s"] is None or left < rec["expires_in_s"]:
                    rec["expires_in_s"] = left
            for oid in self._deferred_free:
                out.setdefault(oid.hex()[:12], {
                    "pins": 0, "expires_in_s": None,
                    "deferred_free": True})["deferred_free"] = True
        return out


# ---------------------------------------------------------------------------
# low-level socket helpers (blocking sockets, bulk-channel threads only)
# ---------------------------------------------------------------------------


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("bulk channel closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_exact_into(sock, view: memoryview) -> None:
    got = 0
    while got < len(view):
        n = sock.recv_into(view[got:])
        if n == 0:
            raise ConnectionError("bulk channel closed mid-chunk")
        got += n


def _sendmsg_all(sock, *parts) -> None:
    """Vectored sendall: one sendmsg per syscall-burst, straight from the
    caller's buffers (no join, no copy), with partial-send resume."""
    bufs = [memoryview(p).cast("B") for p in parts if len(p)]
    while bufs:
        n = sock.sendmsg(bufs)
        while bufs and n >= len(bufs[0]):
            n -= len(bufs[0])
            bufs.pop(0)
        if bufs and n:
            bufs[0] = bufs[0][n:]


def _read_control_frame(sock):
    (length,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    return msgpack.unpackb(_recv_exact(sock, length), raw=False)


def _dial(address: str, connect_timeout: float, io_timeout: float):
    """Dial a bulk address: 'unix:/path' or 'host:port'."""
    if address.startswith("unix:"):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(connect_timeout)
        sock.connect(address[len("unix:"):])
    else:
        host, _, port = address.rpartition(":")
        sock = socket.create_connection((host, int(port)),
                                        timeout=connect_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(io_timeout)
    return sock


# ---------------------------------------------------------------------------
# sender: the bulk channel server
# ---------------------------------------------------------------------------


class BulkTransferServer:
    """Serves streaming pulls out of this node's object store.

    Runs entirely on daemon threads (one acceptor per listener, one per
    connection): bulk byte-pushing must never occupy the raylet's asyncio
    loop, which carries heartbeats and lease grants. Raylet state it
    reads (local_objects) is GIL-atomic dict access; spill restores are
    delegated to the raylet loop via run_coroutine_threadsafe."""

    def __init__(self, raylet):
        self.raylet = raylet
        self.address = ""          # advertised host:port
        self._listeners: list = []
        self._shutdown = False

    def start(self, bind_host: str, advertise_ip: str,
              uds_dir: str | None) -> str:
        tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        tcp.bind((bind_host, 0))
        tcp.listen(16)
        port = tcp.getsockname()[1]
        self.address = f"{advertise_ip}:{port}"
        self._listeners.append(tcp)
        threading.Thread(target=self._accept_loop, args=(tcp,),
                         name="bulk-accept-tcp", daemon=True).start()
        if uds_dir is not None:
            # Same-node twin keyed by the TCP port, so rpc.prefer_uds
            # rewrites the advertised address exactly like rpc listeners.
            try:
                os.makedirs(uds_dir, exist_ok=True)
                path = rpc.uds_address(uds_dir, port)[len("unix:"):]
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
                uds = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                uds.bind(path)
                uds.listen(16)
                self._listeners.append(uds)
                threading.Thread(target=self._accept_loop, args=(uds,),
                                 name="bulk-accept-uds", daemon=True).start()
            except OSError as e:  # pragma: no cover - fs quirks
                logger.warning("no UDS twin for bulk port %d: %s", port, e)
        return self.address

    def close(self):
        self._shutdown = True
        for sock in self._listeners:
            try:
                sock.close()
            except OSError:
                pass

    def _accept_loop(self, listener):
        while not self._shutdown:
            try:
                sock, _ = listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(sock,),
                             name="bulk-serve", daemon=True).start()

    def _serve(self, sock):
        if sock.family in (socket.AF_INET, socket.AF_INET6):
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        raylet = self.raylet
        # Both directions bounded: a puller that stops READING mid-stream
        # (wedged process, netsplit without RST) times this send side out
        # instead of parking the serve thread — and its pinned buffer —
        # forever; an idle connection is reaped the same way (pullers
        # dial per transfer, so reaping idle conns costs nothing).
        sock.settimeout(
            max(raylet.config.bulk_transfer_io_timeout_s, 30.0) * 2)
        pins: TransferPins = raylet.transfer_pins
        token = ("bulk", id(sock), os.getpid())
        open_bufs: dict[bytes, object] = {}  # oid -> held store buffer
        try:
            while not self._shutdown:
                msg = _read_control_frame(sock)
                _msgtype, msgid, method, data = msg
                if method == "ping":
                    sock.sendall(rpc._pack([rpc.REPLY_OK, msgid, method,
                                            "pong"]))
                    continue
                if method != "bulk_pull":
                    err = rpc.RpcError(
                        f"bulk channel carries bulk_pull/ping only, "
                        f"not {method!r}")
                    sock.sendall(rpc._pack([rpc.REPLY_ERR, msgid, method,
                                            [pickle.dumps(err), ""]]))
                    continue
                self._handle_pull(sock, msgid, data, token, open_bufs)
        except (ConnectionError, OSError, _fp.FailpointError, struct.error):
            pass
        except Exception:
            logger.exception("bulk serve loop error")
        finally:
            for buf in open_bufs.values():
                try:
                    buf.close()
                except Exception:
                    pass
            freeable = pins.release_token(token)
            if freeable:
                raylet.complete_deferred_frees_threadsafe(freeable)
            try:
                sock.close()
            except OSError:
                pass

    def _handle_pull(self, sock, msgid, data, token, open_bufs):
        raylet = self.raylet
        oid = data["object_id"]
        offset = int(data.get("offset", 0))
        length = int(data.get("length", 0))  # 0 = stat/pin only
        chunk = int(data.get("chunk", 0)) or \
            raylet.config.object_transfer_chunk_size
        # puller's sampled trace context (tracing.py wire format): this
        # source's serve span joins the puller's transfer tree
        _trace_start = time.time()
        _trace_ctx = _tracing.from_wire(data.get("trace"))
        if _fp.ARMED:
            # transfer registration seam: `raise` -> typed error reply
            # (puller fails this source over); `drop_conn` kills the
            # stream; `exit` kills this (source) raylet mid-transfer
            try:
                if _fp.fire("transfer.register") == "drop_conn":
                    raise ConnectionError("transfer.register drop_conn")
            except _fp.FailpointError as e:
                self._send_err(sock, msgid, e)
                return
        try:
            rec = raylet.local_objects.get(oid)
            if rec is not None and rec.get("spilled"):
                # restore rides the raylet loop (store mutation + spill
                # bookkeeping are loop-confined)
                import asyncio

                asyncio.run_coroutine_threadsafe(
                    raylet._restore_spilled(oid),
                    raylet._loop).result(timeout=60)
                rec = raylet.local_objects.get(oid)
            # The pin outlives this request: held under `token` until the
            # connection closes or the TTL lease lapses, so the object
            # cannot be freed/evicted between two range requests of one
            # registered transfer.
            pins_ttl = raylet.config.transfer_pin_ttl_s
            raylet.transfer_pins.pin(oid, token, pins_ttl)
            buf = open_bufs.get(oid)
            if buf is None:
                # get_raw: pinned view straight into the arena, explicit
                # close at connection teardown — zero-copy on every
                # Python version (get() copies the payload out on <3.12)
                getter = getattr(raylet.store, "get_raw", raylet.store.get)
                buf = getter(ObjectID(oid))
                if buf is None:
                    # drop only THIS object's pin — the connection may be
                    # mid-transfer on other (live) objects
                    freeable = raylet.transfer_pins.unpin(oid, token)
                    if freeable:
                        raylet.complete_deferred_frees_threadsafe(freeable)
                    raise exc.ObjectLostError(oid.hex())
                open_bufs[oid] = buf
            size = buf.size
        except exc.ObjectLostError as e:
            self._send_err(sock, msgid, e)
            return
        if length < 0:
            length = max(0, size - offset)
        end = min(size, offset + length)
        sock.sendall(rpc._pack([rpc.REPLY_OK, msgid, "bulk_pull",
                                {"size": size}]))
        pos = offset
        view = buf.view
        serve_entry = {"object_id": oid.hex()[:12], "t0": time.monotonic(),
                       "size": end - offset, "sent": 0,
                       "purpose": str(data.get("purpose") or "")[:64],
                       "trace_id": (_trace_ctx.trace_id.hex()
                                    if _trace_ctx is not None else "")}
        serve_token = _track(_active_serves, serve_entry)
        try:
            while pos < end:
                n = min(chunk, end - pos)
                if _fp.ARMED:
                    if _fp.fire("transfer.chunk_send") == "drop_conn":
                        raise ConnectionError(
                            "transfer.chunk_send drop_conn")
                M_INFLIGHT_CHUNKS.add(1)
                try:
                    _sendmsg_all(sock, _CHUNK.pack(pos, n),
                                 view[pos:pos + n])
                finally:
                    M_INFLIGHT_CHUNKS.add(-1)
                pos += n
                serve_entry["sent"] = pos - offset
            sock.sendall(_CHUNK.pack(_DONE_OFFSET, 0))
        finally:
            _untrack(_active_serves, serve_token)
        if _trace_ctx is not None and length:
            _tracing.record_span(
                "transfer.serve", _trace_start, time.time(),
                _tracing.child(_trace_ctx),
                {"object_id": oid[:6].hex(), "bytes": end - offset})

    @staticmethod
    def _send_err(sock, msgid, e: BaseException):
        try:
            sock.sendall(rpc._pack([rpc.REPLY_ERR, msgid, "bulk_pull",
                                    [pickle.dumps(e),
                                     traceback.format_exc()]]))
        except (OSError, ConnectionError):
            pass


# ---------------------------------------------------------------------------
# receiver: striped streaming pull
# ---------------------------------------------------------------------------


class _Source:
    """One dialed bulk connection (blocking; lives on its worker thread)."""

    def __init__(self, address: str, connect_timeout: float,
                 io_timeout: float, purpose: str = ""):
        self.address = address
        self.sock = _dial(address, connect_timeout, io_timeout)
        self._msgid = 0
        self.purpose = purpose  # echoed in requests -> source serve rows

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass

    def _request(self, oid: bytes, offset: int, length: int,
                 chunk: int, trace: list | None = None) -> int:
        """Send one bulk_pull request; returns the object's total size.
        Raises the sender's typed error on REPLY_ERR."""
        self._msgid += 1
        req = {"object_id": oid, "offset": offset, "length": length,
               "chunk": chunk}
        if self.purpose:
            req["purpose"] = self.purpose
        if trace is not None:
            # puller's sampled trace context: the source raylet's serve
            # span joins the pull's trace tree (tracing.py wire format)
            req["trace"] = trace
        self.sock.sendall(rpc._pack([
            rpc.REQUEST, self._msgid, "bulk_pull", req]))
        msg = _read_control_frame(self.sock)
        if msg[0] == rpc.REPLY_ERR:
            e = pickle.loads(msg[3][0])
            raise e
        return int(msg[3]["size"])

    def stat(self, oid: bytes) -> int:
        """Pin + size probe: a zero-length pull (stream is just the
        terminator record)."""
        size = self._request(oid, 0, 0, 1)
        self._drain_stream(None, 0, 0)
        return size

    def pull_range(self, oid: bytes, offset: int, length: int, chunk: int,
                   view: memoryview, progress: list,
                   trace: list | None = None) -> None:
        """Stream one contiguous range into `view` at its true offsets.
        `progress[0]` tracks contiguous bytes landed so a failure mid-
        range lets the caller requeue only the remainder."""
        self._request(oid, offset, length, chunk, trace)
        self._drain_stream(view, offset, length, progress)

    def _drain_stream(self, view, offset, length, progress=None):
        expect = offset
        end = offset + length
        while True:
            pos, n = _CHUNK.unpack(_recv_exact(self.sock, _CHUNK.size))
            if pos == _DONE_OFFSET and n == 0:
                break
            if view is None or pos != expect or pos + n > end:
                raise ConnectionError(
                    f"bulk stream protocol error: chunk [{pos},{pos + n}) "
                    f"outside expected [{expect},{end})")
            if _fp.ARMED:
                if _fp.fire("transfer.chunk_recv") == "drop_conn":
                    raise ConnectionError("transfer.chunk_recv drop_conn")
            M_INFLIGHT_CHUNKS.add(1)
            try:
                _recv_exact_into(self.sock, view[pos:pos + n])
            finally:
                M_INFLIGHT_CHUNKS.add(-1)
            M_PULL_BYTES.inc(n)
            expect = pos + n
            if progress is not None:
                progress[0] = expect - offset
        if view is not None and expect != end:
            raise ConnectionError(
                f"bulk stream ended early at {expect} of [{offset},{end})")


def streaming_pull(oid: bytes, object_id: ObjectID, store,
                   addresses: list[str], *, chunk: int, stripe: int,
                   max_sources: int = 4, connect_timeout: float = 5.0,
                   io_timeout: float = 30.0,
                   trace: list | None = None,
                   purpose: str = "") -> int:
    """Pull one object over the bulk plane, striping across up to
    `max_sources` of `addresses`. Creates, fills and seals the store
    entry; aborts it on failure. Blocking — run on an executor thread.
    Returns the object size. Raises PullError when every source fails."""
    errors: list = []
    first: _Source | None = None
    size = None
    usable: list[str] = []
    for addr in addresses:
        if first is None:
            # stat probe: sizes the buffer AND registers the transfer
            # pin on this source before any byte flows
            try:
                src = _Source(addr, connect_timeout, io_timeout, purpose)
            except OSError as e:
                errors.append((addr, e))
                continue
            try:
                size = src.stat(oid)
            except Exception as e:
                errors.append((addr, e))
                src.close()
                continue
            first = src
        # further sources are dialed lazily on their worker threads —
        # an unreachable one just records its error and drops out
        usable.append(addr)
        if len(usable) >= max_sources:
            break
    if first is None or size is None:
        raise PullError(oid, errors)
    # directory entries beyond max_sources are failover SPARES: tried
    # sequentially if every striped source fails (dead stat probes are
    # not retried)
    dead = {a for a, _ in errors}
    spares = [a for a in addresses if a not in usable and a not in dead]

    try:
        try:
            buf = store.create(object_id, size)
        except FileExistsError:
            # stale .build from an earlier abandoned pull (files
            # backend's O_EXCL create has no delete-and-retry like the
            # native arena)
            store.abort(object_id)
            buf = store.create(object_id, size)
    except BaseException:
        # e.g. MemoryError on a full arena: don't leak the stat-probe
        # connection and its sender-side transfer pin across retries
        first.close()
        raise
    wedged = False  # a live writer thread forbids store.abort (below)
    pull_token = None
    try:
        view = buf.view
        unit = max(chunk, stripe)
        queue: collections.deque = collections.deque()
        pos = 0
        while pos < size:
            queue.append((pos, min(unit, size - pos)))
            pos += unit
        if not queue:
            queue.append((0, 0))  # zero-byte object: one empty range
        lock = threading.Lock()
        remaining = [size]
        bytes_by_source: dict[str, int] = {}
        pull_token = _track(_active_pulls, {
            "object_id": oid.hex()[:12], "t0": time.monotonic(),
            "size": size, "remaining": remaining,
            "sources": len(usable), "purpose": purpose,
            "trace_id": (bytes(trace[0]).hex() if trace else "")})

        nsources = max(1, len(usable))
        conns: list[_Source] = []  # live worker connections (abort hook)

        def work(addr: str, conn: _Source | None):
            moved = 0
            try:
                if conn is None:
                    conn = _Source(addr, connect_timeout, io_timeout,
                                   purpose)
                with lock:
                    conns.append(conn)
                while True:
                    with lock:
                        if not queue:
                            return
                        off, ln = queue.popleft()
                        # guided self-scheduling: coalesce ADJACENT
                        # queued units into one request, sized to the
                        # remaining work over 2x the sources — few
                        # request round trips up front, fine-grained
                        # stealing for the tail
                        target = max(unit, remaining[0] // (2 * nsources))
                        while (queue and queue[0][0] == off + ln
                               and ln < target):
                            _o2, l2 = queue.popleft()
                            ln += l2
                    progress = [0]
                    try:
                        conn.pull_range(oid, off, ln, chunk, view, progress,
                                        trace)
                        moved += ln
                        with lock:
                            remaining[0] -= ln
                    except Exception:
                        got = progress[0]
                        moved += got
                        with lock:
                            remaining[0] -= got
                            if ln - got:
                                queue.append((off + got, ln - got))
                        raise
            except Exception as e:
                with lock:
                    errors.append((addr, e))
            finally:
                with lock:
                    bytes_by_source[addr] = moved
                if conn is not None:
                    conn.close()

        if len(usable) == 1 or len(queue) == 1:
            # sequential: sole source, or a single-range object — the
            # other usable sources serve as failover, not parallelism
            # (the queue requeues a failed range's remainder, so the
            # next source resumes where the dead one stopped)
            for i, addr in enumerate(usable):
                work(addr, first if i == 0 else None)
                if remaining[0] <= 0:
                    break
        else:
            threads = []
            for i, addr in enumerate(usable):
                t = threading.Thread(
                    target=work, args=(addr, first if i == 0 else None),
                    name=f"bulk-pull-{i}", daemon=True)
                threads.append(t)
                t.start()
            for t in threads:
                # bounded by per-socket io timeouts; the join timeout is
                # a backstop against a wedged thread leaking the pull
                t.join(timeout=io_timeout * 4)
            if any(t.is_alive() for t in threads):
                # a source trickling >=1 byte per io_timeout defeats the
                # per-recv socket timeout: close the sockets out from
                # under the wedged recvs to break them loose
                with lock:
                    for c in conns:
                        c.close()
                for t in threads:
                    t.join(timeout=5.0)
                wedged = any(t.is_alive() for t in threads)
        if wedged:
            # NEVER abort with a live writer thread: store.abort would
            # recycle the arena range under its recv_into and corrupt
            # whatever lands there next. Leak the unsealed create — the
            # daemon thread dies with the process, and the next pull
            # attempt replaces the stale entry (native create deletes-
            # and-retries; the files path aborts on FileExistsError
            # above).
            logger.error("streaming pull of %s: worker thread wedged "
                         "past every timeout; leaking the unsealed "
                         "create instead of aborting under it",
                         oid[:6].hex())
            buf.close()
            raise PullError(oid, errors + [
                ("local", RuntimeError("pull worker thread wedged"))])
        if remaining[0] > 0:
            for addr in spares:  # every striped source failed: failover
                work(addr, None)
                if remaining[0] <= 0:
                    break
        if remaining[0] > 0:
            raise PullError(oid, errors)
        if sum(1 for b in bytes_by_source.values() if b > 0) >= 2:
            M_PULLS_STRIPED.inc()
        buf.close()
        store.seal(object_id)
    except BaseException:
        buf.close()
        if not wedged:
            store.abort(object_id)
        raise
    finally:
        if pull_token is not None:
            _untrack(_active_pulls, pull_token)
    return size
