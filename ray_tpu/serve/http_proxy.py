"""HTTP proxy actor (reference: python/ray/serve/http_proxy.py:165
HTTPProxyActor — uvicorn/starlette there, aiohttp here). Routes
`route -> endpoint` pushed from the controller via long-poll
(reference: serve/long_poll.py:26): the request path touches no
controller RPC — it reads a locally-cached route table that a single
background thread keeps fresh."""

from __future__ import annotations

import json
import threading
import time


class HTTPProxy:
    """Actor: runs an aiohttp server on a thread; one Router per endpoint."""

    def __init__(self, controller, host: str = "127.0.0.1", port: int = 0):
        self._controller = controller
        self._routers: dict[str, object] = {}
        self._routes: dict[str, dict] = {}
        self._state_lock = threading.Lock()
        self._version = -1
        self._host = host
        self._port = port
        self._actual_port = None
        self._ready = threading.Event()
        self._synced = threading.Event()
        self._closed = False
        self._poller = threading.Thread(target=self._poll_loop, daemon=True)
        self._poller.start()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=10)
        self._synced.wait(timeout=10)

    def _poll_loop(self):
        """Long-poll the controller: one parked RPC instead of a
        get_version per HTTP request."""
        import ray_tpu

        while not self._closed:
            try:
                snap = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._version, 10.0),
                    timeout=40)
            except Exception:
                time.sleep(0.5)
                continue
            if snap is None:
                self._synced.set()  # controller alive, nothing changed
                continue
            with self._state_lock:
                self._routes = dict(snap["routes"])
                self._version = snap["version"]
            self._synced.set()

    def _router_for(self, endpoint: str):
        # Executor threads race here; the lock keeps it to one Router
        # (each owns flusher/completion threads) per endpoint.
        with self._state_lock:
            if endpoint not in self._routers:
                from ray_tpu.serve.router import Router

                self._routers[endpoint] = Router(self._controller, endpoint)
            return self._routers[endpoint]

    def _serve(self):
        import asyncio

        from aiohttp import web

        async def handler(request: "web.Request"):
            body = await request.read()
            loop = asyncio.get_running_loop()

            # Everything blocking (controller RPCs, routing, get) runs in
            # the executor — the event loop only parses/serializes HTTP.
            def _call():
                import ray_tpu

                route = self._routes.get(request.path)
                if route is None:
                    return 404, {"error": f"no route {request.path}"}
                if request.method.upper() not in route["methods"]:
                    return 405, {
                        "error": f"method {request.method} not allowed"}
                try:
                    data = json.loads(body) if body else None
                except json.JSONDecodeError:
                    return 400, {"error": "invalid JSON"}
                router = self._router_for(route["endpoint"])
                try:
                    ref = router.assign(data)
                    return 200, {"result": ray_tpu.get(ref, timeout=60)}
                except Exception as e:
                    return 500, {"error": str(e)}

            status, payload = await loop.run_in_executor(None, _call)
            return web.json_response(payload, status=status)

        async def run():
            app = web.Application()
            app.router.add_route("*", "/{tail:.*}", handler)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, self._host, self._port)
            await site.start()
            self._actual_port = site._server.sockets[0].getsockname()[1]
            self._ready.set()
            while True:
                await asyncio.sleep(3600)

        asyncio.run(run())

    def port(self) -> int:
        return self._actual_port

    def ping(self):
        return "pong"
