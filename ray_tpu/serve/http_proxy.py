"""HTTP proxy actor (reference: python/ray/serve/http_proxy.py:165
HTTPProxyActor — uvicorn/starlette there, aiohttp here). Routes
`route -> endpoint` pushed from the controller via long-poll
(reference: serve/long_poll.py:26): the request path touches no
controller RPC — it reads a locally-cached route table that a single
background thread keeps fresh."""

from __future__ import annotations

import json
import threading
import time

from ray_tpu._private import stats as _stats
from ray_tpu._private import tracing
from ray_tpu.serve import payload as _payload

M_HTTP_E2E_S = _stats.Histogram(
    "serve.http_e2e_s", _stats.LATENCY_BOUNDARIES_S,
    "HTTP request arrival -> response sent (proxy side)")


def _error_response(e: BaseException):
    """Map typed internal errors to honest status codes (the production
    contract: overload and infrastructure loss are RETRYABLE 503s with a
    hint, user exceptions are 500s — a blanket 500 made clients retry
    bugs and give up on sheds). Returns (status, headers, body_dict)."""
    from ray_tpu import exceptions as exc

    if isinstance(e, exc.ServeOverloadedError):
        return 503, {"Retry-After": f"{max(e.retry_after_s, 0.1):.010g}"}, {
            "error": str(e), "type": "ServeOverloadedError",
            "retry_after_s": e.retry_after_s}
    if isinstance(e, exc.ReplicaGroupDied):
        # gang restart in progress: retryable once the controller
        # respawns the group
        return 503, {"Retry-After": "1"}, {
            "error": str(e), "type": "ReplicaGroupDied"}
    if isinstance(e, exc.ObjectLostError):
        # a zero-copy payload's producer died with the only copy
        return 503, {"Retry-After": "1"}, {
            "error": str(e), "type": "ObjectLostError"}
    if isinstance(e, exc.SequenceAborted):
        # the stream was aborted (client gone, KV exhausted mid-decode,
        # engine shutdown): nginx-style 499 — not retryable as-is, not
        # a server bug
        return 499, {}, {"error": str(e), "type": "SequenceAborted"}
    if isinstance(e, exc.TaskError):
        return 500, {}, {"error": str(e), "type": "TaskError",
                         "cause": e.cause_cls_name}
    return 500, {}, {"error": str(e), "type": type(e).__name__}


class HTTPProxy:
    """Actor: runs an aiohttp server on a thread; one Router per endpoint."""

    def __init__(self, controller, host: str = "127.0.0.1", port: int = 0,
                 reuse_port: bool = False, legacy_path: bool = False):
        self._controller = controller
        # legacy_path keeps the pre-coalescing request path (assign_async
        # + wrap_future per ref) alive as the A/B control for the
        # microbenchmark, and as a fallback switch for call_async
        self._legacy_path = legacy_path
        self._routers: dict[str, object] = {}
        self._routes: dict[str, dict] = {}
        self._thresholds: dict[str, int] = {}
        self._streaming: dict[str, bool] = {}
        self._state_lock = threading.Lock()
        self._version = -1
        self._host = host
        self._port = port
        # SO_REUSEPORT lets N proxy actor PROCESSES share one listen
        # port; the kernel spreads accepted connections across them, so
        # qps scales past one event loop's ceiling (the reference scales
        # the same way with one uvicorn proxy per node)
        self._reuse_port = reuse_port
        self._actual_port = None
        self._error: BaseException | None = None
        self._ready = threading.Event()
        self._synced = threading.Event()
        self._closed = False
        self._poller = threading.Thread(target=self._poll_loop, daemon=True)
        self._poller.start()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=10)
        if self._error is not None:
            # Surface bind failures (port in use, bad host) as an actor
            # init error instead of a silent None port 10s later — the
            # caller (_start_proxies) kills partially-started proxies on
            # this (ADVICE.md: orphaned HTTPProxy actors on bind failure).
            raise RuntimeError(
                f"HTTP proxy failed to serve on {host}:{port}: "
                f"{self._error}") from self._error
        self._synced.wait(timeout=10)

    def _poll_loop(self):
        """Long-poll the controller: one parked RPC instead of a
        get_version per HTTP request."""
        import ray_tpu

        while not self._closed:
            try:
                snap = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._version, 10.0),
                    timeout=40)
            except Exception:
                time.sleep(0.5)
                continue
            if snap is None:
                self._synced.set()  # controller alive, nothing changed
                continue
            # per-endpoint zero-copy cutover, read from the primary
            # backend's config (same snapshot the routes came from)
            thresholds = {}
            streaming = {}
            for name, ep_state in (snap.get("endpoints") or {}).items():
                cfg = (ep_state.get("backends", {})
                       .get(ep_state.get("backend"), {})
                       .get("config") or {})
                thresholds[name] = int(
                    cfg.get("large_payload_threshold") or 0)
                streaming[name] = bool(cfg.get("streaming"))
            with self._state_lock:
                self._routes = dict(snap["routes"])
                self._thresholds = thresholds
                self._streaming = streaming
                self._version = snap["version"]
            self._synced.set()

    def _router_for(self, endpoint: str):
        # Executor threads race here; the lock keeps it to one Router
        # (each owns flusher/completion threads) per endpoint.
        with self._state_lock:
            if endpoint not in self._routers:
                from ray_tpu.serve.router import Router

                self._routers[endpoint] = Router(self._controller, endpoint)
            return self._routers[endpoint]

    def _serve(self):
        import asyncio

        from aiohttp import web

        async def stream_handler(request, endpoint, router, data):
            """Streaming-backend request: SSE when the client asked for
            it (Accept: text/event-stream or {"stream": true}), else
            aggregate the decoded tokens into one JSON reply — both ride
            the engine's continuous batch; only the framing differs.
            TTFT decoupling is the SSE path: the first `data:` frame
            flushes one decode step after admission."""
            from ray_tpu.serve.streaming import (SSE_CONTENT_TYPE,
                                                 sse_event)

            wants_sse = (SSE_CONTENT_TYPE
                         in request.headers.get("Accept", "")
                         or (isinstance(data, dict)
                             and data.get("stream")))
            gen = router.stream_async(data, timeout=60.0)
            if not wants_sse:
                toks: list[int] = []
                try:
                    async for chunk in gen:
                        toks.extend(chunk["tokens"])
                except Exception as e:
                    status, headers, doc = _error_response(e)
                    return web.json_response(doc, status=status,
                                             headers=headers)
                return web.json_response({"result": toks})
            resp = web.StreamResponse(
                status=200,
                headers={"Cache-Control": "no-cache",
                         "X-Accel-Buffering": "no"})
            resp.content_type = SSE_CONTENT_TYPE
            await resp.prepare(request)
            total = 0
            try:
                async for chunk in gen:
                    if "meta" in chunk:
                        # stream preamble: seq id + session-cache
                        # hit/miss (delta-prompt clients resend full
                        # history on a miss)
                        await resp.write(sse_event(chunk["meta"],
                                                   event="meta"))
                        continue
                    total = chunk["cursor"]
                    # one frame per engine chunk, flushed immediately:
                    # a disconnected client surfaces here as a write
                    # error/cancel -> gen closes -> sequence aborts and
                    # its KV pages free (the router's abandon path)
                    await resp.write(sse_event(
                        {"tokens": chunk["tokens"], "cursor": total}))
                await resp.write(sse_event(
                    {"done": True, "tokens_total": total}, event="done"))
            except (asyncio.CancelledError, ConnectionResetError,
                    ConnectionError):
                raise
            except Exception as e:
                status, _, doc = _error_response(e)
                try:
                    await resp.write(sse_event(
                        {**doc, "status": status}, event="error"))
                except (ConnectionError, RuntimeError):
                    pass
            finally:
                try:
                    await gen.aclose()  # no-op if exhausted; otherwise
                except BaseException:   # triggers the abort path
                    pass
                try:
                    await resp.write_eof()
                except (ConnectionError, RuntimeError):
                    pass
            return resp

        async def handler(request: "web.Request"):
            # Fully async request path: route lookup is a plain dict get,
            # the router resolves the RESULT directly (call_async) so a
            # request costs zero per-query cross-thread wakeups — the
            # batch's results arrive on this loop in one coalesced tick
            # (reference: serve's uvicorn proxy is equally async
            # end-to-end).
            route = self._routes.get(request.path)
            if route is None:
                return web.json_response(
                    {"error": f"no route {request.path}"}, status=404)
            if request.method.upper() not in route["methods"]:
                return web.json_response(
                    {"error": f"method {request.method} not allowed"},
                    status=405)
            body = (await request.read()) if request.body_exists else None
            endpoint = route["endpoint"]
            ctype = request.headers.get("Content-Type", "")
            if body is not None and ctype.startswith(
                    "application/octet-stream"):
                # binary body (tensor payloads): pass raw bytes through;
                # at/over the endpoint's threshold they ride plasma +
                # the bulk channel as a LargePayload ref instead of
                # being pickled through the router. The plasma put is a
                # blocking copy — off the event loop (like the response
                # unwrap below), or one 512MB body stalls every
                # concurrent small request on this proxy.
                threshold = self._thresholds.get(endpoint) or 0
                if threshold and len(body) >= threshold:
                    data = await asyncio.get_running_loop() \
                        .run_in_executor(None, _payload.wrap, body,
                                         threshold)
                else:
                    data = body
            else:
                try:
                    data = json.loads(body) if body else None
                except json.JSONDecodeError:
                    return web.json_response({"error": "invalid JSON"},
                                             status=400)
            # lock-free hot path: dict reads are GIL-atomic; the locked
            # creator runs only on the first request per endpoint
            router = self._routers.get(endpoint)
            if router is None:
                router = self._router_for(endpoint)
            # Serve trace entry point: head-sample a root context and
            # make it ambient for the dispatch — the router carries it
            # to the replica so one HTTP request becomes one tree
            # (proxy -> router queue -> lease -> replica exec).
            ctx = tracing.maybe_trace()
            token = tracing.push(ctx) if ctx is not None else None
            t0 = time.time()
            try:
                if self._streaming.get(endpoint):
                    return await stream_handler(request, endpoint,
                                                router, data)
                if self._legacy_path:
                    ref = await router.assign_async(data)
                    result = await asyncio.wait_for(
                        asyncio.wrap_future(ref.future()), 60)
                else:
                    result = await router.call_async(data, timeout=60.0)
                if isinstance(result, _payload.LargePayload):
                    # zero-copy response: resolve the plasma ref off the
                    # event loop (first touch may pull over the bulk
                    # channel) and answer binary
                    result = await asyncio.get_running_loop() \
                        .run_in_executor(None, _payload.unwrap, result)
                if isinstance(result, (bytes, bytearray, memoryview)):
                    return web.Response(
                        body=bytes(result),
                        content_type="application/octet-stream")
                return web.json_response({"result": result})
            except Exception as e:
                status, headers, payload_doc = _error_response(e)
                return web.json_response(payload_doc, status=status,
                                         headers=headers)
            finally:
                end = time.time()
                M_HTTP_E2E_S.observe(end - t0,
                                     exemplar=tracing.exemplar_of(ctx))
                if token is not None:
                    tracing.pop(token)
                    tracing.record_span("http.request", t0, end, ctx,
                                        {"name": request.path})

        async def run():
            # client_max_size: large tensor bodies are a first-class
            # workload (they ride plasma past the threshold); aiohttp's
            # 1MB default would 413 them at the door
            app = web.Application(client_max_size=1 << 30)
            app.router.add_route("*", "/{tail:.*}", handler)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, self._host, self._port,
                               reuse_port=self._reuse_port or None)
            await site.start()
            self._actual_port = site._server.sockets[0].getsockname()[1]
            self._ready.set()
            while True:
                await asyncio.sleep(3600)

        try:
            asyncio.run(run())
        except BaseException as e:
            already_up = self._ready.is_set()
            self._error = e
            self._ready.set()
            if already_up:
                # post-startup crash (EMFILE, serve-loop bug): __init__
                # returned long ago and nothing reads _error — log loudly
                # instead of leaving a dark proxy with a live-looking port
                import logging

                logging.getLogger("ray_tpu").exception(
                    "HTTP proxy server crashed after startup")
            # pre-ready failures (bind errors) are raised by __init__

    def port(self) -> int:
        return self._actual_port

    def ping(self):
        return "pong"

    def __ray_debug_state__(self) -> dict:
        """Live-state hook (debug_state.py): route table version + port.
        Per-endpoint router queues surface through the process-level
        router registry (serve/router.py debug_routers), not here."""
        with self._state_lock:
            routes = {path: r.get("endpoint", "")
                      for path, r in self._routes.items()}
        return {"kind": "serve-proxy", "version": self._version,
                "port": self._actual_port, "routes": routes,
                "server_error": (repr(self._error)
                                 if self._error is not None else "")}
