"""Sharded model serving: replica GROUPS (ROADMAP item 1, the serving
analogue of the Ray paper's distributed actors).

A deployment with `num_shards=N` makes each "replica" a gang of N
member actors. Every member holds one Megatron-partitioned shard of the
model (SNIPPETS [3]: ColumnParallel W1 -> activation -> RowParallel W2,
slices cut with `parallel.sharding.column_shard/row_shard`); the gang is
joined in one collective group at bootstrap. The router keeps talking to
a single handle — the group LEADER (rank 0): `handle_batch` fans the
batch to the followers (large bodies travel as LargePayload markers, so
an N-way fan-out is N bulk-channel pulls of one plasma object, not N
pickled copies), every rank computes its partial forward, and one
allreduce(SUM) over the PR 2/8 transport tiers (auto-routed
shm/ring/device by placement and payload type) recovers the full
output, which only the leader returns.

Failure domains: any member death (or a member's forward error) starves
the group allreduce -> every rank times out within the group timeout ->
the leader raises typed `ReplicaGroupDied` to all in-flight callers and
the controller gang-restarts the WHOLE group (fresh pg-backed gang,
fresh collective group name — a half-dead gang is never reused).

Gang scheduling: members are placed via a placement group (the GCS's
atomic 2PC bundle reservation = the gang lease acquisition), ICI_RING
strategy so consecutive ranks land on ICI-neighboring nodes and the
collective transport tier is DERIVED from the placement record
(topology.transport_plan — shm when the ring packed onto one host)
instead of probed; on coordinate-less clusters the GCS degrades
ICI_RING to PACK (counted) and the probe round is preserved.
"""

from __future__ import annotations

import inspect
import time

import numpy as np

import cloudpickle

from ray_tpu._private import failpoints as _fp
from ray_tpu._private import stats as _stats
from ray_tpu._private import tracing as _tracing
from ray_tpu.collective.collective import CollectiveActorMixin
from ray_tpu.serve import payload as _payload
from ray_tpu.serve.engine import StreamingEngineHost

M_GROUP_EXEC_S = _stats.Histogram(
    "serve.group_exec_s", _stats.LATENCY_BOUNDARIES_S,
    "sharded forward per batch, leader side: fan-out + partial + "
    "allreduce (pairs with serve.replica_exec_s for scalar replicas)")


# ---------------------------------------------------------------------------
# reference partitioned model (the SNIPPETS [3] Megatron MLP, numpy/jax
# agnostic: a host gang computes in numpy and the allreduce rides
# shm/ring; on-device jax shards keep their arrays and the DEVICE tier
# carries the reduce over ICI)
# ---------------------------------------------------------------------------


class ShardedMLP:
    """y = act(x @ W1) @ W2 with W1 column-parallel and W2 row-parallel.

    Deployed unsharded it is a plain callable (the bit-exactness
    reference); under a replica group each member calls `shard(rank, n)`
    once at init and `__call__` then returns the PARTIAL output the
    group sums. With integer-valued f32 weights/inputs the sharded sum
    is bit-exact with the unsharded matmul (all partials exactly
    representable), which is how the test pins the forward pass."""

    def __init__(self, w1, w2, activation: str = "relu"):
        self.w1 = np.asarray(w1, dtype=np.float32)
        self.w2 = np.asarray(w2, dtype=np.float32)
        if activation not in ("relu", "identity"):
            raise ValueError(f"unsupported activation {activation!r}")
        self.activation = activation
        self._shard = None  # (rank, num_shards) once sharded

    def shard(self, rank: int, num_shards: int) -> "ShardedMLP":
        from ray_tpu.parallel.sharding import column_shard, row_shard

        self.w1 = column_shard(self.w1, rank, num_shards)
        self.w2 = row_shard(self.w2, rank, num_shards)
        self._shard = (rank, num_shards)
        return self

    def __call__(self, requests: list):
        x = np.asarray(
            [np.frombuffer(r, dtype=np.float32)
             if isinstance(r, (bytes, bytearray)) else r
             for r in requests], dtype=np.float32)
        h = x @ self.w1
        if self.activation == "relu":
            h = np.maximum(h, 0.0)
        return h @ self.w2


# ---------------------------------------------------------------------------
# group member actor
# ---------------------------------------------------------------------------


class ReplicaGroupMember(CollectiveActorMixin, StreamingEngineHost):
    """One shard of a replica group. Rank 0 is the LEADER: it is the
    handle the router dispatches to; `handle_batch` there drives the
    collective forward. Ranks 1..N-1 only ever see `shard_exec` pushes
    from their leader (actor-call ordering from one caller keeps every
    rank's op sequence aligned, so the allreduces pair up without a
    sequence protocol).

    Streaming backends (`streaming=True`) host the continuous-batching
    decode engine instead: the LEADER runs the scheduler + decode loop
    (started in set_peers, once the gang exists), followers run mirror
    engines driven one `decode_step_exec` per step — the Megatron gang
    forward becomes one *step*, not the whole request."""

    def __init__(self, pickled_callable: bytes, init_args: tuple,
                 user_config: dict | None, backend: str, group_name: str,
                 world_size: int, rank: int,
                 large_payload_threshold: int = 0,
                 group_timeout_s: float = 10.0,
                 config: dict | None = None):
        target = cloudpickle.loads(pickled_callable)
        inst = target(*init_args) if inspect.isclass(target) else target
        shard = getattr(inst, "shard", None)
        if not callable(shard):
            raise TypeError(
                f"num_shards={world_size} backend {backend!r} requires a "
                f"callable implementing shard(rank, num_shards) that "
                f"returns the per-shard partial-forward callable; "
                f"{type(inst).__name__} does not")
        self._callable = shard(rank, world_size) or inst
        if user_config is not None:
            reconfigure = getattr(self._callable, "reconfigure", None)
            if reconfigure:
                reconfigure(user_config)
        self._backend = backend
        self._group_name = group_name
        self._world = world_size
        self._rank = rank
        self._threshold = large_payload_threshold
        self._group_timeout_s = group_timeout_s
        self._config = dict(config or {})
        self._streaming = bool(self._config.get("streaming"))
        self._peers: list = []
        self._batches_handled = 0
        self._last_batch_at = 0.0
        if self._streaming and rank > 0:
            # follower mirror: same KV shard + model, no scheduler —
            # the leader drives it one decode_step_exec per step
            self._start_engine(self._callable, self._config, backend,
                               allreduce=self._group_allreduce,
                               driver=False)

    def _group_allreduce(self, arr):
        from ray_tpu.collective import collective as col

        return col.allreduce(arr, self._group_name)

    # -- controller wiring ----------------------------------------------

    def set_peers(self, peers: list):
        """Leader only: handles of ranks 1..N-1, set once the collective
        group is bootstrapped. For streaming backends this is also where
        the decode engine starts — the gang is whole from here on."""
        self._peers = list(peers)
        if self._streaming and self._engine is None:
            self._start_engine(self._callable, self._config,
                               self._backend,
                               allreduce=self._group_allreduce,
                               peers=self._peers, driver=True)
        return True

    def decode_step_exec(self, plan: dict):
        """Follower entry, one call per decode step: replay the
        leader's step plan on this rank's KV shard (joins the step's
        allreduce; the plan keeps every rank's state identical)."""
        return self._require_engine().apply_plan(plan)

    def ping(self):
        return "pong"

    def reconfigure(self, user_config: dict):
        fn = getattr(self._callable, "reconfigure", None)
        if fn:
            fn(user_config)
        return True

    def arm_failpoint(self, name: str, action: str, **kw):
        """Test hook: arm a failpoint in THIS member's process (the
        chaos sweep picks one victim per seed; env/cluster arming would
        fire in every member at the same nth)."""
        _fp.arm(name, action, **kw)
        return True

    # -- forward ---------------------------------------------------------

    def _forward_partial(self, requests: list):
        """Unwrap zero-copy markers, fire the chaos seam, compute this
        shard's partial output."""
        local = [_payload.unwrap(r) for r in requests]
        if _fp.ARMED:
            # the member-kill seam: `exit` here is a shard dying
            # mid-forward, leaving every survivor starved in allreduce
            _fp.fire_strict("serve.group_forward")
        return local, np.asarray(self._callable(local))

    def shard_exec(self, requests: list):
        """Follower entry: partial forward + join the group allreduce
        (the reduced result is discarded here — only the leader
        answers)."""
        from ray_tpu.collective import collective as col

        _, partial = self._forward_partial(requests)
        col.allreduce(partial, self._group_name)
        self._batches_handled += 1
        self._last_batch_at = time.time()
        return True

    def handle_batch(self, requests: list):
        """Leader entry (same contract as Replica.handle_batch: one RPC
        per batch, per-request results split by num_returns)."""
        from ray_tpu.collective import collective as col
        from ray_tpu import exceptions as exc

        if self._streaming:
            raise RuntimeError(
                "streaming backend: use the stream API "
                "(handle.stream(...) / SSE through the proxy), not "
                "request/response dispatch")
        start = time.time()
        # own partial FIRST: a leader-side user error (bad input) raises
        # plainly before any follower was involved — no gang restart
        local, partial = self._forward_partial(requests)
        refs = [p.shard_exec.remote(requests) for p in self._peers]
        try:
            reduced = col.allreduce(partial, self._group_name)
        except BaseException as e:
            # a member died or errored before its allreduce: starved
            # group -> TimeoutError within the group timeout. Name the
            # follower failure when one already surfaced.
            raise exc.ReplicaGroupDied(
                self._backend, self._group_name,
                self._peer_failure(refs) or f"{type(e).__name__}: {e}"
            ) from e
        finally:
            M_GROUP_EXEC_S.observe(time.time() - start,
                                   exemplar=_tracing.current_id())
            self._batches_handled += 1
            self._last_batch_at = time.time()
        failure = self._peer_failure(refs, wait_s=self._group_timeout_s)
        if failure:
            # follower completed its allreduce but failed afterwards (or
            # its reply was lost): the group's op streams may be skewed —
            # surface typed and let the controller restart the gang
            raise exc.ReplicaGroupDied(self._backend, self._group_name,
                                       failure)
        out = self._finalize(reduced, local)
        if self._threshold:
            # wrap responses only for zero-copy-protocol callers (the
            # HTTP proxy sends LargePayload markers; plain handle
            # callers get values)
            out = [_payload.wrap(r, self._threshold)
                   if isinstance(req, _payload.LargePayload) else r
                   for r, req in zip(out, requests)]
        return tuple(out) if len(out) > 1 else out[0]

    def _finalize(self, reduced, requests: list) -> list:
        fin = getattr(self._callable, "finalize", None)
        if callable(fin):
            out = list(fin(reduced, requests))
        else:
            out = [reduced[i] for i in range(len(requests))]
        if len(out) != len(requests):
            raise ValueError(
                f"sharded callable produced {len(out)} results for "
                f"{len(requests)} requests")
        return out

    def _peer_failure(self, refs, wait_s: float = 0.0) -> str:
        """First follower failure, if any surfaced (non-blocking probe by
        default; bounded wait when the leader's op already completed and
        follower replies are owed)."""
        import ray_tpu

        if not refs:
            return ""
        try:
            done, pending = ray_tpu.wait(refs, num_returns=len(refs),
                                         timeout=wait_s)
        except Exception as e:
            return f"{type(e).__name__}: {e}"
        if wait_s and pending:
            return (f"{len(pending)} follower(s) never completed the "
                    f"batch within {wait_s}s")
        for ref in done:
            try:
                ray_tpu.get(ref, timeout=1.0)
            except BaseException as e:
                return f"follower failed: {type(e).__name__}: {e}"
        return ""

    def __ray_debug_state__(self) -> dict:
        out = {
            "kind": "serve-replica-group-member",
            "backend": self._backend,
            "group": self._group_name,
            "rank": self._rank,
            "world_size": self._world,
            "batches_handled": self._batches_handled,
            "last_batch_age_s": (round(time.time() - self._last_batch_at, 3)
                                 if self._last_batch_at else None),
        }
        if self._engine is not None:
            out["engine"] = self._engine.debug_state()
        return out


# ---------------------------------------------------------------------------
# gang bootstrap / teardown (controller-side helpers; run inside the
# ServeController actor's worker process)
# ---------------------------------------------------------------------------


def spawn_replica_group(backend: str, pickled_callable: bytes,
                        init_args: tuple, config: dict,
                        pg=None) -> dict:
    """Gang-schedule one replica group: reserve an N-bundle placement
    group (atomic 2PC — the gang lease acquisition: all members get
    resources or none do), spawn one member per bundle, bootstrap the
    collective group across them, wire the leader's peer handles.
    Returns the gang record the controller tracks. On ANY bootstrap
    failure every spawned member and the reservation are torn down —
    a half-bootstrapped gang never leaks."""
    import uuid

    import ray_tpu
    from ray_tpu.collective.collective import create_collective_group
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    n = int(config["num_shards"])
    gang_id = uuid.uuid4().hex[:8]
    group_name = f"serve:{backend}:{gang_id}"
    timeout_s = float(config.get("shard_group_timeout_s") or 10.0)
    own_pg = pg is None
    if own_pg:
        # ICI_RING: consecutive ranks land on ICI-neighboring nodes (the
        # geometry the gang's allreduce ring wants) and the collective
        # transport below derives from the record. On clusters without
        # topology coords the GCS degrades it to PACK (counted) — the
        # pre-topology behavior, bit-for-bit.
        pg = placement_group(
            [{"CPU": float(config.get("num_cpus_per_shard") or 0.001)}
             for _ in range(n)],
            strategy="ICI_RING",
            cost_model=config.get("placement_cost_model") or "",
            name=f"serve-gang-{backend}-{gang_id}")
    members: list = []
    try:
        if not pg.ready(timeout=30.0):
            raise TimeoutError(
                f"gang reservation for backend {backend!r} "
                f"({n} bundles) not placeable within 30s")
        member_cls = ray_tpu.remote(ReplicaGroupMember)
        for rank in range(n):
            members.append(member_cls.options(
                placement_group=pg,
                placement_group_bundle_index=rank,
            ).remote(
                pickled_callable, init_args, config.get("user_config"),
                backend, group_name, n, rank,
                int(config.get("large_payload_threshold") or 0),
                timeout_s, dict(config)))
        create_collective_group(
            members, n, list(range(n)), backend="host",
            group_name=group_name, timeout=timeout_s,
            transport=config.get("shard_transport") or "auto",
            # ICI_RING-placed gangs derive their tier from the record
            # (probe-free); PACK-fallback records carry no plan and the
            # probe round is preserved
            placement_group=pg)
        ray_tpu.get(members[0].set_peers.remote(members[1:]), timeout=60)
    except BaseException:
        for m in members:
            try:
                ray_tpu.kill(m)
            except Exception:
                pass
        if own_pg:
            try:
                remove_placement_group(pg)
            except Exception:
                pass
        raise
    return {"leader": members[0], "members": members, "pg": pg,
            "group_name": group_name, "gang_id": gang_id,
            "spawned_at": time.time()}


def kill_replica_group(gang: dict, remove_pg: bool = True) -> None:
    """Tear one gang down: hard-kill every member (collective segments
    are unlinked by the survivors'/owner's close paths + the conftest
    leak sweep names stragglers) and release the reservation."""
    import ray_tpu
    from ray_tpu.util.placement_group import remove_placement_group

    for m in gang.get("members") or []:
        try:
            ray_tpu.kill(m)
        except Exception:
            pass
    if remove_pg and gang.get("pg") is not None:
        try:
            remove_placement_group(gang["pg"])
        except Exception:
            pass
