"""Paged KV-cache for the streaming inference tier (ROADMAP item 1,
the vLLM PagedAttention idea sized for this runtime).

One pool per decode engine (per gang RANK: each shard caches only its
own column-sharded slice of the per-token KV vectors, so an N-way gang
holds an N-way-partitioned cache with no cross-rank traffic on reads).
The pool is a single fixed arena of `num_pages` pages of `page_size`
token rows each; sequences own pages through a page table (logical
token index -> (page, slot)), so a sequence's cache grows in page-sized
quanta with zero copying and frees back to the pool the moment the
sequence finishes or aborts.

Cross-session prefix sharing (ROADMAP item 4): pages carry a REFCOUNT
and the pool hosts a radix tree over page-aligned token prefixes (the
`PrefixIndex`). A full page is immutable once written, so identical
page-aligned prefixes prefill ONCE: admission walks the tree
(`adopt_prefix`), adopts the longest matching prefix by bumping page
refcounts, and only the tail tokens are embedded. `truncate`/`free`/
tree eviction are refcount decrements — a page returns to the free
list only at refcount 0 — and a write landing in a shared tail page
(possible only after `truncate` into a shared full page) COPIES the
written rows to a fresh page first (copy-on-write at the divergence
point), so a reader never observes another session's divergent rows.
The tree itself holds one reference per indexed page; under pool
pressure the allocator reclaims index-only pages leaf-first in
deterministic LRU order (a logical clock, not wall time — every gang
rank applies the same op stream and must evict identically).

Arena residency: in-cluster pools place their backing buffer in the
same tmpfs as the plasma store arena (`<session>/objects/kvpool`,
beside the collective segments) — shard-resident across steps like
PR 10 payloads, and visible in /dev/shm accounting. The file is
unlinked immediately after mapping (anonymous-by-unlink), so a
hard-killed member can never leak a segment file; logical page leaks
are the observable kind and are named by `leak_report()` + the
conftest leak sweep.

Backends: numpy (host gangs — the default) or jax, where the append is
a jitted update with the arena DONATED (`donate_argnums=0`), so the
per-token write mutates the buffer in place instead of copying the
whole arena per token.

Chaos seam: `serve.kv_page_alloc` fires on every page allocation.
"""

from __future__ import annotations

import hashlib
import os
import threading

import numpy as np

from ray_tpu._private import failpoints as _fp
from ray_tpu.serve.metrics import (M_KV_PAGES, M_KV_PAGES_SHARED,
                                   M_PREFIX_HITS, M_PREFIX_SAVED)


class KVCacheExhausted(RuntimeError):
    """The pool has no free page. Admission paths shed on this; decode
    paths abort the requesting sequence (typed SequenceAborted)."""

    def __init__(self, pool: str, num_pages: int):
        self.pool = pool
        self.num_pages = num_pages
        super().__init__(
            f"KV page pool {pool!r} exhausted ({num_pages} pages all "
            f"in use)")


def _arena_dir() -> str | None:
    """Directory beside the plasma store arena for in-cluster pools
    (mirrors the collective segment_dir convention); None outside a
    runtime — the pool then uses a plain anonymous buffer."""
    from ray_tpu._private import global_state

    cw = global_state.get_core_worker()
    root = getattr(getattr(cw, "store", None), "root", None) if cw else None
    if not root:
        return None
    return os.path.join(os.path.dirname(os.path.normpath(root)), "kvpool")


def _alloc_arena(name: str, nbytes: int) -> np.ndarray:
    """Flat uint8 buffer for the page arena: shm-file-backed beside the
    store arena when a runtime is up (unlinked after mapping — no leak
    path), else a plain numpy allocation."""
    d = None
    try:
        d = _arena_dir()
    except Exception:
        d = None
    if d is not None:
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"{name}-{os.getpid()}")
            buf = np.memmap(path, dtype=np.uint8, mode="w+",
                            shape=(max(nbytes, 1),))
            os.unlink(path)  # anonymous-by-unlink: survives only as long
            return buf       # as this mapping; a SIGKILL can't leak it
        except OSError:
            pass
    return np.zeros(max(nbytes, 1), dtype=np.uint8)


# Live pools in this process, for debug_state / the conftest leak sweep
# (named logical-page leaks, not bare gauge numbers).
_live_pools: dict[int, "PagedKVCache"] = {}
_pools_lock = threading.Lock()


def debug_pools() -> list[dict]:
    with _pools_lock:
        pools = list(_live_pools.values())
    out = []
    for p in pools:
        try:
            out.append(p.debug_state())
        except Exception:
            continue
    return out


# -- prefix hashing ---------------------------------------------------------


def _chain_digest(prev: bytes, block) -> bytes:
    h = hashlib.blake2b(prev, digest_size=8)
    h.update(np.asarray(block, dtype=np.int64).tobytes())
    return h.digest()


def prefix_block_hashes(tokens, page_size: int,
                        max_blocks: int = 32) -> list[str]:
    """Chained hashes of the page-aligned token prefix: entry i covers
    tokens[0 : (i+1)*page_size]. The SAME function runs engine-side
    (stream meta) and router-side (prefix routing), so a hash match
    means the replica holds exactly that page-aligned prefix. Only FULL
    pages hash — a prefix shorter than one page has no shareable page
    and reports nothing (the mis-aligned-hashing doctor finding keys
    off this)."""
    if page_size < 1:
        return []
    out: list[str] = []
    d = b""
    n = min(len(tokens) // page_size, max_blocks)
    for i in range(n):
        d = _chain_digest(d, tokens[i * page_size:(i + 1) * page_size])
        out.append(d.hex())
    return out


class _PrefixNode:
    """One full page of the radix tree: `block` (the page's tokens) keys
    it under its parent, `page` is the arena page holding those tokens'
    KV rows (index-owned: one refcount held while the node lives)."""

    __slots__ = ("block", "page", "parent", "children", "stamp", "digest")

    def __init__(self, block: tuple, page: int,
                 parent: "_PrefixNode | None", digest: bytes):
        self.block = block
        self.page = page
        self.parent = parent
        self.children: dict[tuple, _PrefixNode] = {}
        self.stamp = 0
        self.digest = digest


class PageTable:
    """One sequence's (or cached session's) view of the pool: ordered
    page ids + the count of token rows written. Pages may be SHARED
    (refcount > 1) with other tables / the prefix index; full shared
    pages are read-only and a tail write copies first (CoW)."""

    __slots__ = ("owner", "pages", "length")

    def __init__(self, owner: str):
        self.owner = owner
        self.pages: list[int] = []
        self.length = 0


class PagedKVCache:
    """Fixed-size page pool + per-owner page tables (thread-safe: the
    engine thread appends while actor threads open/abort/inspect).

    `prefix_max_nodes` > 0 enables the prefix index (bounded node
    count); 0 keeps the pre-sharing behavior exactly (every page
    exclusively owned, refcounts degenerate to 0/1)."""

    def __init__(self, num_pages: int, page_size: int, width: int,
                 name: str = "kv", backend: str = "numpy",
                 prefix_max_nodes: int = 0):
        if num_pages < 1 or page_size < 1 or width < 1:
            raise ValueError("num_pages, page_size and width must be >= 1")
        self.name = name
        self.num_pages = num_pages
        self.page_size = page_size
        self.width = width
        self.backend = backend
        nbytes = num_pages * page_size * width * 4
        if backend == "jax":
            import jax.numpy as jnp

            self._pages = jnp.zeros((num_pages, page_size, width),
                                    dtype=jnp.float32)
            self._donated_update = _make_donated_update()
        else:
            raw = _alloc_arena(name, nbytes)
            self._pages = np.frombuffer(
                raw, dtype=np.float32,
                count=num_pages * page_size * width).reshape(
                    num_pages, page_size, width)
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        self._tables: dict[str, PageTable] = {}
        self._lock = threading.Lock()
        # refcounts: tables + the prefix index each hold one ref per
        # page; a page is reusable only at refcount 0
        self._refs = [0] * num_pages
        self._index_flag = bytearray(num_pages)  # 1 = index holds a ref
        self._in_use = 0    # pages with >= 1 TABLE ref (the gauge)
        self._shared = 0    # pages with refcount > 1
        self._g_in_use = 0  # last values pushed to the process gauges
        self._g_shared = 0
        # prefix index (radix tree over page-aligned token prefixes)
        self._pref_max = max(0, int(prefix_max_nodes or 0))
        self._pref_root: dict[tuple, _PrefixNode] = {}
        self._pref_all: set[_PrefixNode] = set()
        self._pref_lookups = 0
        self._pref_hits = 0
        self._pref_tokens_saved = 0
        self._clock = 0  # deterministic LRU stamp (not wall time)
        with _pools_lock:
            _live_pools[id(self)] = self

    # -- refcount plumbing (all under self._lock) ------------------------

    def _table_refs(self, page: int) -> int:
        return self._refs[page] - (1 if self._index_flag[page] else 0)

    def _incref_table(self, page: int):
        r = self._refs[page]
        if r - (1 if self._index_flag[page] else 0) == 0:
            self._in_use += 1
        if r == 1:
            self._shared += 1
        self._refs[page] = r + 1

    def _decref_table(self, page: int):
        r = self._refs[page] - 1
        self._refs[page] = r
        if r - (1 if self._index_flag[page] else 0) == 0:
            self._in_use -= 1
        if r == 1:
            self._shared -= 1
        elif r == 0:
            self._free.append(page)

    def _incref_index(self, page: int):
        r = self._refs[page]
        if r == 1:
            self._shared += 1
        self._refs[page] = r + 1
        self._index_flag[page] = 1

    def _decref_index(self, page: int):
        self._index_flag[page] = 0
        r = self._refs[page] - 1
        self._refs[page] = r
        if r == 1:
            self._shared -= 1
        elif r == 0:
            self._free.append(page)

    def _sync_gauges(self):
        # under self._lock; pushes only deltas so many pools per process
        # share the gauges without clobbering each other
        if self._in_use != self._g_in_use:
            M_KV_PAGES.add(self._in_use - self._g_in_use)
            self._g_in_use = self._in_use
        if self._shared != self._g_shared:
            M_KV_PAGES_SHARED.add(self._shared - self._g_shared)
            self._g_shared = self._shared

    # -- allocation ------------------------------------------------------

    def alloc_table(self, owner: str) -> PageTable:
        with self._lock:
            if owner in self._tables:
                raise ValueError(f"owner {owner!r} already has a table")
            t = self._tables[owner] = PageTable(owner)
        return t

    def has(self, owner: str) -> bool:
        return owner in self._tables

    def adopt(self, old_owner: str, new_owner: str) -> int:
        """Re-key a table (session cache -> live sequence and back).
        Returns the token length carried over."""
        with self._lock:
            t = self._tables.pop(old_owner)
            t.owner = new_owner
            self._tables[new_owner] = t
            return t.length

    def _alloc_page(self) -> int:
        # under self._lock: a TABLE allocation (refcount 1). Pool
        # pressure reclaims index-only pages first — the prefix cache
        # must never turn into an exhaustion a cold pool wouldn't hit.
        if _fp.ARMED:
            _fp.fire_strict("serve.kv_page_alloc")
        if not self._free:
            self._pref_reclaim()
        if not self._free:
            raise KVCacheExhausted(self.name, self.num_pages)
        page = self._free.pop()
        self._refs[page] = 1
        self._in_use += 1
        return page

    def _copy_rows(self, src: int, dst: int, nrows: int):
        # under self._lock
        if self.backend == "jax":
            self._pages = self._pages.at[dst, :nrows].set(
                self._pages[src, :nrows])
        else:
            self._pages[dst, :nrows] = self._pages[src, :nrows]

    def append(self, owner: str, vectors) -> None:
        """Write `vectors` ((T, width) float32) as the owner's next T
        token rows, allocating pages on demand. Raises KVCacheExhausted
        with the table intact (already-written rows stay valid) when the
        pool runs dry — the caller aborts/sheds and frees. A shared tail
        page (refcount > 1: reachable only by truncating into a shared
        full page) is copied before the write — divergence never mutates
        rows another owner reads."""
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        with self._lock:
            t = self._tables[owner]
            try:
                for row in vectors:
                    slot = t.length % self.page_size
                    if slot == 0:
                        t.pages.append(self._alloc_page())
                    elif self._refs[t.pages[-1]] > 1:
                        # copy-on-write at the divergence point
                        fresh = self._alloc_page()
                        self._copy_rows(t.pages[-1], fresh, slot)
                        self._decref_table(t.pages[-1])
                        t.pages[-1] = fresh
                    page = t.pages[-1]
                    if self.backend == "jax":
                        self._pages = self._donated_update(
                            self._pages, page, slot, row)
                    else:
                        self._pages[page, slot] = row
                    t.length += 1
            finally:
                self._sync_gauges()

    def gather_sum(self, owner: str):
        """Sum of the owner's cached token rows ((width,) float32) — the
        read path of the reference model's decode step (page-table
        indirection: full pages summed whole, the tail page masked)."""
        with self._lock:
            t = self._tables[owner]
            out = np.zeros(self.width, dtype=np.float32)
            if not t.pages:
                return out
            pages = (np.asarray(self._pages) if self.backend == "jax"
                     else self._pages)
            full, tail = divmod(t.length, self.page_size)
            for page in t.pages[:full]:
                out += pages[page].sum(axis=0)
            if tail:
                out += pages[t.pages[full]][:tail].sum(axis=0)
            return out

    def truncate(self, owner: str, length: int) -> int:
        """Drop the owner's rows past `length` (releasing now-empty tail
        pages — a refcount decrement: a page still shared with another
        table or the prefix index survives); returns pages released.
        Deterministic from the same arithmetic on every rank — the
        warm-session shed path restores an adopted prefix to exactly its
        pre-admission state."""
        import math

        with self._lock:
            t = self._tables[owner]
            if length >= t.length:
                return 0
            keep = math.ceil(length / self.page_size)
            tail = t.pages[keep:]
            del t.pages[keep:]
            for page in tail:
                self._decref_table(page)
            t.length = length
            self._sync_gauges()
            return len(tail)

    def length(self, owner: str) -> int:
        with self._lock:
            t = self._tables.get(owner)
            return t.length if t else 0

    def free(self, owner: str) -> int:
        """Release every page of `owner` (refcount decrements: shared
        pages survive for their other holders); returns the count (0
        for an unknown owner — free is idempotent: abort paths race
        finish paths and must both be safe to run)."""
        with self._lock:
            t = self._tables.pop(owner, None)
            if t is None:
                return 0
            n = len(t.pages)
            for page in t.pages:
                self._decref_table(page)
            t.pages.clear()
            self._sync_gauges()
        return n

    def free_all(self) -> int:
        with self._lock:
            owners = list(self._tables)
        n = sum(self.free(o) for o in owners)
        self.clear_prefix()
        return n

    def close(self):
        self.free_all()
        with _pools_lock:
            _live_pools.pop(id(self), None)

    # -- prefix index (cross-session sharing) ----------------------------

    def adopt_prefix(self, owner: str, tokens) -> int:
        """Create `owner`'s table pre-populated with the longest
        page-aligned prefix of `tokens` the index holds (one refcount
        bump per adopted page — no copy, no prefill). Returns the
        adopted token count; the caller embeds only tokens[matched:]."""
        with self._lock:
            if owner in self._tables:
                raise ValueError(f"owner {owner!r} already has a table")
            t = self._tables[owner] = PageTable(owner)
            if self._pref_max <= 0 or not self._pref_root:
                self._pref_lookups += 1
                return 0
            self._pref_lookups += 1
            self._clock += 1
            ps = self.page_size
            cmap = self._pref_root
            matched: list[int] = []
            for i in range(len(tokens) // ps):
                node = cmap.get(tuple(int(x) for x
                                      in tokens[i * ps:(i + 1) * ps]))
                if node is None:
                    break
                node.stamp = self._clock
                matched.append(node.page)
                cmap = node.children
            if matched:
                for page in matched:
                    self._incref_table(page)
                t.pages = list(matched)
                t.length = len(matched) * ps
                self._pref_hits += 1
                self._pref_tokens_saved += t.length
                M_PREFIX_HITS.inc()
                M_PREFIX_SAVED.inc(t.length)
            self._sync_gauges()
            return t.length

    def register_prefix(self, owner: str, tokens) -> int:
        """Index `owner`'s full pages covering the page-aligned prefix
        of `tokens` (after a successful prefill): later admissions with
        the same prefix adopt them. The index holds ONE ref per indexed
        page, so indexed pages outlive the registering sequence; the
        node bound (and pool pressure) evicts leaf-first in LRU order.
        Returns nodes added."""
        with self._lock:
            if self._pref_max <= 0:
                return 0
            t = self._tables.get(owner)
            if t is None:
                return 0
            ps = self.page_size
            nblocks = min(len(tokens), t.length) // ps
            cmap = self._pref_root
            parent: _PrefixNode | None = None
            digest = b""
            added = 0
            path: set[int] = set()
            self._clock += 1
            for i in range(nblocks):
                block = tuple(int(x) for x in tokens[i * ps:(i + 1) * ps])
                digest = _chain_digest(digest, block)
                node = cmap.get(block)
                if node is None:
                    while (len(self._pref_all) >= self._pref_max
                           and self._evict_leaf(exclude=path)):
                        pass
                    if len(self._pref_all) >= self._pref_max:
                        break
                    node = _PrefixNode(block, t.pages[i], parent, digest)
                    cmap[block] = node
                    self._pref_all.add(node)
                    self._incref_index(node.page)
                    added += 1
                node.stamp = self._clock
                path.add(id(node))
                parent = node
                cmap = node.children
            self._sync_gauges()
            return added

    def _evict_leaf(self, exclude: set[int] = frozenset()) -> bool:
        # under self._lock: drop the least-recently-used LEAF node
        # (deterministic tie-break on the path digest — every gang rank
        # applies the same op stream and must evict the same node)
        best = None
        for node in self._pref_all:
            if node.children or id(node) in exclude:
                continue
            if best is None or (node.stamp, node.digest) < \
                    (best.stamp, best.digest):
                best = node
        if best is None:
            return False
        self._drop_node(best)
        return True

    def _drop_node(self, node: _PrefixNode):
        # under self._lock; node must be a leaf
        cmap = node.parent.children if node.parent is not None \
            else self._pref_root
        cmap.pop(node.block, None)
        self._pref_all.discard(node)
        self._decref_index(node.page)

    def _pref_reclaim(self):
        # under self._lock: free-list empty — evict index leaves until a
        # page actually frees (an evicted page still table-shared frees
        # nothing but stops blocking deeper leaves) or the index is dry
        while not self._free and self._evict_leaf():
            pass

    def clear_prefix(self) -> int:
        """Drop the whole index (engine death / shutdown: the chaos
        invariant is zero pages held by ANYTHING afterwards)."""
        with self._lock:
            n = len(self._pref_all)
            for node in self._pref_all:
                self._decref_index(node.page)
            self._pref_all.clear()
            self._pref_root = {}
            self._sync_gauges()
        return n

    def prefix_stats(self) -> dict:
        with self._lock:
            cached = sum(1 for node in self._pref_all
                         if self._refs[node.page] == 1)
            return {
                "enabled": self._pref_max > 0,
                "nodes": len(self._pref_all),
                "max_nodes": self._pref_max,
                "lookups": self._pref_lookups,
                "hits": self._pref_hits,
                "tokens_saved": self._pref_tokens_saved,
                "pages_cached": cached,
                "pages_shared": self._shared,
            }

    # -- warm start (hot prefix pages over the bulk channel) -------------

    def export_prefix(self, max_pages: int = 128) -> list[dict]:
        """Hot index pages for a sibling replica's cache warm-up, BFS
        from the root (near-root pages are the most-shared prefixes;
        parents always precede children so the importer can rebuild the
        chain), recency-ordered within each node's children. Entries:
        {"parent": index into this list (-1 = root), "block": tokens,
        "rows": (page_size, width) float32}."""
        with self._lock:
            pages = (np.asarray(self._pages) if self.backend == "jax"
                     else self._pages)
            out: list[dict] = []
            queue = [(n, -1) for n in sorted(
                self._pref_root.values(),
                key=lambda n: (-n.stamp, n.digest))]
            while queue and len(out) < max_pages:
                node, pidx = queue.pop(0)
                out.append({"parent": pidx,
                            "block": list(node.block),
                            "rows": np.array(pages[node.page],
                                             dtype=np.float32)})
                my = len(out) - 1
                queue.extend((k, my) for k in sorted(
                    node.children.values(),
                    key=lambda n: (-n.stamp, n.digest)))
            return out

    def import_prefix(self, entries: list[dict]) -> int:
        """Adopt exported prefix pages into this pool's index (warm
        start: the prefill compute rode the bulk channel instead of
        being recomputed). Advisory — stops without error at the node
        bound or on pool pressure; never evicts live state to make
        room. Returns pages imported."""
        if self._pref_max <= 0:
            return 0
        added = 0
        with self._lock:
            nodes: list[_PrefixNode | None] = []
            self._clock += 1
            for e in entries:
                pidx = int(e.get("parent", -1))
                parent = (nodes[pidx]
                          if 0 <= pidx < len(nodes) else None)
                if pidx >= 0 and parent is None:
                    nodes.append(None)  # ancestor was skipped
                    continue
                block = tuple(int(x) for x in e["block"])
                if len(block) != self.page_size:
                    nodes.append(None)  # page-size mismatch: skip chain
                    continue
                cmap = (parent.children if parent is not None
                        else self._pref_root)
                node = cmap.get(block)
                if node is None:
                    rows = np.asarray(e["rows"], dtype=np.float32)
                    if rows.shape != (self.page_size, self.width) \
                            or len(self._pref_all) >= self._pref_max \
                            or not self._free:
                        nodes.append(None)
                        continue
                    page = self._free.pop()
                    self._refs[page] = 1
                    self._index_flag[page] = 1
                    if self.backend == "jax":
                        self._pages = self._pages.at[page].set(rows)
                    else:
                        self._pages[page][:] = rows
                    digest = _chain_digest(
                        parent.digest if parent is not None else b"",
                        block)
                    node = _PrefixNode(block, page, parent, digest)
                    cmap[block] = node
                    self._pref_all.add(node)
                    added += 1
                node.stamp = self._clock
                nodes.append(node)
            self._sync_gauges()
        return added

    # -- introspection ---------------------------------------------------

    def pages_in_use(self) -> int:
        """Pages held by at least one TABLE (live sequences + retained
        sessions). Index-only pages are reclaimable cache, reported
        separately as pages_cached — they are not leaks and not in-use."""
        with self._lock:
            return self._in_use

    def owners(self) -> dict[str, int]:
        """owner -> page count (the per-session page-count rows of
        `ray-tpu state serve` / the dashboard)."""
        with self._lock:
            return {o: len(t.pages) for o, t in self._tables.items()}

    def leak_report(self, live_owners) -> list[dict]:
        """Tables whose owner is NOT in `live_owners` (live sequences +
        retained sessions): by construction the engine frees on finish/
        abort, so anything here is a leaked-page bug the conftest sweep
        names."""
        live = set(live_owners)
        with self._lock:
            return [{"owner": o, "pages": len(t.pages),
                     "tokens": t.length}
                    for o, t in self._tables.items()
                    if o not in live and t.pages]

    def debug_state(self) -> dict:
        with self._lock:
            cached = sum(1 for node in self._pref_all
                         if self._refs[node.page] == 1)
            lookups = self._pref_lookups
            hits = self._pref_hits
            return {
                "name": self.name,
                "backend": self.backend,
                "pages_total": self.num_pages,
                "pages_in_use": self._in_use,
                "pages_shared": self._shared,
                "pages_cached": cached,
                "page_size": self.page_size,
                "width": self.width,
                "owners": {o: len(t.pages)
                           for o, t in self._tables.items()},
                "prefix": {
                    "enabled": self._pref_max > 0,
                    "nodes": len(self._pref_all),
                    "max_nodes": self._pref_max,
                    "lookups": lookups,
                    "hits": hits,
                    "hit_rate": round(hits / lookups, 4) if lookups
                    else 0.0,
                    "tokens_saved": self._pref_tokens_saved,
                },
            }


def _make_donated_update():
    """Jitted single-row page write with the arena DONATED: XLA reuses
    the input buffer for the output, so the per-token update is in-place
    instead of an O(arena) copy (the jax path of `append`). The first
    dispatch per arena shape resolves through the persistent AOT compile
    cache (_private/compile_cache.py): a fresh serve replica whose arena
    shape an earlier replica already compiled deserializes the stored
    executable — no re-trace, no compile event — while a cold replica
    compiles, records the event (the decode-step seam of the
    jax.compile_s / recompile-storm plane), and populates the cache."""
    import jax

    from ray_tpu._private import compile_cache as _cc
    from ray_tpu._private import profiling as _profiling

    def _update(pages, page, slot, row):
        return pages.at[page, slot].set(row)

    jitted = jax.jit(_update, donate_argnums=(0,), static_argnums=())
    # the arena shape is fixed for the cache's lifetime but unknown
    # until the first token, so the CachedFunction is built lazily on
    # first dispatch (this runs per token inside the cache lock; the
    # steady state is one None check)
    state: dict = {"fn": None}

    def update(pages, page, slot, row):
        fn = state["fn"]
        if fn is None:
            sc = _profiling.shape_class(pages)
            fn = state["fn"] = _cc.CachedFunction(
                "serve.kv_update", (sc, str(pages.dtype), row.shape[0]),
                jitted, donate_argnums=(0,),
                record_key="serve.kv_update:" + sc)
        return fn(pages, page, slot, row)

    return update
