"""Paged KV-cache for the streaming inference tier (ROADMAP item 1,
the vLLM PagedAttention idea sized for this runtime).

One pool per decode engine (per gang RANK: each shard caches only its
own column-sharded slice of the per-token KV vectors, so an N-way gang
holds an N-way-partitioned cache with no cross-rank traffic on reads).
The pool is a single fixed arena of `num_pages` pages of `page_size`
token rows each; sequences own pages through a page table (logical
token index -> (page, slot)), so a sequence's cache grows in page-sized
quanta with zero copying and frees back to the pool the moment the
sequence finishes or aborts.

Arena residency: in-cluster pools place their backing buffer in the
same tmpfs as the plasma store arena (`<session>/objects/kvpool`,
beside the collective segments) — shard-resident across steps like
PR 10 payloads, and visible in /dev/shm accounting. The file is
unlinked immediately after mapping (anonymous-by-unlink), so a
hard-killed member can never leak a segment file; logical page leaks
are the observable kind and are named by `leak_report()` + the
conftest leak sweep.

Backends: numpy (host gangs — the default) or jax, where the append is
a jitted update with the arena DONATED (`donate_argnums=0`), so the
per-token write mutates the buffer in place instead of copying the
whole arena per token.

Chaos seam: `serve.kv_page_alloc` fires on every page allocation.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ray_tpu._private import failpoints as _fp
from ray_tpu.serve.metrics import M_KV_PAGES


class KVCacheExhausted(RuntimeError):
    """The pool has no free page. Admission paths shed on this; decode
    paths abort the requesting sequence (typed SequenceAborted)."""

    def __init__(self, pool: str, num_pages: int):
        self.pool = pool
        self.num_pages = num_pages
        super().__init__(
            f"KV page pool {pool!r} exhausted ({num_pages} pages all "
            f"in use)")


def _arena_dir() -> str | None:
    """Directory beside the plasma store arena for in-cluster pools
    (mirrors the collective segment_dir convention); None outside a
    runtime — the pool then uses a plain anonymous buffer."""
    from ray_tpu._private import global_state

    cw = global_state.get_core_worker()
    root = getattr(getattr(cw, "store", None), "root", None) if cw else None
    if not root:
        return None
    return os.path.join(os.path.dirname(os.path.normpath(root)), "kvpool")


def _alloc_arena(name: str, nbytes: int) -> np.ndarray:
    """Flat uint8 buffer for the page arena: shm-file-backed beside the
    store arena when a runtime is up (unlinked after mapping — no leak
    path), else a plain numpy allocation."""
    d = None
    try:
        d = _arena_dir()
    except Exception:
        d = None
    if d is not None:
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"{name}-{os.getpid()}")
            buf = np.memmap(path, dtype=np.uint8, mode="w+",
                            shape=(max(nbytes, 1),))
            os.unlink(path)  # anonymous-by-unlink: survives only as long
            return buf       # as this mapping; a SIGKILL can't leak it
        except OSError:
            pass
    return np.zeros(max(nbytes, 1), dtype=np.uint8)


# Live pools in this process, for debug_state / the conftest leak sweep
# (named logical-page leaks, not bare gauge numbers).
_live_pools: dict[int, "PagedKVCache"] = {}
_pools_lock = threading.Lock()


def debug_pools() -> list[dict]:
    with _pools_lock:
        pools = list(_live_pools.values())
    out = []
    for p in pools:
        try:
            out.append(p.debug_state())
        except Exception:
            continue
    return out


class PageTable:
    """One sequence's (or cached session's) view of the pool: ordered
    page ids + the count of token rows written."""

    __slots__ = ("owner", "pages", "length")

    def __init__(self, owner: str):
        self.owner = owner
        self.pages: list[int] = []
        self.length = 0


class PagedKVCache:
    """Fixed-size page pool + per-owner page tables (thread-safe: the
    engine thread appends while actor threads open/abort/inspect)."""

    def __init__(self, num_pages: int, page_size: int, width: int,
                 name: str = "kv", backend: str = "numpy"):
        if num_pages < 1 or page_size < 1 or width < 1:
            raise ValueError("num_pages, page_size and width must be >= 1")
        self.name = name
        self.num_pages = num_pages
        self.page_size = page_size
        self.width = width
        self.backend = backend
        nbytes = num_pages * page_size * width * 4
        if backend == "jax":
            import jax.numpy as jnp

            self._pages = jnp.zeros((num_pages, page_size, width),
                                    dtype=jnp.float32)
            self._donated_update = _make_donated_update()
        else:
            raw = _alloc_arena(name, nbytes)
            self._pages = np.frombuffer(
                raw, dtype=np.float32,
                count=num_pages * page_size * width).reshape(
                    num_pages, page_size, width)
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        self._tables: dict[str, PageTable] = {}
        self._lock = threading.Lock()
        with _pools_lock:
            _live_pools[id(self)] = self

    # -- allocation ------------------------------------------------------

    def alloc_table(self, owner: str) -> PageTable:
        with self._lock:
            if owner in self._tables:
                raise ValueError(f"owner {owner!r} already has a table")
            t = self._tables[owner] = PageTable(owner)
        return t

    def has(self, owner: str) -> bool:
        return owner in self._tables

    def adopt(self, old_owner: str, new_owner: str) -> int:
        """Re-key a table (session cache -> live sequence and back).
        Returns the token length carried over."""
        with self._lock:
            t = self._tables.pop(old_owner)
            t.owner = new_owner
            self._tables[new_owner] = t
            return t.length

    def _alloc_page(self) -> int:
        # under self._lock
        if _fp.ARMED:
            _fp.fire_strict("serve.kv_page_alloc")
        if not self._free:
            raise KVCacheExhausted(self.name, self.num_pages)
        page = self._free.pop()
        M_KV_PAGES.add(1)
        return page

    def append(self, owner: str, vectors) -> None:
        """Write `vectors` ((T, width) float32) as the owner's next T
        token rows, allocating pages on demand. Raises KVCacheExhausted
        with the table intact (already-written rows stay valid) when the
        pool runs dry — the caller aborts/sheds and frees."""
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        with self._lock:
            t = self._tables[owner]
            for row in vectors:
                slot = t.length % self.page_size
                if slot == 0:
                    t.pages.append(self._alloc_page())
                page = t.pages[-1]
                if self.backend == "jax":
                    self._pages = self._donated_update(
                        self._pages, page, slot, row)
                else:
                    self._pages[page, slot] = row
                t.length += 1

    def gather_sum(self, owner: str):
        """Sum of the owner's cached token rows ((width,) float32) — the
        read path of the reference model's decode step (page-table
        indirection: full pages summed whole, the tail page masked)."""
        with self._lock:
            t = self._tables[owner]
            out = np.zeros(self.width, dtype=np.float32)
            if not t.pages:
                return out
            pages = (np.asarray(self._pages) if self.backend == "jax"
                     else self._pages)
            full, tail = divmod(t.length, self.page_size)
            for page in t.pages[:full]:
                out += pages[page].sum(axis=0)
            if tail:
                out += pages[t.pages[full]][:tail].sum(axis=0)
            return out

    def truncate(self, owner: str, length: int) -> int:
        """Drop the owner's rows past `length` (freeing now-empty tail
        pages); returns pages freed. Deterministic from the same
        arithmetic on every rank — the warm-session shed path restores
        an adopted prefix to exactly its pre-admission state."""
        import math

        freed = 0
        with self._lock:
            t = self._tables[owner]
            if length >= t.length:
                return 0
            keep = math.ceil(length / self.page_size)
            tail = t.pages[keep:]
            del t.pages[keep:]
            self._free.extend(reversed(tail))
            t.length = length
            freed = len(tail)
        if freed:
            M_KV_PAGES.add(-freed)
        return freed

    def length(self, owner: str) -> int:
        with self._lock:
            t = self._tables.get(owner)
            return t.length if t else 0

    def free(self, owner: str) -> int:
        """Return every page of `owner` to the pool; returns the count
        (0 for an unknown owner — free is idempotent: abort paths race
        finish paths and must both be safe to run)."""
        with self._lock:
            t = self._tables.pop(owner, None)
            if t is None:
                return 0
            n = len(t.pages)
            self._free.extend(reversed(t.pages))
            t.pages.clear()
        if n:
            M_KV_PAGES.add(-n)
        return n

    def free_all(self) -> int:
        with self._lock:
            owners = list(self._tables)
        return sum(self.free(o) for o in owners)

    def close(self):
        self.free_all()
        with _pools_lock:
            _live_pools.pop(id(self), None)

    # -- introspection ---------------------------------------------------

    def pages_in_use(self) -> int:
        with self._lock:
            return self.num_pages - len(self._free)

    def owners(self) -> dict[str, int]:
        """owner -> page count (the per-session page-count rows of
        `ray-tpu state serve` / the dashboard)."""
        with self._lock:
            return {o: len(t.pages) for o, t in self._tables.items()}

    def leak_report(self, live_owners) -> list[dict]:
        """Tables whose owner is NOT in `live_owners` (live sequences +
        retained sessions): by construction the engine frees on finish/
        abort, so anything here is a leaked-page bug the conftest sweep
        names."""
        live = set(live_owners)
        with self._lock:
            return [{"owner": o, "pages": len(t.pages),
                     "tokens": t.length}
                    for o, t in self._tables.items()
                    if o not in live and t.pages]

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "backend": self.backend,
                "pages_total": self.num_pages,
                "pages_in_use": self.num_pages - len(self._free),
                "page_size": self.page_size,
                "width": self.width,
                "owners": {o: len(t.pages)
                           for o, t in self._tables.items()},
            }


def _make_donated_update():
    """Jitted single-row page write with the arena DONATED: XLA reuses
    the input buffer for the output, so the per-token update is in-place
    instead of an O(arena) copy (the jax path of `append`). The first
    dispatch per arena shape resolves through the persistent AOT compile
    cache (_private/compile_cache.py): a fresh serve replica whose arena
    shape an earlier replica already compiled deserializes the stored
    executable — no re-trace, no compile event — while a cold replica
    compiles, records the event (the decode-step seam of the
    jax.compile_s / recompile-storm plane), and populates the cache."""
    import jax

    from ray_tpu._private import compile_cache as _cc
    from ray_tpu._private import profiling as _profiling

    def _update(pages, page, slot, row):
        return pages.at[page, slot].set(row)

    jitted = jax.jit(_update, donate_argnums=(0,), static_argnums=())
    # the arena shape is fixed for the cache's lifetime but unknown
    # until the first token, so the CachedFunction is built lazily on
    # first dispatch (this runs per token inside the cache lock; the
    # steady state is one None check)
    state: dict = {"fn": None}

    def update(pages, page, slot, row):
        fn = state["fn"]
        if fn is None:
            sc = _profiling.shape_class(pages)
            fn = state["fn"] = _cc.CachedFunction(
                "serve.kv_update", (sc, str(pages.dtype), row.shape[0]),
                jitted, donate_argnums=(0,),
                record_key="serve.kv_update:" + sc)
        return fn(pages, page, slot, row)

    return update
