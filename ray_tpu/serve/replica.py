"""Replica actor (reference: python/ray/serve/backend_worker.py:175
RayServeReplica). Batching lives router-side here (the BatchQueue idea,
backend_worker.py:33, moved to the caller so one actor RPC carries a whole
batch — on TPU the batch is the unit that fills the MXU)."""

from __future__ import annotations

import inspect
import time

import cloudpickle

from ray_tpu._private import failpoints as _fp
from ray_tpu._private import stats as _stats
from ray_tpu._private import tracing as _tracing
from ray_tpu.serve.engine import StreamingEngineHost

M_REPLICA_EXEC_S = _stats.Histogram(
    "serve.replica_exec_s", _stats.LATENCY_BOUNDARIES_S,
    "user-callable execution per batch (replica side; pairs with "
    "serve.router_queue_s as the autoscaler's latency feed)")


def _is_accept_batch(fn) -> bool:
    return getattr(fn, "_serve_accept_batch", False)


def accept_batch(fn):
    """Mark a callable as taking a LIST of requests per call (reference:
    serve/api.py:697 accept_batch)."""
    fn._serve_accept_batch = True
    return fn


class Replica(StreamingEngineHost):
    """Hosts one copy of the user's callable — and, for streaming
    backends, an unsharded decode engine (allreduce = identity): the
    continuous-batching tier doesn't require sharding."""

    def __init__(self, pickled_callable: bytes, init_args: tuple,
                 user_config: dict | None,
                 large_payload_threshold: int = 0,
                 config: dict | None = None):
        self._threshold = large_payload_threshold
        self._backend_name = (config or {}).get("_backend_name") or ""
        target = cloudpickle.loads(pickled_callable)
        if inspect.isclass(target):
            self._callable = target(*init_args)
            call = getattr(self._callable, "__call__", None)
            self._accept_batch = _is_accept_batch(
                getattr(type(self._callable), "__call__", None)) or \
                _is_accept_batch(call)
        else:
            self._callable = target
            self._accept_batch = _is_accept_batch(target)
        if user_config is not None:
            reconfigure = getattr(self._callable, "reconfigure", None)
            if reconfigure:
                reconfigure(user_config)
        self._streaming = bool((config or {}).get("streaming"))
        if self._streaming:
            self._start_engine(self._callable, config or {},
                               self._backend_name)
        self._batches_handled = 0
        self._last_batch_at = 0.0

    def arm_failpoint(self, name: str, action: str, **kw):
        """Test hook: arm a failpoint in THIS replica's process (chaos
        picks one victim; env arming would fire in every replica)."""
        _fp.arm(name, action, **kw)
        return True

    def reconfigure(self, user_config: dict):
        fn = getattr(self._callable, "reconfigure", None)
        if fn:
            fn(user_config)
        return True

    def handle_batch(self, requests: list):
        """One RPC per batch; returns per-request results (the runtime
        splits them into the callers' ObjectRefs via num_returns).
        Zero-copy plane: LargePayload markers resolve here (the bytes
        rode plasma + the bulk channel, not the router), and results at
        or over the threshold ride plasma back the same way."""
        from ray_tpu.serve import payload as _payload

        if self._streaming:
            # the decode loop owns this replica's compute (and, sharded,
            # its collective op stream): request/response callers go
            # through the stream API instead of racing it
            raise RuntimeError(
                "streaming backend: use the stream API "
                "(handle.stream(...) / SSE through the proxy), not "
                "request/response dispatch")
        # wrap responses only for callers speaking the zero-copy
        # protocol (the HTTP proxy): a plain handle.remote() caller gets
        # values, never markers
        wrap_back = [isinstance(r, _payload.LargePayload)
                     for r in requests]
        requests = [_payload.unwrap(r) for r in requests]
        start = time.time()
        try:
            if self._accept_batch:
                out = self._callable(requests)
                if len(out) != len(requests):
                    raise ValueError(
                        f"accept_batch callable returned {len(out)} results "
                        f"for {len(requests)} requests")
            else:
                out = [self._callable(r) for r in requests]
        finally:
            # the batch executes inside the traced task's ambient
            # context (router's tracing.use around .remote()), so the
            # exemplar links this batch's slowest-request tree
            M_REPLICA_EXEC_S.observe(time.time() - start,
                                     exemplar=_tracing.current_id())
            self._batches_handled += 1
            self._last_batch_at = time.time()
        if self._threshold:
            out = [_payload.wrap(r, self._threshold) if w else r
                   for r, w in zip(out, wrap_back)]
        return tuple(out) if len(out) > 1 else out[0]

    def ping(self):
        return "pong"

    def __ray_debug_state__(self) -> dict:
        """Live-state hook (debug_state.py)."""
        out = {"kind": "serve-replica",
               "backend": self._backend_name,
               "batches_handled": self._batches_handled,
               "last_batch_age_s": (round(time.time()
                                          - self._last_batch_at, 3)
                                    if self._last_batch_at else None)}
        if self._engine is not None:
            out["engine"] = self._engine.debug_state()
        return out
