"""ray_tpu.serve — actor-based model serving with dynamic micro-batching
(the Serve equivalent; reference: python/ray/serve/). On TPU the batch is
what fills the MXU: the router groups queries to max_batch_size before one
replica RPC. Production tier (ROADMAP item 1): bounded admission queues
with typed load shedding, zero-copy large payloads over plasma + the bulk
channel, sharded replica GROUPS whose forward pass is collective-backed
(serve/replica_group.py), and a STREAMING inference tier — token-level
continuous batching inside the replica/gang leader, a paged shard-resident
KV-cache, SSE end-to-end, and session-affinity routing (serve/engine.py,
serve/kv_cache.py, serve/streaming.py)."""

from ray_tpu.exceptions import (ReplicaGroupDied, SequenceAborted,
                                ServeOverloadedError)
from ray_tpu.serve.api import Client, connect, shutdown, start
from ray_tpu.serve.config import BackendConfig
from ray_tpu.serve.engine import ShardedTokenLM
from ray_tpu.serve.payload import LargePayload
from ray_tpu.serve.replica import accept_batch
from ray_tpu.serve.replica_group import ShardedMLP
from ray_tpu.serve.router import ServeHandle

__all__ = [
    "BackendConfig",
    "Client",
    "LargePayload",
    "ReplicaGroupDied",
    "SequenceAborted",
    "ServeHandle",
    "ServeOverloadedError",
    "ShardedMLP",
    "ShardedTokenLM",
    "accept_batch",
    "connect",
    "shutdown",
    "start",
]
