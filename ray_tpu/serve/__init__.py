"""ray_tpu.serve — actor-based model serving with dynamic micro-batching
(the Serve equivalent; reference: python/ray/serve/). On TPU the batch is
what fills the MXU: the router groups queries to max_batch_size before one
replica RPC."""

from ray_tpu.serve.api import Client, connect, shutdown, start
from ray_tpu.serve.config import BackendConfig
from ray_tpu.serve.replica import accept_batch
from ray_tpu.serve.router import ServeHandle

__all__ = [
    "BackendConfig",
    "Client",
    "ServeHandle",
    "accept_batch",
    "connect",
    "shutdown",
    "start",
]
