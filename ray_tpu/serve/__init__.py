"""ray_tpu.serve — actor-based model serving with dynamic micro-batching
(the Serve equivalent; reference: python/ray/serve/). On TPU the batch is
what fills the MXU: the router groups queries to max_batch_size before one
replica RPC. Production tier (ROADMAP item 1): bounded admission queues
with typed load shedding, zero-copy large payloads over plasma + the bulk
channel, and sharded replica GROUPS whose forward pass is collective-
backed (serve/replica_group.py)."""

from ray_tpu.exceptions import ReplicaGroupDied, ServeOverloadedError
from ray_tpu.serve.api import Client, connect, shutdown, start
from ray_tpu.serve.config import BackendConfig
from ray_tpu.serve.payload import LargePayload
from ray_tpu.serve.replica import accept_batch
from ray_tpu.serve.replica_group import ShardedMLP
from ray_tpu.serve.router import ServeHandle

__all__ = [
    "BackendConfig",
    "Client",
    "LargePayload",
    "ReplicaGroupDied",
    "ServeHandle",
    "ServeOverloadedError",
    "ShardedMLP",
    "accept_batch",
    "connect",
    "shutdown",
    "start",
]
