"""Backend configuration (reference: python/ray/serve/config.py
BackendConfig — num_replicas, max_batch_size, batch_wait_timeout,
max_concurrent_queries)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class AutoscalingConfig:
    """Queue-depth autoscaling (reference: python/ray/serve/
    autoscaling_policy.py:137 calculate_desired_num_replicas — scale so
    each replica carries ~target_queued queued queries)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_queued: float = 2.0       # queued queries per replica
    downscale_delay_s: float = 5.0   # hold-down before shrinking

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class BackendConfig:
    num_replicas: int = 1
    max_batch_size: int | None = None     # None = no batching
    batch_wait_timeout: float = 0.01      # s to wait filling a batch
    max_concurrent_queries: int = 8       # in-flight cap per replica
    user_config: dict | None = None
    autoscaling: dict | None = None       # AutoscalingConfig.to_dict()

    def __post_init__(self):
        if self.num_replicas < 0:
            raise ValueError("num_replicas must be >= 0")
        if self.max_batch_size is not None and self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.max_concurrent_queries < 1:
            raise ValueError("max_concurrent_queries must be >= 1")
        if isinstance(self.autoscaling, AutoscalingConfig):
            self.autoscaling = self.autoscaling.to_dict()

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "BackendConfig":
        return cls(**{k: v for k, v in d.items()
                      if k in {f.name for f in dataclasses.fields(cls)}})
