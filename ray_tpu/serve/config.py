"""Backend configuration (reference: python/ray/serve/config.py
BackendConfig — num_replicas, max_batch_size, batch_wait_timeout,
max_concurrent_queries; extended here with the production-tier knobs:
bounded admission queues, zero-copy payload cutover, and sharded
replica groups)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class AutoscalingConfig:
    """Queue-depth autoscaling (reference: python/ray/serve/
    autoscaling_policy.py:137 calculate_desired_num_replicas — scale so
    each replica carries ~target_queued queued queries)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_queued: float = 2.0       # queued queries per replica
    downscale_delay_s: float = 5.0   # hold-down before shrinking
    # -- KV-aware scaling (streaming backends) ---------------------------
    # The tick also sizes the fleet by KV-page pressure: replicas polled
    # for pages_in_use/pages_total, a short linear prediction over
    # kv_horizon_s extrapolates prefill load, and the fleet grows so the
    # predicted occupancy stays under kv_target_util per replica.
    # desired = max(queue_desired, kv_desired). 0 disables.
    kv_target_util: float = 0.8      # predicted pool occupancy ceiling
    kv_horizon_s: float = 10.0       # prediction lookahead

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class BackendConfig:
    num_replicas: int = 1
    max_batch_size: int | None = None     # None = no batching
    batch_wait_timeout: float = 0.01      # s to wait filling a batch
    max_concurrent_queries: int = 8       # in-flight cap per replica
    user_config: dict | None = None
    autoscaling: dict | None = None       # AutoscalingConfig.to_dict()
    # -- admission control (load shedding / backpressure) ---------------
    # Bounded router queue per endpoint: queries arriving when `queued
    # >= max_queued_requests` are refused with a typed
    # ServeOverloadedError (HTTP 503 + Retry-After) instead of growing
    # an unbounded backlog whose latency collapses under overload.
    # None = unbounded (legacy behavior).
    max_queued_requests: int | None = None
    # Hint callers receive with a shed (Retry-After seconds).
    overload_retry_after_s: float = 1.0
    # -- zero-copy payloads ---------------------------------------------
    # Request/response bodies at or over this many bytes ride plasma +
    # the bulk channel as ObjectRefs instead of being pickled through
    # the router. 0/None = always pickle (legacy behavior).
    large_payload_threshold: int = 1 << 20
    # -- sharded replica groups -----------------------------------------
    # num_shards > 1 turns each replica into a GANG of num_shards
    # member actors holding a Megatron-partitioned model; the forward
    # pass is collective-backed (see serve/replica_group.py).
    num_shards: int = 1
    shard_group_timeout_s: float = 10.0   # collective op deadline
    shard_transport: str = "auto"         # pin shm/ring/device, or auto
    num_cpus_per_shard: float = 0.001     # gang bundle reservation size
    # -- streaming inference (continuous batching / paged KV-cache) -----
    # streaming=True hosts a token-level decode engine in each replica
    # (the gang LEADER for num_shards>1): requests are admitted into the
    # running batch between decode steps, finished sequences retire
    # early, and responses stream token-by-token (SSE over HTTP). The
    # model must speak the decode protocol (see engine.ShardedTokenLM).
    streaming: bool = False
    max_decode_batch: int = 8             # running sequences per engine
    max_waiting_sequences: int = 32       # admission bound (typed shed)
    kv_page_size: int = 16                # tokens per KV page
    kv_pages_total: int = 512             # page pool size per rank
    kv_backend: str = "numpy"             # or "jax" (donated updates)
    session_cache_max: int = 32           # retained session KV tables
    stream_poll_s: float = 2.0            # router long-poll slice
    # -- KV-cache economy (cross-session prefix sharing) ----------------
    # prefix_sharing=True builds a radix tree over full KV pages:
    # admissions adopt the longest indexed page-aligned prefix
    # (refcounted, copy-on-write at divergence) and prefill only the
    # tail. The router mirrors the same page hashes to route new
    # sessions to the replica already holding their prefix.
    prefix_sharing: bool = True
    prefix_index_max_nodes: int = 256     # prefix-tree size per replica
    kv_warm_pages: int = 64               # pages pulled at scale-up (0=off)
    router_session_cap: int = 4096        # sticky-session LRU bound
    router_prefix_cap: int = 8192         # prefix-index LRU bound

    def __post_init__(self):
        if self.num_replicas < 0:
            raise ValueError("num_replicas must be >= 0")
        if self.max_batch_size is not None and self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.max_concurrent_queries < 1:
            raise ValueError("max_concurrent_queries must be >= 1")
        if self.max_queued_requests is not None \
                and self.max_queued_requests < 1:
            raise ValueError("max_queued_requests must be >= 1 (or None)")
        if self.num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        if self.num_shards > 1 and self.shard_group_timeout_s <= 0:
            raise ValueError("shard_group_timeout_s must be > 0")
        if self.streaming:
            if self.max_decode_batch < 1:
                raise ValueError("max_decode_batch must be >= 1")
            if self.max_waiting_sequences < 1:
                raise ValueError("max_waiting_sequences must be >= 1")
            if self.kv_page_size < 1 or self.kv_pages_total < 1:
                raise ValueError(
                    "kv_page_size and kv_pages_total must be >= 1")
            if self.kv_backend not in ("numpy", "jax"):
                raise ValueError("kv_backend must be 'numpy' or 'jax'")
            if self.session_cache_max < 0:
                raise ValueError("session_cache_max must be >= 0")
            if self.prefix_index_max_nodes < 0:
                raise ValueError("prefix_index_max_nodes must be >= 0")
            if self.kv_warm_pages < 0:
                raise ValueError("kv_warm_pages must be >= 0")
            if self.router_session_cap < 1 or self.router_prefix_cap < 1:
                raise ValueError(
                    "router_session_cap and router_prefix_cap must be >= 1")
        if isinstance(self.autoscaling, AutoscalingConfig):
            self.autoscaling = self.autoscaling.to_dict()

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "BackendConfig":
        return cls(**{k: v for k, v in d.items()
                      if k in {f.name for f in dataclasses.fields(cls)}})
