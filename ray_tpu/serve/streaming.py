"""Streamed responses end-to-end (ROADMAP item 1): the token plane
between a decode engine and its callers.

Engine side, a `TokenChannel` per live sequence: the decode thread
pushes each step's token and finishes the channel with an optional
typed error. Consumer side, the channel supports BOTH a threaded
blocking read (in-process callers, tests) and an asyncio long-poll
(`wait_async` — the gang leader's async `stream_next` actor method
parks here without holding the actor's event loop), waking waiters
through their own loop via `call_soon_threadsafe` so a token burst is
one wakeup, not one per waiter poll tick.

Above the actor boundary the tokens travel router -> proxy as chunk
dicts (`stream_next` long-poll replies) and leave the proxy as
Server-Sent Events — `sse_event`/`iter_sse_lines` define the wire
framing both the proxy and the test/bench clients speak, so
time-to-first-token is measured on the same bytes clients see.

Chaos seam: `serve.stream_emit` fires on every channel push (leader
emit path).
"""

from __future__ import annotations

import json
import threading
import time

from ray_tpu._private import failpoints as _fp


class TokenChannel:
    """Single-producer token stream with cursor-based reads (a reader
    that reconnects re-reads from its cursor; the channel keeps the
    whole sequence — generations are short-lived and bounded by
    max_tokens, so no ring eviction)."""

    __slots__ = ("seq_id", "tokens", "done", "error", "created_at",
                 "first_token_at", "finished_at", "consumed", "_cond",
                 "_waiters")

    def __init__(self, seq_id: str):
        self.seq_id = seq_id
        self.tokens: list[int] = []
        self.done = False
        self.error = None
        self.created_at = time.time()
        self.first_token_at = None
        self.finished_at = None
        self.consumed = 0  # highest cursor a reader acked (backlog gauge)
        self._cond = threading.Condition()
        # (loop, asyncio.Event) pairs parked in wait_async
        self._waiters: list = []

    # -- producer (decode thread) ---------------------------------------

    def push(self, token: int) -> None:
        if _fp.ARMED:
            _fp.fire_strict("serve.stream_emit")
        with self._cond:
            if self.done:
                return
            if self.first_token_at is None:
                self.first_token_at = time.time()
            self.tokens.append(int(token))
            self._wake_locked()

    def finish(self, error=None) -> None:
        """Close the channel (idempotent; the first error wins — an
        abort racing a gang-death must not downgrade the typed error a
        reader already saw)."""
        with self._cond:
            if self.done:
                return
            self.done = True
            self.error = error
            self.finished_at = time.time()
            self._wake_locked()

    def _wake_locked(self):
        self._cond.notify_all()
        waiters, self._waiters = self._waiters, []
        for loop, event in waiters:
            try:
                loop.call_soon_threadsafe(event.set)
            except RuntimeError:
                pass  # reader's loop closed: nobody is waiting

    # -- consumers -------------------------------------------------------

    def chunk(self, cursor: int) -> dict:
        """Everything past `cursor` + terminal state, msgpack/pickle
        safe (the `stream_next` reply payload)."""
        with self._cond:
            if cursor > self.consumed:
                self.consumed = min(cursor, len(self.tokens))
            return {"tokens": list(self.tokens[cursor:]),
                    "cursor": len(self.tokens),
                    "done": self.done,
                    "error": self.error}

    def wait(self, cursor: int, timeout: float) -> dict:
        """Blocking read: park until there is anything past `cursor` or
        the channel finished; empty non-done chunk on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self.tokens) <= cursor and not self.done:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
        return self.chunk(cursor)

    async def wait_async(self, cursor: int, timeout: float) -> dict:
        """Asyncio read: same contract as wait(), parked on the caller's
        event loop (the leader's stream_next actor method — other actor
        coroutines keep interleaving while this one is parked)."""
        import asyncio

        deadline = time.monotonic() + timeout
        while True:
            with self._cond:
                if len(self.tokens) > cursor or self.done:
                    return self.chunk(cursor)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self.chunk(cursor)
                event = asyncio.Event()
                self._waiters.append((asyncio.get_running_loop(), event))
            try:
                await asyncio.wait_for(event.wait(), remaining)
            except asyncio.TimeoutError:
                return self.chunk(cursor)


# ---------------------------------------------------------------------------
# SSE wire framing (proxy writer + test/bench readers speak this)
# ---------------------------------------------------------------------------

SSE_CONTENT_TYPE = "text/event-stream"


def meta_chunk(seq_id: str, **meta) -> dict:
    """The stream's FIRST chunk: no tokens, just admission metadata
    (session_cached, prefix_hashes, ...) the client contract needs
    before any token arrives — shaped like a token chunk so SSE framing
    and cursor handling are uniform."""
    return {"meta": {"seq": seq_id, **meta},
            "tokens": [], "cursor": 0, "done": False}


def sse_event(data: dict, event: str | None = None) -> bytes:
    """One Server-Sent Event frame: optional `event:` line + one
    JSON-encoded `data:` line + blank-line terminator."""
    head = f"event: {event}\n" if event else ""
    return (head + f"data: {json.dumps(data)}\n\n").encode()


def iter_sse_lines(line_iter):
    """Parse an SSE byte-line stream into (event, data_dict) pairs —
    the client half of sse_event, shared by tests and the bench so TTFT
    is measured on real frames."""
    event = None
    for raw in line_iter:
        line = raw.decode() if isinstance(raw, (bytes, bytearray)) else raw
        line = line.rstrip("\r\n")
        if not line:
            event = None
            continue
        if line.startswith("event:"):
            event = line[6:].strip()
        elif line.startswith("data:"):
            yield event, json.loads(line[5:].strip())
