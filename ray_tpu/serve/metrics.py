"""Serve production-tier counters (admission control, replica groups,
zero-copy payload plane). Registered in whichever process hosts the
component (proxy/driver routers, the controller actor's worker, replica
workers); they flow into the PR 6 metrics history via the normal
worker/driver stats push, so shed RATE and restart counts are graphable
from `ray-tpu top` / `cluster_metrics(history=N)` without touching any
hot path."""

from __future__ import annotations

from ray_tpu._private import stats as _stats

M_SHED_TOTAL = _stats.Count(
    "serve.shed_total",
    "requests refused at router admission (queue depth >= "
    "max_queued_requests) with a typed ServeOverloadedError / HTTP 503")

M_ADMITTED_TOTAL = _stats.Count(
    "serve.admitted_total",
    "requests accepted into a bounded router queue (pairs with "
    "serve.shed_total: shed rate = shed / (shed + admitted))")

M_ROUTER_QUEUED = _stats.Gauge(
    "serve.router_queued",
    "live queued queries across this process's routers (the admission "
    "gauge shed/cancel paths must keep honest)")

M_GROUP_RESTARTS_TOTAL = _stats.Count(
    "serve.group_restarts_total",
    "sharded replica-group gang restarts (any member death restarts the "
    "whole gang)")

M_ZERO_COPY_BYTES_TOTAL = _stats.Count(
    "serve.zero_copy_bytes_total",
    "request/response body bytes that rode plasma + the bulk channel as "
    "ObjectRefs instead of being pickled through the router")
