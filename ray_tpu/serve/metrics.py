"""Serve production-tier counters (admission control, replica groups,
zero-copy payload plane). Registered in whichever process hosts the
component (proxy/driver routers, the controller actor's worker, replica
workers); they flow into the PR 6 metrics history via the normal
worker/driver stats push, so shed RATE and restart counts are graphable
from `ray-tpu top` / `cluster_metrics(history=N)` without touching any
hot path."""

from __future__ import annotations

from ray_tpu._private import stats as _stats

M_SHED_TOTAL = _stats.Count(
    "serve.shed_total",
    "requests refused at router admission (queue depth >= "
    "max_queued_requests) with a typed ServeOverloadedError / HTTP 503")

M_ADMITTED_TOTAL = _stats.Count(
    "serve.admitted_total",
    "requests accepted into a bounded router queue (pairs with "
    "serve.shed_total: shed rate = shed / (shed + admitted))")

M_ROUTER_QUEUED = _stats.Gauge(
    "serve.router_queued",
    "live queued queries across this process's routers (the admission "
    "gauge shed/cancel paths must keep honest)")

M_GROUP_RESTARTS_TOTAL = _stats.Count(
    "serve.group_restarts_total",
    "sharded replica-group gang restarts (any member death restarts the "
    "whole gang)")

M_ZERO_COPY_BYTES_TOTAL = _stats.Count(
    "serve.zero_copy_bytes_total",
    "request/response body bytes that rode plasma + the bulk channel as "
    "ObjectRefs instead of being pickled through the router")

# -- streaming inference tier (continuous batching / paged KV-cache) -----

M_TOKENS_TOTAL = _stats.Count(
    "serve.tokens_total",
    "tokens emitted by decode engines in this process (the streaming "
    "tier's goodput counter; tokens/s = delta over the metrics history)")

M_TTFT_S = _stats.Histogram(
    "serve.ttft_s", _stats.LATENCY_BOUNDARIES_S,
    "sequence admission -> first emitted token (engine side): the "
    "latency continuous batching decouples from total generation time")

M_DECODE_BATCH = _stats.Gauge(
    "serve.decode_batch_size",
    "running sequences in this process's decode engine batch (occupancy "
    "of the token-level scheduler; waiting sequences are not counted)")

M_DECODE_STEP_S = _stats.Histogram(
    "serve.decode_step_s", _stats.LATENCY_BOUNDARIES_S,
    "one decode step: batch assembly + (gang fan-out +) forward + "
    "allreduce + token append/emit (the stall doctor's decode stage)")

M_KV_PAGES = _stats.Gauge(
    "serve.kv_pages_in_use",
    "allocated KV-cache pages across this process's page pools (moves "
    "with every alloc/free; sequence finish/abort must return it)")

M_SESSIONS_EVICTED_TOTAL = _stats.Count(
    "serve.sessions_evicted_total",
    "session KV-cache entries evicted (LRU past session_cache_max): the "
    "evicted session's next turn opens COLD — stream_open reports "
    "session_cached=false and the client must resend full history")

# -- KV-cache economy (cross-session prefix sharing, ROADMAP item 4) ------

M_PREFIX_HITS = _stats.Count(
    "serve.prefix_hits_total",
    "admissions that adopted a nonempty page-aligned prefix from the "
    "per-replica PrefixIndex (the shared prefill was NOT recomputed)")

M_PREFIX_SAVED = _stats.Count(
    "serve.prefix_prefill_tokens_saved_total",
    "prompt tokens whose prefill was skipped by prefix adoption (an "
    "N-session shared prefix pays prefill once: this grows by "
    "(N-1) x page-aligned prefix length)")

M_KV_PAGES_SHARED = _stats.Gauge(
    "serve.kv_pages_shared",
    "KV pages with refcount > 1 (held by several sequences/sessions "
    "and/or the prefix index at once): the HBM the economy is saving")

M_ROUTER_SESSIONS_PRUNED = _stats.Count(
    "serve.router_sessions_pruned_total",
    "router sticky-session entries dropped: LRU past the bounded table "
    "cap, or pruned by engine eviction feedback in the stream meta (a "
    "pruned session re-routes by prefix index / least-loaded)")

M_KV_WARM_PAGES = _stats.Count(
    "serve.kv_warm_pages_total",
    "prefix pages a fresh replica imported from a sibling over the bulk "
    "channel at scale-up (prefill compute NOT recomputed on the new "
    "replica)")
