"""serve public API (reference: python/ray/serve/api.py — serve.start
:533, Client.create_endpoint :186, create_backend :330, get_handle)."""

from __future__ import annotations

import cloudpickle

import ray_tpu
from ray_tpu.serve.config import BackendConfig
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.http_proxy import HTTPProxy
from ray_tpu.serve.router import ServeHandle

_client = None


class Client:
    def __init__(self, controller, proxies=None,
                 http_port: int | None = None):
        self._controller = controller
        self._proxies = list(proxies or [])
        self._http_port = http_port
        self._handles: dict[str, ServeHandle] = {}

    @property
    def _proxy(self):  # back-compat single-proxy view
        return self._proxies[0] if self._proxies else None

    # -- backends --------------------------------------------------------

    def create_backend(self, name: str, func_or_class, *init_args,
                       config: BackendConfig | dict | None = None):
        cfg = config or BackendConfig()
        if isinstance(cfg, BackendConfig):
            cfg = cfg.to_dict()
        else:
            cfg = BackendConfig.from_dict(cfg).to_dict()
        ray_tpu.get(self._controller.create_backend.remote(
            name, cloudpickle.dumps(func_or_class), tuple(init_args), cfg),
            timeout=120)

    def delete_backend(self, name: str):
        ray_tpu.get(self._controller.delete_backend.remote(name), timeout=60)

    def update_backend_config(self, name: str,
                              config: BackendConfig | dict):
        if isinstance(config, BackendConfig):
            config = config.to_dict()
        ray_tpu.get(self._controller.update_backend_config.remote(
            name, dict(config)), timeout=120)

    def get_backend_config(self, name: str) -> BackendConfig:
        return BackendConfig.from_dict(ray_tpu.get(
            self._controller.get_backend_config.remote(name), timeout=60))

    def list_backends(self) -> list[str]:
        return ray_tpu.get(self._controller.list_backends.remote(),
                           timeout=60)

    # -- endpoints -------------------------------------------------------

    def create_endpoint(self, name: str, *, backend: str,
                        route: str | None = None,
                        methods: list[str] | None = None):
        ray_tpu.get(self._controller.create_endpoint.remote(
            name, backend, route, methods), timeout=60)

    def delete_endpoint(self, name: str):
        ray_tpu.get(self._controller.delete_endpoint.remote(name),
                    timeout=60)

    def set_traffic(self, endpoint: str, traffic: dict):
        """Split an endpoint's traffic across backends by weight —
        the canary/rollout primitive (reference: serve/api.py
        set_traffic). Weights normalize: {"v1": 0.9, "v2": 0.1}."""
        ray_tpu.get(self._controller.set_traffic.remote(
            endpoint, dict(traffic)), timeout=60)

    def shadow_traffic(self, endpoint: str, backend: str,
                       proportion: float):
        """Mirror `proportion` of the endpoint's requests to `backend`,
        dropping results (reference: serve/api.py shadow_traffic);
        proportion=0 stops shadowing."""
        ray_tpu.get(self._controller.shadow_traffic.remote(
            endpoint, backend, proportion), timeout=60)

    def list_endpoints(self) -> dict:
        return ray_tpu.get(self._controller.list_endpoints.remote(),
                           timeout=60)

    def get_handle(self, endpoint: str) -> ServeHandle:
        if endpoint not in self._handles:
            self._handles[endpoint] = ServeHandle(self._controller, endpoint)
        return self._handles[endpoint]

    # -- http ------------------------------------------------------------

    def enable_http(self, host: str = "127.0.0.1", port: int = 0,
                    http_workers: int | None = None) -> int:
        """Start the HTTP proxy actors after the fact; returns the port."""
        if not self._proxies:
            self._proxies, self._http_port = _start_proxies(
                self._controller, host, port, http_workers)
        return self._http_port

    @property
    def http_port(self) -> int | None:
        return self._http_port

    def shutdown(self):
        global _client
        for handle in self._handles.values():
            handle._router.close()
        self._handles.clear()
        try:  # stop the autoscale tick before the hard kill
            ray_tpu.get(self._controller.stop.remote(), timeout=2)
        except Exception:
            pass
        for actor in self._proxies + [self._controller]:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        if _client is self:
            _client = None


def _start_proxies(controller, host: str, port: int,
                   http_workers: int | None) -> tuple[list, int]:
    """N HTTP proxy processes sharing one port via SO_REUSEPORT — the
    kernel load-balances accepts, so qps scales past a single event
    loop's per-request ceiling (one pure-python loop tops out around
    1k qps; the reference leans on uvicorn's C hot path + one proxy
    per node instead).

    Default is ONE proxy: each proxy runs its own Router with its own
    in-flight accounting, so N proxies overcommit a backend's
    max_concurrent_queries cap up to N-fold — scaling out is an explicit
    choice (http_workers=N), not a surprise."""
    import socket

    n = http_workers or 1
    if n > 1 and port == 0:
        # reserve a concrete port all workers can share: a bound (not
        # listening) SO_REUSEPORT socket holds the number while the
        # proxies bind, and never receives connections
        holder = socket.socket()
        holder.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        holder.bind((host, 0))
        port = holder.getsockname()[1]
    else:
        holder = None
    proxies = []
    try:
        proxy_cls = ray_tpu.remote(HTTPProxy)
        for _ in range(n):
            # append as we go (not a comprehension): if the k-th remote()
            # raises, the k-1 already-spawned proxies must be killable
            proxies.append(proxy_cls.remote(controller, host, port,
                                            reuse_port=(n > 1)))
        actual = ray_tpu.get([p.port.remote() for p in proxies],
                             timeout=60)
    except Exception:
        # a proxy failed to bind (port in use) or never came up: kill the
        # ones already spawned so nothing is leaked — the caller never
        # learns their handles (ADVICE.md: orphaned HTTPProxy actors)
        for p in proxies:
            try:
                ray_tpu.kill(p)
            except Exception:
                pass
        raise
    finally:
        if holder is not None:
            holder.close()
    return proxies, actual[0]


def start(*, http: bool = False, http_host: str = "127.0.0.1",
          http_port: int = 0, http_workers: int | None = None,
          detached: bool = False) -> Client:
    """Start (or connect to) a serve instance (reference: api.py:533)."""
    global _client
    if _client is not None:
        if http and not _client._proxies:
            _client.enable_http(http_host, http_port, http_workers)
        return _client
    controller_cls = ray_tpu.remote(ServeController)
    controller = controller_cls.remote()
    proxies = []
    port = None
    if http:
        try:
            proxies, port = _start_proxies(controller, http_host,
                                           http_port, http_workers)
        except Exception:
            # _start_proxies already killed its proxies; without this
            # the controller would outlive the failed start() as an
            # orphan no caller holds a handle to
            try:
                ray_tpu.kill(controller)
            except Exception:
                pass
            raise
    _client = Client(controller, proxies, port)
    return _client


def connect() -> Client:
    if _client is None:
        raise RuntimeError("serve has not been started in this process")
    return _client


def shutdown():
    if _client is not None:
        _client.shutdown()
