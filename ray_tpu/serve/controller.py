"""ServeController actor (reference: python/ray/serve/controller.py:34 +
backend_state.py reconciliation): owns the desired state — backends,
endpoints, replica sets — and reconciles actual replica actors toward it.

Routers/proxies stay in sync via LONG-POLL (reference: serve/long_poll.py:26
LongPollHost): `listen_for_change(version)` is an async actor method that
parks until the config version advances and then returns one full snapshot
— zero controller RPCs on the request path. Queue-depth autoscaling
(reference: autoscaling_policy.py:137) piggybacks on the same traffic:
routers report queue lengths with each poll cycle and the controller
resizes replica sets toward target_queued per replica."""

from __future__ import annotations

import math
import time

import ray_tpu
from ray_tpu.serve.config import BackendConfig
from ray_tpu.serve.replica import Replica


class ServeController:
    # Autoscaling clock: router reports drive reactive scaling, the tick
    # drives idle convergence (a deployment with NO router traffic — or
    # no router at all, handle-only — must still drift to min_replicas).
    AUTOSCALE_TICK_S = 0.5
    # A queue report older than this reads as 0: a router that died (or
    # an endpoint whose traffic stopped reaching any router) must not
    # pin replicas up with its last non-zero report forever.
    QUEUE_REPORT_TTL_S = 10.0

    def __init__(self):
        import threading

        # name -> {"config": dict, "pickled": bytes, "init_args": tuple,
        #          "replicas": [handle]}
        self.backends: dict[str, dict] = {}
        # name -> {"backend": str, "route": str|None, "methods": [str]}
        self.endpoints: dict[str, dict] = {}
        self.version = 0
        # endpoint -> (latest reported router queue length, monotonic ts)
        self._queue_lens: dict[str, tuple[float, float]] = {}
        self._last_downscale_ok: dict[str, float] = {}
        self._last_autoscale = 0.0
        # serializes tick-thread autoscaling against report-triggered
        # autoscaling on the actor's dispatcher thread
        self._autoscale_lock = threading.Lock()
        self._stopped = False
        # Long-poll parking: listeners wait on this event (on the actor's
        # async loop); sync mutators fire it thread-safely via the loop.
        self._change_event = None
        self._loop = None
        threading.Thread(target=self._autoscale_loop,
                         name="serve-autoscale", daemon=True).start()

    def _autoscale_loop(self):
        """The control-loop clock (reference: controller.py run_control_loop):
        without it, _maybe_autoscale only ran when router traffic reports
        arrived, so an idle deployment never scaled down to min_replicas
        and a handle-only deployment never autoscaled at all."""
        import logging

        logger = logging.getLogger("ray_tpu.serve.controller")
        while not self._stopped:
            time.sleep(self.AUTOSCALE_TICK_S)
            try:
                self._maybe_autoscale()
            except Exception:
                logger.exception("autoscale tick failed")

    def stop(self):
        """Stop the autoscale tick thread (called by Client.shutdown
        before the actor is killed; also the teardown for in-process
        controllers in tests)."""
        self._stopped = True
        return True

    def __ray_debug_state__(self) -> dict:
        """Live-state hook (debug_state.py): desired vs actual replica
        sets and the router queue reports driving the autoscaler —
        plain dict reads under the GIL, safe from any thread."""
        now = time.monotonic()
        return {
            "kind": "serve-controller",
            "version": self.version,
            "backends": {
                name: {"replicas": len(rec["replicas"]),
                       "target": rec["config"].get("num_replicas"),
                       "autoscaling":
                           bool(rec["config"].get("autoscaling"))}
                for name, rec in list(self.backends.items())},
            "endpoints": {
                name: {"route": ep.get("route"),
                       "traffic": dict(ep["traffic"])}
                for name, ep in list(self.endpoints.items())},
            "queue_reports": {
                ep: {"queued": q, "report_age_s": round(now - ts, 3)}
                for ep, (q, ts) in list(self._queue_lens.items())},
        }

    def _notify_change(self):
        """Wake parked listen_for_change calls; safe from any thread."""
        loop = self._loop
        if loop is None:
            return

        def _fire():
            import asyncio

            ev = self._change_event
            self._change_event = asyncio.Event()
            if ev is not None:
                ev.set()

        try:
            loop.call_soon_threadsafe(_fire)
        except RuntimeError:
            pass

    # -- backends --------------------------------------------------------

    def create_backend(self, name: str, pickled_callable: bytes,
                       init_args: tuple, config: dict):
        if name in self.backends:
            raise ValueError(f"backend {name!r} already exists")
        cfg = BackendConfig.from_dict(config)
        # _autoscale_lock: the tick thread walks backends/replicas;
        # structural mutations must not interleave with its _reconcile
        with self._autoscale_lock:
            self.backends[name] = {
                "config": cfg.to_dict(),
                "pickled": pickled_callable,
                "init_args": init_args,
                "replicas": [],
            }
            self._reconcile(name)
        self.version += 1
        self._notify_change()
        return True

    def delete_backend(self, name: str):
        used_by = [ep for ep, rec in self.endpoints.items()
                   if name in rec["traffic"] or name in rec["shadow"]]
        if used_by:
            # Reference semantics: a backend can't vanish under a live
            # endpoint — routers would keep dispatching to dead replicas.
            raise ValueError(
                f"backend {name!r} is used by endpoint(s) {used_by}; "
                f"delete them first")
        with self._autoscale_lock:
            # under the lock: a tick-thread _reconcile appending a fresh
            # replica to a just-popped rec would orphan that actor
            rec = self.backends.pop(name, None)
            if rec is None:
                return False
            for handle in rec["replicas"]:
                try:
                    ray_tpu.kill(handle)
                except Exception:
                    pass
        self.version += 1
        self._notify_change()
        return True

    def update_backend_config(self, name: str, config: dict):
        with self._autoscale_lock:
            rec = self._backend(name)
            merged = {**rec["config"], **config}
            rec["config"] = BackendConfig.from_dict(merged).to_dict()
            self._reconcile(name)
            replicas = list(rec["replicas"])
        if rec["config"].get("user_config") is not None:
            # reconfigure outside the lock: a 60s replica get must not
            # stall the autoscale tick
            refs = [r.reconfigure.remote(rec["config"]["user_config"])
                    for r in replicas]
            ray_tpu.get(refs, timeout=60)
        self.version += 1
        self._notify_change()
        return True

    def get_backend_config(self, name: str) -> dict:
        return dict(self._backend(name)["config"])

    def list_backends(self) -> list[str]:
        return list(self.backends)

    def _backend(self, name: str) -> dict:
        if name not in self.backends:
            raise ValueError(f"no backend {name!r}")
        return self.backends[name]

    def _reconcile(self, name: str):
        rec = self._backend(name)
        want = rec["config"]["num_replicas"]
        replicas = rec["replicas"]
        replica_cls = ray_tpu.remote(Replica)
        while len(replicas) < want:
            replicas.append(replica_cls.remote(
                rec["pickled"], rec["init_args"],
                rec["config"].get("user_config")))
        while len(replicas) > want:
            handle = replicas.pop()
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass

    # -- endpoints -------------------------------------------------------

    def create_endpoint(self, name: str, backend: str,
                        route: str | None = None,
                        methods: list[str] | None = None):
        self._backend(backend)
        self.endpoints[name] = {
            "backend": backend,  # primary (back-compat/introspection)
            "traffic": {backend: 1.0},
            "shadow": {},
            "route": route,
            "methods": [m.upper() for m in (methods or ["GET"])],
        }
        self.version += 1
        self._notify_change()
        return True

    def set_traffic(self, endpoint: str, traffic: dict):
        """Weighted split across backends (reference: serve/api.py
        set_traffic — the canary/rollout primitive). Weights normalize;
        every named backend must exist."""
        ep = self._endpoint(endpoint)
        if not traffic:
            raise ValueError("traffic dict must not be empty")
        total = 0.0
        for backend, weight in traffic.items():
            self._backend(backend)
            w = float(weight)
            if w < 0:
                raise ValueError(f"negative weight for {backend!r}")
            total += w
        if total <= 0:
            raise ValueError("traffic weights sum to zero")
        ep["traffic"] = {b: float(w) / total for b, w in traffic.items()
                        if float(w) > 0}
        ep["backend"] = max(ep["traffic"], key=ep["traffic"].get)
        self.version += 1
        self._notify_change()
        return True

    def shadow_traffic(self, endpoint: str, backend: str,
                       proportion: float):
        """Mirror a fraction of requests to `backend`, results dropped
        (reference: serve/api.py shadow_traffic). proportion=0 stops."""
        ep = self._endpoint(endpoint)
        proportion = float(proportion)
        if not 0.0 <= proportion <= 1.0:
            raise ValueError("proportion must be in [0, 1]")
        if proportion == 0.0:
            ep["shadow"].pop(backend, None)
        else:
            self._backend(backend)
            ep["shadow"][backend] = proportion
        self.version += 1
        self._notify_change()
        return True

    def _endpoint(self, name: str) -> dict:
        if name not in self.endpoints:
            raise ValueError(f"no endpoint {name!r}")
        return self.endpoints[name]

    def delete_endpoint(self, name: str):
        out = self.endpoints.pop(name, None) is not None
        self.version += 1
        self._notify_change()
        return out

    def list_endpoints(self) -> dict:
        return {k: {kk: vv for kk, vv in v.items()}
                for k, v in self.endpoints.items()}

    # -- router/proxy state sync ----------------------------------------

    def get_version(self) -> int:
        return self.version

    def get_routing_state(self, endpoint: str) -> dict:
        """Everything a router needs to drive one endpoint: the traffic
        split plus per-backend config/replicas."""
        ep = self._endpoint(endpoint)
        involved = set(ep["traffic"]) | set(ep["shadow"])
        return {
            "version": self.version,
            "backend": ep["backend"],
            "traffic": dict(ep["traffic"]),
            "shadow": dict(ep["shadow"]),
            "backends": {
                b: {"config": dict(self._backend(b)["config"]),
                    "replicas": list(self._backend(b)["replicas"])}
                for b in involved
            },
        }

    # -- long poll (reference: serve/long_poll.py:26) --------------------

    def _snapshot(self) -> dict:
        return {
            "version": self.version,
            "routes": {
                ep["route"]: {"endpoint": name, "methods": ep["methods"]}
                for name, ep in self.endpoints.items() if ep.get("route")
            },
            "endpoints": {name: self.get_routing_state(name)
                          for name in self.endpoints},
        }

    async def listen_for_change(self, cur_version: int,
                                timeout_s: float = 10.0):
        """Park until the config version advances past cur_version, then
        return a full snapshot; None on timeout (client just re-polls).
        Async actor method: concurrent listeners interleave on the actor's
        event loop while sync mutators keep running on the dispatcher and
        wake them via _notify_change — true parking, no poll loop."""
        import asyncio

        if self._loop is None:
            self._loop = asyncio.get_running_loop()
            self._change_event = asyncio.Event()
        deadline = time.monotonic() + timeout_s
        while self.version == cur_version:
            ev = self._change_event
            if self.version != cur_version:  # re-check after grabbing ev
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                return None
        return self._snapshot()

    # -- autoscaling (reference: autoscaling_policy.py:137) --------------

    def report_queue_len(self, endpoint: str, queued: int):
        """Routers report their queue depth each poll cycle; reports
        drive reactive scaling, the periodic tick (_autoscale_loop)
        drives idle convergence."""
        self._queue_lens[endpoint] = (float(queued), time.monotonic())
        self._maybe_autoscale()
        return True

    def _maybe_autoscale(self):
        with self._autoscale_lock:
            self._maybe_autoscale_locked()

    def _maybe_autoscale_locked(self):
        now = time.monotonic()
        if now - self._last_autoscale < 0.5:
            return
        self._last_autoscale = now
        for name, rec in list(self.backends.items()):
            auto = rec["config"].get("autoscaling")
            if not auto:
                continue
            queued = sum(
                q * (self.endpoints[ep]["traffic"].get(name, 0.0)
                     + self.endpoints[ep]["shadow"].get(name, 0.0))
                for ep, (q, ts) in self._queue_lens.items()
                if ep in self.endpoints
                and now - ts < self.QUEUE_REPORT_TTL_S)
            cur = len(rec["replicas"])
            target = auto.get("target_queued", 2.0) or 2.0
            desired = max(auto.get("min_replicas", 1),
                          min(auto.get("max_replicas", 4),
                              max(1, math.ceil(queued / target))))
            if desired > cur:
                self._resize(name, desired)
                self._last_downscale_ok[name] = (
                    now + auto.get("downscale_delay_s", 5.0))
            elif desired < cur:
                # Hold-down: only shrink after the backlog has stayed low
                # past the delay window (reference smooths the same way).
                if now >= self._last_downscale_ok.get(name, 0.0):
                    self._resize(name, desired)

    def _resize(self, name: str, n: int):
        rec = self._backend(name)
        rec["config"]["num_replicas"] = n
        self._reconcile(name)
        self.version += 1
        self._notify_change()
