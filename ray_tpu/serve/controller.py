"""ServeController actor (reference: python/ray/serve/controller.py:34 +
backend_state.py reconciliation): owns the desired state — backends,
endpoints, replica sets — and reconciles actual replica actors toward it.
Config versions let routers/proxies poll-refresh (the long_poll.py idea)."""

from __future__ import annotations

import ray_tpu
from ray_tpu.serve.config import BackendConfig
from ray_tpu.serve.replica import Replica


class ServeController:
    def __init__(self):
        # name -> {"config": dict, "pickled": bytes, "init_args": tuple,
        #          "replicas": [handle]}
        self.backends: dict[str, dict] = {}
        # name -> {"backend": str, "route": str|None, "methods": [str]}
        self.endpoints: dict[str, dict] = {}
        self.version = 0

    # -- backends --------------------------------------------------------

    def create_backend(self, name: str, pickled_callable: bytes,
                       init_args: tuple, config: dict):
        if name in self.backends:
            raise ValueError(f"backend {name!r} already exists")
        cfg = BackendConfig.from_dict(config)
        self.backends[name] = {
            "config": cfg.to_dict(),
            "pickled": pickled_callable,
            "init_args": init_args,
            "replicas": [],
        }
        self._reconcile(name)
        self.version += 1
        return True

    def delete_backend(self, name: str):
        used_by = [ep for ep, rec in self.endpoints.items()
                   if rec["backend"] == name]
        if used_by:
            # Reference semantics: a backend can't vanish under a live
            # endpoint — routers would keep dispatching to dead replicas.
            raise ValueError(
                f"backend {name!r} is used by endpoint(s) {used_by}; "
                f"delete them first")
        rec = self.backends.pop(name, None)
        if rec is None:
            return False
        for handle in rec["replicas"]:
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
        self.version += 1
        return True

    def update_backend_config(self, name: str, config: dict):
        rec = self._backend(name)
        merged = {**rec["config"], **config}
        rec["config"] = BackendConfig.from_dict(merged).to_dict()
        self._reconcile(name)
        if rec["config"].get("user_config") is not None:
            refs = [r.reconfigure.remote(rec["config"]["user_config"])
                    for r in rec["replicas"]]
            ray_tpu.get(refs, timeout=60)
        self.version += 1
        return True

    def get_backend_config(self, name: str) -> dict:
        return dict(self._backend(name)["config"])

    def list_backends(self) -> list[str]:
        return list(self.backends)

    def _backend(self, name: str) -> dict:
        if name not in self.backends:
            raise ValueError(f"no backend {name!r}")
        return self.backends[name]

    def _reconcile(self, name: str):
        rec = self._backend(name)
        want = rec["config"]["num_replicas"]
        replicas = rec["replicas"]
        replica_cls = ray_tpu.remote(Replica)
        while len(replicas) < want:
            replicas.append(replica_cls.remote(
                rec["pickled"], rec["init_args"],
                rec["config"].get("user_config")))
        while len(replicas) > want:
            handle = replicas.pop()
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass

    # -- endpoints -------------------------------------------------------

    def create_endpoint(self, name: str, backend: str,
                        route: str | None = None,
                        methods: list[str] | None = None):
        self._backend(backend)
        self.endpoints[name] = {
            "backend": backend,
            "route": route,
            "methods": [m.upper() for m in (methods or ["GET"])],
        }
        self.version += 1
        return True

    def delete_endpoint(self, name: str):
        out = self.endpoints.pop(name, None) is not None
        self.version += 1
        return out

    def list_endpoints(self) -> dict:
        return {k: {kk: vv for kk, vv in v.items()}
                for k, v in self.endpoints.items()}

    # -- router/proxy state sync ----------------------------------------

    def get_version(self) -> int:
        return self.version

    def get_routing_state(self, endpoint: str) -> dict:
        """Everything a router needs to drive one endpoint."""
        ep = self.endpoints.get(endpoint)
        if ep is None:
            raise ValueError(f"no endpoint {endpoint!r}")
        rec = self._backend(ep["backend"])
        return {
            "version": self.version,
            "backend": ep["backend"],
            "config": dict(rec["config"]),
            "replicas": list(rec["replicas"]),
        }
