"""ServeController actor (reference: python/ray/serve/controller.py:34 +
backend_state.py reconciliation): owns the desired state — backends,
endpoints, replica sets — and reconciles actual replica actors toward it.

Routers/proxies stay in sync via LONG-POLL (reference: serve/long_poll.py:26
LongPollHost): `listen_for_change(version)` is an async actor method that
parks until the config version advances and then returns one full snapshot
— zero controller RPCs on the request path. Queue-depth autoscaling
(reference: autoscaling_policy.py:137) piggybacks on the same traffic:
routers report queue lengths with each poll cycle and the controller
resizes replica sets toward target_queued per replica."""

from __future__ import annotations

import math
import time

import ray_tpu
from ray_tpu.serve.config import BackendConfig
from ray_tpu.serve.metrics import M_GROUP_RESTARTS_TOTAL
from ray_tpu.serve.replica import Replica
from ray_tpu.serve.replica_group import (kill_replica_group,
                                         spawn_replica_group)


class ServeController:
    # Autoscaling clock: router reports drive reactive scaling, the tick
    # drives idle convergence (a deployment with NO router traffic — or
    # no router at all, handle-only — must still drift to min_replicas).
    AUTOSCALE_TICK_S = 0.5
    # A queue report older than this reads as 0: a router that died (or
    # an endpoint whose traffic stopped reaching any router) must not
    # pin replicas up with its last non-zero report forever.
    QUEUE_REPORT_TTL_S = 10.0
    # KV-pressure poll cadence for streaming autoscaled backends (the
    # engine_state gets run OUTSIDE the autoscale lock on the tick
    # thread; a stale sample past 3x this is ignored).
    KV_POLL_TTL_S = 2.0

    def __init__(self):
        import threading

        # name -> {"config": dict, "pickled": bytes, "init_args": tuple,
        #          "replicas": [handle]}
        self.backends: dict[str, dict] = {}
        # name -> {"backend": str, "route": str|None, "methods": [str]}
        self.endpoints: dict[str, dict] = {}
        self.version = 0
        # endpoint -> (latest reported router queue length, monotonic ts)
        self._queue_lens: dict[str, tuple[float, float]] = {}
        self._gang_restarts = 0
        # backend -> {"in_use", "pages_total", "replicas", "ts", "ring"}
        # sampled KV-page pressure for KV-aware autoscaling
        self._kv_stats: dict[str, dict] = {}
        self._last_downscale_ok: dict[str, float] = {}
        self._last_autoscale = 0.0
        # serializes tick-thread autoscaling against report-triggered
        # autoscaling on the actor's dispatcher thread
        self._autoscale_lock = threading.Lock()
        self._stopped = False
        # Long-poll parking: listeners wait on this event (on the actor's
        # async loop); sync mutators fire it thread-safely via the loop.
        self._change_event = None
        self._loop = None
        threading.Thread(target=self._autoscale_loop,
                         name="serve-autoscale", daemon=True).start()

    def _autoscale_loop(self):
        """The control-loop clock (reference: controller.py run_control_loop):
        without it, _maybe_autoscale only ran when router traffic reports
        arrived, so an idle deployment never scaled down to min_replicas
        and a handle-only deployment never autoscaled at all. The same
        tick drives replica-GROUP health: a gang with any DEAD member is
        torn down and respawned whole (gang restart)."""
        import logging

        logger = logging.getLogger("ray_tpu.serve.controller")
        while not self._stopped:
            time.sleep(self.AUTOSCALE_TICK_S)
            try:
                # poll BEFORE taking the autoscale lock: a slow replica
                # get must not freeze resizes / gang restarts
                self._refresh_kv_stats()
            except Exception:
                logger.exception("kv-pressure poll failed")
            try:
                self._maybe_autoscale()
            except Exception:
                logger.exception("autoscale tick failed")
            try:
                self._check_gangs()
            except Exception:
                logger.exception("gang health tick failed")

    def stop(self):
        """Stop the autoscale tick thread (called by Client.shutdown
        before the actor is killed; also the teardown for in-process
        controllers in tests)."""
        self._stopped = True
        return True

    def __ray_debug_state__(self) -> dict:
        """Live-state hook (debug_state.py): desired vs actual replica
        sets and the router queue reports driving the autoscaler —
        plain dict reads under the GIL, safe from any thread."""
        now = time.monotonic()
        return {
            "kind": "serve-controller",
            "version": self.version,
            "gang_restarts": self._gang_restarts,
            "backends": {
                name: {"replicas": len(rec["replicas"]),
                       "target": rec["config"].get("num_replicas"),
                       "num_shards": rec["config"].get("num_shards", 1),
                       "gangs": [
                           {"gang_id": g["gang_id"],
                            "group": g["group_name"],
                            "age_s": round(time.time() - g["spawned_at"],
                                           1)}
                           for g in rec.get("gangs") or []],
                       "autoscaling":
                           bool(rec["config"].get("autoscaling"))}
                for name, rec in list(self.backends.items())},
            "endpoints": {
                name: {"route": ep.get("route"),
                       "traffic": dict(ep["traffic"])}
                for name, ep in list(self.endpoints.items())},
            "queue_reports": {
                ep: {"queued": q, "report_age_s": round(now - ts, 3)}
                for ep, (q, ts) in list(self._queue_lens.items())},
            "kv_pressure": {
                name: {"pages_in_use": st["in_use"],
                       "pages_total": st["pages_total"],
                       "sample_age_s": round(now - st["ts"], 3)}
                for name, st in list(self._kv_stats.items())},
        }

    def _notify_change(self):
        """Wake parked listen_for_change calls; safe from any thread."""
        loop = self._loop
        if loop is None:
            return

        def _fire():
            import asyncio

            ev = self._change_event
            self._change_event = asyncio.Event()
            if ev is not None:
                ev.set()

        try:
            loop.call_soon_threadsafe(_fire)
        except RuntimeError:
            pass

    # -- backends --------------------------------------------------------

    def create_backend(self, name: str, pickled_callable: bytes,
                       init_args: tuple, config: dict):
        if name in self.backends:
            raise ValueError(f"backend {name!r} already exists")
        cfg = BackendConfig.from_dict(config)
        # _autoscale_lock: the tick thread walks backends/replicas;
        # structural mutations must not interleave with its _reconcile
        with self._autoscale_lock:
            self.backends[name] = {
                "config": cfg.to_dict(),
                "pickled": pickled_callable,
                "init_args": init_args,
                "replicas": [],
            }
            try:
                self._reconcile(name)
            except BaseException:
                # failed bootstrap (e.g. a gang whose callable has no
                # shard protocol, or an unplaceable reservation) must
                # not leave a half-registered backend behind — NOR the
                # gangs/replicas reconcile already spawned before the
                # failing one (they'd be untracked and leak forever)
                rec = self.backends.pop(name, None)
                if rec is not None:
                    for gang in rec.get("gangs") or []:
                        kill_replica_group(gang)
                    for handle in rec.get("replicas") or []:
                        try:
                            ray_tpu.kill(handle)
                        except Exception:
                            pass
                raise
        self.version += 1
        self._notify_change()
        return True

    def delete_backend(self, name: str):
        used_by = [ep for ep, rec in self.endpoints.items()
                   if name in rec["traffic"] or name in rec["shadow"]]
        if used_by:
            # Reference semantics: a backend can't vanish under a live
            # endpoint — routers would keep dispatching to dead replicas.
            raise ValueError(
                f"backend {name!r} is used by endpoint(s) {used_by}; "
                f"delete them first")
        with self._autoscale_lock:
            # under the lock: a tick-thread _reconcile appending a fresh
            # replica to a just-popped rec would orphan that actor
            rec = self.backends.pop(name, None)
            if rec is None:
                return False
            if rec.get("gangs"):
                for gang in rec["gangs"]:
                    kill_replica_group(gang)
            else:
                for handle in rec["replicas"]:
                    try:
                        ray_tpu.kill(handle)
                    except Exception:
                        pass
        self.version += 1
        self._notify_change()
        return True

    def update_backend_config(self, name: str, config: dict):
        with self._autoscale_lock:
            rec = self._backend(name)
            old_shards = rec["config"].get("num_shards", 1)
            merged = {**rec["config"], **config}
            merged_cfg = BackendConfig.from_dict(merged).to_dict()
            if merged_cfg.get("num_shards", 1) != old_shards:
                raise ValueError(
                    f"num_shards of a live backend cannot change "
                    f"({old_shards} -> {merged_cfg.get('num_shards')}); "
                    f"deploy a new backend and shift traffic instead")
            if bool(merged_cfg.get("streaming")) != bool(
                    rec["config"].get("streaming")):
                raise ValueError(
                    "streaming of a live backend cannot change (live "
                    "replicas' decode engines are not reconfigurable); "
                    "deploy a new backend and shift traffic instead")
            rec["config"] = merged_cfg
            self._reconcile(name)
            # gangs: reconfigure reaches every member, not just leaders
            replicas = ([m for g in rec["gangs"] for m in g["members"]]
                        if rec.get("gangs") else list(rec["replicas"]))
        if rec["config"].get("user_config") is not None:
            # reconfigure outside the lock: a 60s replica get must not
            # stall the autoscale tick
            refs = [r.reconfigure.remote(rec["config"]["user_config"])
                    for r in replicas]
            ray_tpu.get(refs, timeout=60)
        self.version += 1
        self._notify_change()
        return True

    def get_backend_config(self, name: str) -> dict:
        return dict(self._backend(name)["config"])

    def list_backends(self) -> list[str]:
        return list(self.backends)

    def _backend(self, name: str) -> dict:
        if name not in self.backends:
            raise ValueError(f"no backend {name!r}")
        return self.backends[name]

    def _reconcile(self, name: str):
        rec = self._backend(name)
        want = rec["config"]["num_replicas"]
        replicas = rec["replicas"]
        if rec["config"].get("num_shards", 1) > 1:
            # Sharded backend: each "replica" is a GANG; rec["gangs"][i]
            # is the gang whose leader is rec["replicas"][i].
            gangs = rec.setdefault("gangs", [])
            while len(gangs) < want:
                gang = spawn_replica_group(
                    name, rec["pickled"], rec["init_args"], rec["config"])
                gangs.append(gang)
                replicas.append(gang["leader"])
            while len(gangs) > want:
                gang = gangs.pop()
                replicas.pop()
                kill_replica_group(gang)
            return
        replica_cls = ray_tpu.remote(Replica)
        while len(replicas) < want:
            replicas.append(replica_cls.remote(
                rec["pickled"], rec["init_args"],
                rec["config"].get("user_config"),
                rec["config"].get("large_payload_threshold") or 0,
                {**rec["config"], "_backend_name": name}))
        while len(replicas) > want:
            handle = replicas.pop()
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass

    # -- replica-group (gang) health -------------------------------------

    def _check_gangs(self):
        """One health pass: any gang member DEAD in the GCS actor table
        => gang-restart the whole group (kill survivors, fresh pg-backed
        gang + collective group, swap the leader handle, bump version so
        routers cut over). In-flight requests against the old gang get
        typed ReplicaGroupDied (leader alive: starved allreduce; leader
        dead: ActorDiedError mapped by the router).

        Locking: ONLY the gang-table reads/mutations hold
        _autoscale_lock. The liveness RPCs and the (possibly tens of
        seconds) respawn run outside it — a stuck placement must not
        freeze create/delete/update_backend, the autoscaler, or the
        routers' 30s controller gets (same rule as the reconfigure path
        above)."""
        from ray_tpu._private import global_state

        cw = global_state.get_core_worker()
        if cw is None:
            return
        # Elastic membership: a member sitting on a DRAINING node is as
        # restart-worthy as a dead one — the node is leaving, and the
        # fresh gang's ICI_RING placement re-snakes the torus around the
        # hole (masked coords) while the old gang still answers. One
        # cluster-view read per pass, not per gang.
        try:
            draining = {n["node_id"]
                        for n in cw.cluster_info()["nodes"]
                        if n.get("state") not in (None, "ALIVE")}
        except Exception:
            draining = set()
        now = time.monotonic()
        with self._autoscale_lock:
            candidates = [
                (name, rec, gang)
                for name, rec in list(self.backends.items())
                for gang in (rec.get("gangs") or [])
                if not gang.get("restarting")
                and gang.get("restart_backoff_until", 0.0) <= now]
        for name, rec, gang in candidates:
            if not self._gang_is_dead(cw, gang, draining):
                continue
            with self._autoscale_lock:
                gangs = rec.get("gangs") or []
                if (self.backends.get(name) is not rec
                        or gang not in gangs or gang.get("restarting")):
                    continue  # deleted/resized under us
                gang["restarting"] = True
                i = gangs.index(gang)
            self._restart_gang(name, rec, i, gang)

    @staticmethod
    def _gang_is_dead(cw, gang: dict, draining_nodes: set = frozenset()) -> bool:
        for member in gang["members"]:
            try:
                info = cw.get_actor_info(member._actor_id.binary())
            except Exception:
                return False  # GCS unreachable: don't thrash
            if info is None or info.get("state") == "DEAD":
                return True
            if info.get("node_id") in draining_nodes:
                # planned departure: restart proactively, inside the
                # drain window, instead of waiting for the member to die
                return True
        return False

    def _restart_gang(self, name: str, rec: dict, i: int, gang: dict):
        """Drain-then-kill gang restart (called WITHOUT the autoscale
        lock; `gang["restarting"]` was claimed under it). Followers die
        NOW (no caller ever dispatches to them); the LEADER is left
        alive long enough for its in-flight collective forwards to
        starve into typed ReplicaGroupDied within the group timeout —
        killing it immediately would downgrade every in-flight caller's
        error to a bare ActorDiedError. A timer reaps the drained leader
        (and the old gang's reservation) after the timeout + grace; the
        fresh gang takes over the routing slot once it spawns. A failed
        respawn (cluster temporarily short on resources) leaves the
        slot's dead gang in place — callers keep getting typed errors —
        and retries with backoff WITHOUT re-draining or re-counting."""
        import logging
        import threading

        import ray_tpu as _rt
        from ray_tpu.util.placement_group import remove_placement_group

        logger = logging.getLogger("ray_tpu.serve.controller")
        if not gang.get("drain_started"):
            # one-shot side effects, however many respawn retries follow
            gang["drain_started"] = True
            logger.warning(
                "backend %r gang %s lost a member; gang-restarting",
                name, gang["gang_id"])
            for member in gang["members"][1:]:
                try:
                    _rt.kill(member)
                except Exception:
                    pass
            leader, pg = gang["leader"], gang["pg"]
            grace = float(rec["config"].get("shard_group_timeout_s")
                          or 10.0) + 2.0

            def _reap():
                try:
                    _rt.kill(leader)
                except Exception:
                    pass
                try:
                    remove_placement_group(pg)
                except Exception:
                    pass

            timer = threading.Timer(grace, _reap)
            timer.daemon = True
            timer.start()
        try:
            fresh = spawn_replica_group(name, rec["pickled"],
                                        rec["init_args"], rec["config"])
        except BaseException:
            logger.exception(
                "backend %r gang %s respawn failed; retrying with "
                "backoff", name, gang["gang_id"])
            gang["restart_backoff_until"] = time.monotonic() + 5.0
            gang["restarting"] = False
            return
        with self._autoscale_lock:
            gangs = rec.get("gangs") or []
            if (self.backends.get(name) is not rec
                    or i >= len(gangs) or gangs[i] is not gang):
                # backend deleted or resized mid-respawn: the slot is
                # gone — don't leak the fresh gang into nowhere
                kill_replica_group(fresh)
                return
            gangs[i] = fresh
            rec["replicas"][i] = fresh["leader"]
            self._gang_restarts += 1
        M_GROUP_RESTARTS_TOTAL.inc()
        self.version += 1
        self._notify_change()

    def get_gang_members(self, name: str) -> list:
        """Member handles of every gang of a sharded backend (ordered
        rank 0..N-1 per gang) — the test/chaos surface for arming
        member-local failpoints and picking victims."""
        rec = self._backend(name)
        return [list(g["members"]) for g in rec.get("gangs") or []]

    # -- endpoints -------------------------------------------------------

    def create_endpoint(self, name: str, backend: str,
                        route: str | None = None,
                        methods: list[str] | None = None):
        self._backend(backend)
        self.endpoints[name] = {
            "backend": backend,  # primary (back-compat/introspection)
            "traffic": {backend: 1.0},
            "shadow": {},
            "route": route,
            "methods": [m.upper() for m in (methods or ["GET"])],
        }
        self.version += 1
        self._notify_change()
        return True

    def set_traffic(self, endpoint: str, traffic: dict):
        """Weighted split across backends (reference: serve/api.py
        set_traffic — the canary/rollout primitive). Weights normalize;
        every named backend must exist."""
        ep = self._endpoint(endpoint)
        if not traffic:
            raise ValueError("traffic dict must not be empty")
        total = 0.0
        for backend, weight in traffic.items():
            self._backend(backend)
            w = float(weight)
            if w < 0:
                raise ValueError(f"negative weight for {backend!r}")
            total += w
        if total <= 0:
            raise ValueError("traffic weights sum to zero")
        live = [b for b, w in traffic.items() if float(w) > 0]
        self._check_streaming_uniform(live + list(ep["shadow"]))
        ep["traffic"] = {b: float(w) / total for b, w in traffic.items()
                        if float(w) > 0}
        ep["backend"] = max(ep["traffic"], key=ep["traffic"].get)
        self.version += 1
        self._notify_change()
        return True

    def shadow_traffic(self, endpoint: str, backend: str,
                       proportion: float):
        """Mirror a fraction of requests to `backend`, results dropped
        (reference: serve/api.py shadow_traffic). proportion=0 stops."""
        ep = self._endpoint(endpoint)
        proportion = float(proportion)
        if not 0.0 <= proportion <= 1.0:
            raise ValueError("proportion must be in [0, 1]")
        if proportion == 0.0:
            ep["shadow"].pop(backend, None)
        else:
            self._backend(backend)
            self._check_streaming_uniform(list(ep["traffic"]) + [backend])
            ep["shadow"][backend] = proportion
        self.version += 1
        self._notify_change()
        return True

    def _check_streaming_uniform(self, backends: list):
        """An endpoint's backends must agree on `streaming`: the proxy
        picks its dispatch style (SSE/stream vs request/response) per
        ENDPOINT while the router picks a backend per REQUEST by
        weight, so a mixed split would hard-500 whichever arm loses
        the primary flag. Canary between two streaming backends (or
        two request-level ones) instead."""
        flags = {b: bool(self._backend(b)["config"].get("streaming"))
                 for b in backends}
        if len(set(flags.values())) > 1:
            raise ValueError(
                f"cannot split/shadow an endpoint across streaming AND "
                f"request-level backends: {flags}; deploy the "
                f"replacement with the same serving mode")

    def _endpoint(self, name: str) -> dict:
        if name not in self.endpoints:
            raise ValueError(f"no endpoint {name!r}")
        return self.endpoints[name]

    def delete_endpoint(self, name: str):
        out = self.endpoints.pop(name, None) is not None
        self.version += 1
        self._notify_change()
        return out

    def list_endpoints(self) -> dict:
        return {k: {kk: vv for kk, vv in v.items()}
                for k, v in self.endpoints.items()}

    # -- router/proxy state sync ----------------------------------------

    def get_version(self) -> int:
        return self.version

    def get_routing_state(self, endpoint: str) -> dict:
        """Everything a router needs to drive one endpoint: the traffic
        split plus per-backend config/replicas."""
        ep = self._endpoint(endpoint)
        involved = set(ep["traffic"]) | set(ep["shadow"])
        return {
            "version": self.version,
            "backend": ep["backend"],
            "traffic": dict(ep["traffic"]),
            "shadow": dict(ep["shadow"]),
            "backends": {
                b: {"config": dict(self._backend(b)["config"]),
                    "replicas": list(self._backend(b)["replicas"])}
                for b in involved
            },
        }

    # -- long poll (reference: serve/long_poll.py:26) --------------------

    def _snapshot(self) -> dict:
        return {
            "version": self.version,
            "routes": {
                ep["route"]: {"endpoint": name, "methods": ep["methods"]}
                for name, ep in self.endpoints.items() if ep.get("route")
            },
            "endpoints": {name: self.get_routing_state(name)
                          for name in self.endpoints},
        }

    async def listen_for_change(self, cur_version: int,
                                timeout_s: float = 10.0):
        """Park until the config version advances past cur_version, then
        return a full snapshot; None on timeout (client just re-polls).
        Async actor method: concurrent listeners interleave on the actor's
        event loop while sync mutators keep running on the dispatcher and
        wake them via _notify_change — true parking, no poll loop."""
        import asyncio

        if self._loop is None:
            self._loop = asyncio.get_running_loop()
            self._change_event = asyncio.Event()
        deadline = time.monotonic() + timeout_s
        while self.version == cur_version:
            ev = self._change_event
            if self.version != cur_version:  # re-check after grabbing ev
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                return None
        return self._snapshot()

    # -- autoscaling (reference: autoscaling_policy.py:137) --------------

    def report_queue_len(self, endpoint: str, queued: int):
        """Routers report their queue depth each poll cycle; reports
        drive reactive scaling, the periodic tick (_autoscale_loop)
        drives idle convergence."""
        self._queue_lens[endpoint] = (float(queued), time.monotonic())
        self._maybe_autoscale()
        return True

    def _maybe_autoscale(self):
        with self._autoscale_lock:
            self._maybe_autoscale_locked()

    def _maybe_autoscale_locked(self):
        now = time.monotonic()
        if now - self._last_autoscale < 0.5:
            return
        self._last_autoscale = now
        for name, rec in list(self.backends.items()):
            auto = rec["config"].get("autoscaling")
            if not auto:
                continue
            queued = sum(
                q * (self.endpoints[ep]["traffic"].get(name, 0.0)
                     + self.endpoints[ep]["shadow"].get(name, 0.0))
                for ep, (q, ts) in self._queue_lens.items()
                if ep in self.endpoints
                and now - ts < self.QUEUE_REPORT_TTL_S)
            cur = len(rec["replicas"])
            target = auto.get("target_queued", 2.0) or 2.0
            # two pressure signals, take the max: queue depth (reactive,
            # router-reported) and predicted KV-page occupancy
            # (streaming backends: prefill load materializes as pages
            # long before queues back up)
            want = max(1, math.ceil(queued / target))
            kv_want = self._kv_desired(name, auto)
            desired = max(auto.get("min_replicas", 1),
                          min(auto.get("max_replicas", 4),
                              max(want, kv_want)))
            if desired > cur:
                self._resize(name, desired)
                self._last_downscale_ok[name] = (
                    now + auto.get("downscale_delay_s", 5.0))
            elif desired < cur:
                # Hold-down: only shrink after the backlog has stayed low
                # past the delay window (reference smooths the same way).
                if now >= self._last_downscale_ok.get(name, 0.0):
                    self._resize(name, desired)

    def _refresh_kv_stats(self):
        """Sample KV-page pressure from streaming autoscaled backends
        (engine_state gets, OUTSIDE the autoscale lock). Keeps a short
        per-backend ring of (ts, pages_in_use) — the same series the
        metrics history graphs as `serve.kv_pages_in_use`, sampled here
        per backend because the history aggregates per process."""
        now = time.monotonic()
        for name, rec in list(self.backends.items()):
            cfg = rec["config"]
            if not (cfg.get("streaming") and cfg.get("autoscaling")):
                continue
            st = self._kv_stats.get(name)
            if st is not None and now - st["ts"] < self.KV_POLL_TTL_S:
                continue
            replicas = list(rec["replicas"])
            if not replicas:
                continue
            try:
                states = ray_tpu.get(
                    [r.engine_state.remote() for r in replicas],
                    timeout=5)
            except Exception:
                continue
            in_use = total = 0
            for es in states:
                kv = (es or {}).get("kv") or {}
                in_use += int(kv.get("pages_in_use") or 0)
                total += int(kv.get("pages_total") or 0)
            now = time.monotonic()
            ring = list(st["ring"]) if st is not None else []
            ring.append((now, float(in_use)))
            ring = [s for s in ring if now - s[0] < 60.0][-32:]
            self._kv_stats[name] = {
                "in_use": in_use, "pages_total": total,
                "replicas": len(replicas), "ts": now, "ring": ring}

    def _kv_desired(self, name: str, auto: dict) -> int:
        """Replicas needed so PREDICTED KV occupancy stays under
        kv_target_util per pool: linear extrapolation of the sampled
        pages_in_use series kv_horizon_s ahead. 0 = no opinion (stale
        sample, KV scaling disabled, or not a streaming backend)."""
        util = float(auto.get("kv_target_util", 0.8) or 0.0)
        if util <= 0:
            return 0
        st = self._kv_stats.get(name)
        now = time.monotonic()
        if (st is None or not st["pages_total"]
                or now - st["ts"] > 3 * self.KV_POLL_TTL_S):
            return 0
        predicted = float(st["in_use"])
        horizon = float(auto.get("kv_horizon_s", 10.0) or 0.0)
        ring = st["ring"]
        if horizon > 0 and len(ring) >= 2:
            (t0, v0), (t1, v1) = ring[0], ring[-1]
            if t1 > t0:
                predicted = max(0.0, v1 + (v1 - v0) / (t1 - t0) * horizon)
        per_replica = st["pages_total"] / max(1, st["replicas"])
        return math.ceil(predicted / max(1.0, per_replica * util))

    def _resize(self, name: str, n: int):
        rec = self._backend(name)
        before = list(rec["replicas"])
        rec["config"]["num_replicas"] = n
        self._reconcile(name)
        fresh = [r for r in rec["replicas"] if r not in before]
        cfg = rec["config"]
        if (fresh and before and cfg.get("streaming")
                and cfg.get("num_shards", 1) == 1
                and int(cfg.get("kv_warm_pages") or 0) > 0):
            # warm the newcomers' prefix caches from a sibling over the
            # bulk channel — advisory, off the control path
            import threading

            threading.Thread(
                target=self._warm_replicas, daemon=True,
                name=f"serve-kv-warm-{name}",
                args=(name, before, fresh,
                      int(cfg.get("kv_warm_pages") or 0))).start()
        self.version += 1
        self._notify_change()

    def _warm_replicas(self, name: str, donors: list, fresh: list,
                       max_pages: int):
        """Scale-up cache warming: one donor exports its hottest prefix
        pages to plasma, each new replica imports them (pull rides the
        bulk channel donor -> importer; the controller only relays the
        ~100-byte ref marker). Gangs never warm — members must replay
        the driver's op stream, so imports are refused replica-side."""
        import logging

        logger = logging.getLogger("ray_tpu.serve.controller")
        try:
            payload = None
            for donor in donors:
                try:
                    payload = ray_tpu.get(
                        donor.export_prefix_pages.remote(max_pages),
                        timeout=15)
                except Exception:
                    continue
                if payload and payload.get("pages"):
                    break
                payload = None
            if payload is None:
                return
            for r in fresh:
                try:
                    # nested ref: rehydrates on the importer WITHOUT
                    # resolution — import_prefix_pages pulls it there
                    ray_tpu.get(r.import_prefix_pages.remote(
                        {"ref": payload["ref"]}), timeout=30)
                except Exception:
                    logger.debug("kv warm import failed for %s", name,
                                 exc_info=True)
        except Exception:
            logger.debug("kv warm pass failed for %s", name,
                         exc_info=True)
