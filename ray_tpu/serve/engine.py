"""Token-level continuous-batching decode engine (ROADMAP item 1, the
Orca/vLLM iteration-level scheduler sized for this runtime).

The Megatron gang forward (SNIPPETS [3]) becomes one *step* of a decode
loop instead of the whole request: each loop iteration the engine
(running inside the PR 10 gang LEADER, or inside a plain replica for
unsharded deployments) assembles a `StepPlan` — sequences to abort,
new sequences to admit from the bounded waiting queue, and the running
batch — fans the plan to the follower ranks (one actor call per
follower per step; actor-call ordering from the single engine thread
keeps every rank's op stream aligned), and every rank applies it
identically: prefill-embed the admitted prompts into its shard of the
paged KV-cache, gather each running sequence's cache sum, compute the
shard-partial logits, allreduce(SUM) over the gang's collective group,
argmax the next token, append its KV entry, and retire sequences that
hit EOS or max_tokens. Only the leader additionally EMITS tokens into
per-sequence `TokenChannel`s — time-to-first-token is one step after
admission, decoupled from total generation length, and finished short
sequences retire (and free their pages) while long ones keep decoding.

Determinism: every rank sees the same plan, the same allreduced logits
and therefore makes the same finish/eviction/exhaustion decisions, so
follower mirrors never need a second protocol round. Client aborts —
the only non-deterministic event — always travel in the plan.

Failure domain: a member death mid-step starves the allreduce; the
leader maps the timeout to typed `ReplicaGroupDied`, finishes EVERY
open channel with it, frees all KV pages, and marks the engine dead
(the controller's gang restart brings a fresh engine). Session state
dies with the gang — affinity routing falls back to least-loaded.

Chaos seams: `serve.decode_step` (every rank, top of each applied
step), `serve.stream_emit` (leader emit), `serve.kv_page_alloc`
(page allocation).
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict

import numpy as np

from ray_tpu._private import failpoints as _fp
from ray_tpu.serve.kv_cache import (KVCacheExhausted, PagedKVCache,
                                    prefix_block_hashes)
from ray_tpu.serve.metrics import (M_DECODE_BATCH, M_DECODE_STEP_S,
                                   M_KV_WARM_PAGES,
                                   M_SESSIONS_EVICTED_TOTAL,
                                   M_TOKENS_TOTAL, M_TTFT_S)
from ray_tpu.serve.streaming import TokenChannel

# finished channels are kept this long for late/reconnecting readers,
# then reaped by the decode loop
CHANNEL_TTL_S = 60.0

_SESSION_PREFIX = "sess:"


# ---------------------------------------------------------------------------
# reference streaming model (the generative sibling of ShardedMLP)
# ---------------------------------------------------------------------------


class ShardedTokenLM:
    """Integer-weight autoregressive reference model whose per-token KV
    entry is a Megatron-partitioned MLP activation.

    next_logits = relu(sum_t u_t) @ W_out,  u_t = relu(E[tok_t] @ W_up)

    W_up is COLUMN-sharded and W_out ROW-sharded (parallel.sharding
    kv_slice bounds), so each rank's cached u_t slice is shard-local —
    the per-shard KV page slices of the paged cache — and one
    allreduce(SUM) per step recovers the full logits. With
    integer-valued f32 weights every partial product and running sum is
    exactly representable: the sharded continuous-batching decode is
    BIT-exact vs this class's own single-process `generate`, whatever
    the batch composition (the A/B test's pin).
    """

    def __init__(self, embed, w_up, w_out, eos_token: int = 0):
        self.embed = np.asarray(embed, dtype=np.float32)
        self.w_up = np.asarray(w_up, dtype=np.float32)
        self.w_out = np.asarray(w_out, dtype=np.float32)
        self.eos_token = int(eos_token)
        self.vocab = self.embed.shape[0]
        self._shard = None

    @classmethod
    def make(cls, seed: int, vocab: int = 32, hidden: int = 8,
             inner: int = 16, eos_token: int = 0) -> "ShardedTokenLM":
        """Deterministic integer-weight instance (tests/bench)."""
        rng = np.random.default_rng(seed)
        return cls(rng.integers(-2, 3, (vocab, hidden)),
                   rng.integers(-2, 3, (hidden, inner)),
                   rng.integers(-2, 3, (inner, vocab)),
                   eos_token=eos_token)

    def shard(self, rank: int, num_shards: int) -> "ShardedTokenLM":
        from ray_tpu.parallel.sharding import kv_slice

        # one slice bound drives BOTH weights and the cache width, so
        # the KV pages this rank writes are exactly the columns its
        # up-projection produces (per-shard KV page slices)
        lo, hi = kv_slice(self.w_up.shape[-1], rank, num_shards)
        self.w_up = self.w_up[:, lo:hi]
        self.w_out = self.w_out[lo:hi]
        self._shard = (rank, num_shards)
        return self

    @property
    def kv_width(self) -> int:
        """Per-rank KV vector width (this shard's slice of the inner
        dim — the paged cache's row width)."""
        return self.w_up.shape[-1]

    def embed_tokens(self, tokens) -> np.ndarray:
        """KV entries for `tokens`: (T, kv_width) shard-local slices."""
        toks = np.asarray(tokens, dtype=np.int64) % self.vocab
        return np.maximum(self.embed[toks] @ self.w_up, 0.0)

    def partial_logits(self, sums) -> np.ndarray:
        """(B, kv_width) cache sums -> (B, vocab) PARTIAL logits the
        gang allreduces (unsharded: already the full logits)."""
        return np.maximum(np.asarray(sums, dtype=np.float32), 0.0) \
            @ self.w_out

    @staticmethod
    def next_tokens(logits) -> np.ndarray:
        """Greedy decode, ties to the lowest index — deterministic
        across batch compositions and rank counts."""
        return np.argmax(np.asarray(logits), axis=-1)

    def generate(self, prompt, max_tokens: int) -> list[int]:
        """Single-process full-generation reference (and the
        request-level serving arm via __call__): the exact loop the
        engine runs, without paging or batching."""
        u = self.embed_tokens(list(prompt))
        total = u.sum(axis=0)
        out: list[int] = []
        for _ in range(int(max_tokens)):
            logits = self.partial_logits(total[None, :])[0]
            tok = int(np.argmax(logits))
            out.append(tok)
            if tok == self.eos_token:
                break
            total = total + self.embed_tokens([tok])[0]
        return out

    def generate_batch(self, prompts: list, max_tokens: list) -> list:
        """Request-level BATCHED decoding (the preserved A/B control
        arm): the batch is one tensor stepped in LOCKSTEP until every
        row finishes — finished short rows keep burning compute as
        padding and the batch's composition is frozen at admission,
        exactly the inefficiency iteration-level scheduling removes.
        Each row's tokens are identical to generate() (rows are
        independent), so the A/B is bit-exact either way."""
        n = len(prompts)
        totals = np.stack([self.embed_tokens(p).sum(axis=0)
                           if p else np.zeros(self.kv_width,
                                              dtype=np.float32)
                           for p in prompts])
        outs: list[list[int]] = [[] for _ in range(n)]
        done = [False] * n
        for _ in range(max(int(m) for m in max_tokens) if n else 0):
            logits = self.partial_logits(totals)  # full batch, pads too
            toks = self.next_tokens(logits)
            u = self.embed_tokens([int(t) for t in toks])
            for i in range(n):
                if done[i]:
                    continue
                tok = int(toks[i])
                outs[i].append(tok)
                if tok == self.eos_token or \
                        len(outs[i]) >= int(max_tokens[i]):
                    done[i] = True
                else:
                    totals[i] = totals[i] + u[i]
            if all(done):
                break
        return outs

    def __call__(self, requests: list):
        """Request-level serving entry: one frozen lockstep batch per
        RPC (a whole generation blocks its slot)."""
        parsed = [parse_stream_request(r) for r in requests]
        return self.generate_batch([p for p, _, _, _ in parsed],
                                   [m for _, m, _, _ in parsed])

    __call__._serve_accept_batch = True  # takes the whole batch list


def parse_stream_request(data) -> tuple[list[int], int, str | None, bool]:
    """(prompt, max_tokens, session, stream?) from a request body: a
    dict ({"prompt": [...], "max_tokens": N, "session": s,
    "stream": bool}) or a bare token list."""
    if isinstance(data, dict):
        prompt = [int(t) for t in (data.get("prompt") or [])]
        return (prompt, int(data.get("max_tokens") or 16),
                data.get("session") or None, bool(data.get("stream")))
    if data is None:
        return [], 16, None, False
    return [int(t) for t in data], 16, None, False


# ---------------------------------------------------------------------------
# sequences and step plans
# ---------------------------------------------------------------------------


class Sequence:
    __slots__ = ("seq_id", "prompt", "max_tokens", "session", "generated",
                 "channel", "submitted_at", "admitted_at", "cached_tokens",
                 "prefix_tokens", "kv_sum", "trace_id")

    def __init__(self, seq_id: str, prompt: list[int], max_tokens: int,
                 session: str | None, channel: TokenChannel | None,
                 trace_id: str | None = None):
        self.seq_id = seq_id
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.session = session
        self.generated: list[int] = []
        self.channel = channel
        self.submitted_at = time.time()
        self.admitted_at = None
        self.cached_tokens = 0  # session-cache prefix reused at admit
        self.prefix_tokens = 0  # cross-session prefix adopted at admit
        # hex trace id of the submitting request (when sampled): the
        # decode-step histogram's exemplar link back to one stream
        self.trace_id = trace_id
        # running sum of this sequence's cached KV rows, maintained
        # incrementally (one page-table gather at admission, O(width)
        # per step after — the decode loop must not re-walk T pages per
        # token). Integer-valued f32 keeps it bit-equal to gather_sum.
        self.kv_sum = None


def _plan_wire(aborts, admits, batch) -> dict:
    return {"aborts": [(s, r) for s, r in aborts],
            "admits": [{"seq": s.seq_id, "prompt": s.prompt,
                        "max_tokens": s.max_tokens, "session": s.session}
                       for s in admits],
            "batch": list(batch)}


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


class DecodeEngine:
    """Token-level continuous-batching scheduler + paged-KV executor.

    `driver=True` (leader / unsharded replica): owns the decode thread,
    the waiting queue, the token channels and the plan. `driver=False`
    (follower mirror): pure executor — `apply_plan` is called once per
    step by the leader and replays the identical state transition on
    this rank's KV shard."""

    def __init__(self, model, config: dict, backend: str,
                 allreduce=None, peers=None, driver: bool = True,
                 on_dead=None):
        self._model = model
        self._backend = backend
        self._cfg = config
        self._allreduce = allreduce or (lambda x: x)
        self._peers = list(peers or [])
        self._driver = driver
        self._on_dead = on_dead
        width = getattr(model, "kv_width", None)
        if width is None:
            raise TypeError(
                f"streaming backend {backend!r} requires a model with "
                f"the decode protocol (kv_width/embed_tokens/"
                f"partial_logits); {type(model).__name__} lacks it")
        # cross-session prefix sharing (ROADMAP item 4): page-aligned
        # prompt prefixes index into the pool's radix tree; admission
        # adopts the longest match and prefills only the tail. Every
        # gang rank makes the same tree decisions from the same plan
        # stream, so sharing stays deterministic across ranks.
        self._prefix_sharing = bool(config.get("prefix_sharing", True))
        self._kv = PagedKVCache(
            int(config.get("kv_pages_total") or 512),
            int(config.get("kv_page_size") or 16),
            int(width), name=f"kv:{backend}",
            backend=config.get("kv_backend") or "numpy",
            prefix_max_nodes=(
                int(config.get("prefix_index_max_nodes") or 256)
                if self._prefix_sharing else 0))
        self._max_batch = int(config.get("max_decode_batch") or 8)
        self._max_waiting = int(config.get("max_waiting_sequences") or 32)
        self._session_max = int(config.get("session_cache_max") or 32)
        self._retry_after = float(
            config.get("overload_retry_after_s") or 1.0)
        self._lock = threading.Lock()
        self._running: dict[str, Sequence] = {}   # insertion = batch order
        self._waiting: list[Sequence] = []
        self._pending_aborts: list[tuple[str, str]] = []
        self._channels: dict[str, TokenChannel] = {}
        # retained session caches in LRU order (least-recently-finished
        # first): adoption pops the key, retire re-appends it, so
        # eviction is popitem(last=False) — O(1) under churn instead of
        # the old O(n) min()-scan per evicted entry
        self._sessions: OrderedDict[str, float] = OrderedDict()
        self._sessions_evicted = 0
        # engine-side LRU evictions the router hasn't heard about yet:
        # drained into the next stream_open reply (stream meta) so the
        # router prunes its sticky entry instead of pinning the session
        # to a replica that no longer holds its pages
        self._evicted_feedback: list[str] = []
        self._steps = 0
        self._tokens_emitted = 0
        self._last_step_at = time.time()
        self._dead: BaseException | None = None
        self._stopped = False
        self._wake = threading.Event()
        self._thread = None
        if driver:
            self._thread = threading.Thread(
                target=self._loop, name=f"decode-{backend}", daemon=True)
            self._thread.start()

    # -- driver surface (leader / unsharded replica) ---------------------

    def submit(self, prompt: list[int], max_tokens: int,
               session: str | None = None) -> str:
        """Queue one sequence for admission at the next step boundary.
        Sheds typed when the bounded waiting queue is full; raises the
        engine's death error (typed ReplicaGroupDied) once dead."""
        from ray_tpu import exceptions as exc

        from ray_tpu._private import tracing as _tracing

        seq_id = uuid.uuid4().hex[:12]
        ch = TokenChannel(seq_id)
        trace_id = _tracing.current_id()
        with self._lock:
            if self._dead is not None:
                raise self._dead
            if self._stopped:
                raise RuntimeError(
                    f"decode engine for {self._backend!r} is stopped")
            if len(self._waiting) >= self._max_waiting:
                raise exc.ServeOverloadedError(
                    self._backend, len(self._waiting), self._max_waiting,
                    self._retry_after)
            seq = Sequence(seq_id, list(prompt), int(max_tokens),
                           session, ch, trace_id=trace_id)
            self._waiting.append(seq)
            self._channels[seq_id] = ch
        self._wake.set()
        return seq_id

    def abort(self, seq_id: str, reason: str = "aborted") -> bool:
        """Abort a sequence wherever it is. Waiting: withdrawn outright.
        Running: queued into the next plan so every rank frees the same
        pages on the same step. Unknown/finished: no-op (idempotent —
        the disconnect path races the finish path)."""
        from ray_tpu import exceptions as exc

        with self._lock:
            for i, s in enumerate(self._waiting):
                if s.seq_id == seq_id:
                    self._waiting.pop(i)
                    s.channel.finish(exc.SequenceAborted(seq_id, reason))
                    return True
            ch = self._channels.get(seq_id)
            if ch is not None and not ch.done:
                # running — or mid-admission between plan construction
                # and apply: the pending entry survives until the
                # sequence is visible in `running` (see _next_plan)
                self._pending_aborts.append((seq_id, reason))
                self._wake.set()
                return True
        return False

    def channel(self, seq_id: str) -> TokenChannel | None:
        return self._channels.get(seq_id)

    def session_info(self, session: str) -> dict:
        """Cached-session introspection (the affinity tests' truth)."""
        key = _SESSION_PREFIX + session
        return {"cached": self._kv.has(key),
                "tokens": self._kv.length(key)}

    # -- prefix economy ---------------------------------------------------

    def prefix_hashes(self, prompt: list[int]) -> list[str]:
        """Chained page-aligned prefix hashes of `prompt` — reported in
        the stream_open meta so the router can index which replica
        holds which prefixes (same function both sides: a hash computed
        here matches one computed from the same tokens anywhere)."""
        if not self._prefix_sharing:
            return []
        return prefix_block_hashes(prompt, self._kv.page_size)

    def drain_evicted_sessions(self) -> list[str]:
        """Session names LRU-evicted since the last drain (bounded at
        64) — piggybacked on stream_open replies so the router prunes
        its sticky table instead of routing to a cold cache forever."""
        with self._lock:
            out, self._evicted_feedback = self._evicted_feedback, []
        return out

    def export_prefix(self, max_pages: int = 128) -> list[dict]:
        """Hottest prefix-tree pages, parents-first (warm-start donor
        side; see PagedKVCache.export_prefix)."""
        return self._kv.export_prefix(max_pages)

    def import_prefix(self, entries: list[dict]) -> int:
        """Advisory warm import of a sibling's exported prefix pages;
        returns pages actually adopted (0 on any mismatch)."""
        n = self._kv.import_prefix(entries)
        if n:
            M_KV_WARM_PAGES.inc(n)
        return n

    # -- decode loop -----------------------------------------------------

    def _loop(self):
        import logging

        while not self._stopped and self._dead is None:
            plan = self._next_plan()
            if plan is None:
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                self._reap_channels()
                continue
            t0 = time.perf_counter()
            try:
                self._exec_step(plan)
            except BaseException as e:
                if self._stopped:
                    break
                logging.getLogger("ray_tpu.serve").exception(
                    "decode step failed; killing engine")
                self._die(e)
                break
            # measure the step BEFORE taking the engine lock: a submit
            # burst contending it must not inflate decode_step_s (the
            # stall doctor scales its decode threshold from this p99)
            step_s = time.perf_counter() - t0
            with self._lock:
                exemplar = next((s.trace_id
                                 for s in self._running.values()
                                 if s.trace_id), None)
                M_DECODE_BATCH.set(len(self._running))
                self._last_step_at = time.time()
            M_DECODE_STEP_S.observe(step_s, exemplar=exemplar)
            if self._steps % 256 == 0:
                # under sustained load the idle-path reap never runs;
                # finished channels must still age out
                self._reap_channels()

    def _next_plan(self) -> dict | None:
        with self._lock:
            aborts = [(s, r) for s, r in self._pending_aborts
                      if s in self._running]
            # keep aborts for sequences not yet visible in `running`
            # (admitted later this very step) alive for the next plan;
            # drop entries whose channel already finished
            self._pending_aborts = [
                (s, r) for s, r in self._pending_aborts
                if s not in self._running and s in self._channels
                and not self._channels[s].done]
            aborted = {s for s, _ in aborts}
            admits: list[Sequence] = []
            room = self._max_batch - (len(self._running) - len(aborted))
            while self._waiting and room > 0:
                admits.append(self._waiting.pop(0))
                room -= 1
            if not (self._running or admits or aborts):
                return None
            batch = [s for s in self._running if s not in aborted]
            batch.extend(s.seq_id for s in admits)
            return {"aborts": aborts, "admits": admits, "batch": batch,
                    "wire": _plan_wire(aborts, admits, batch)}

    def _exec_step(self, plan: dict):
        """One step: fan the plan to followers, apply locally (the
        allreduce inside meets theirs), then probe follower health the
        way handle_batch does."""
        from ray_tpu import exceptions as exc

        refs = [p.decode_step_exec.remote(plan["wire"])
                for p in self._peers]
        try:
            self._apply_locked_step(plan["aborts"], plan["admits"],
                                    plan["batch"])
        except BaseException as e:
            if not self._peers:
                raise
            # a member died or errored before its allreduce: starved
            # group -> TimeoutError within the group timeout. Name the
            # follower failure when one already surfaced.
            raise exc.ReplicaGroupDied(
                self._backend, "",
                self._peer_failure(refs) or f"{type(e).__name__}: {e}"
            ) from e
        if self._peers:
            failure = self._peer_failure(refs)
            if failure:
                # a follower completed its allreduce but failed after
                # (or its reply was lost): op streams may be skewed
                raise exc.ReplicaGroupDied(self._backend, "", failure)

    def _peer_failure(self, refs, wait_s: float = 0.0) -> str:
        import ray_tpu

        if not refs:
            return ""
        try:
            done, pending = ray_tpu.wait(refs, num_returns=len(refs),
                                         timeout=wait_s)
        except Exception as e:
            return f"{type(e).__name__}: {e}"
        for ref in done:
            try:
                ray_tpu.get(ref, timeout=1.0)
            except BaseException as e:
                return f"follower failed: {type(e).__name__}: {e}"
        return ""

    # -- step application (every rank) -----------------------------------

    def apply_plan(self, wire: dict) -> bool:
        """Follower entry (decode_step_exec): replay one step from its
        wire form. Also fires the per-rank chaos seam."""
        aborts = list(wire.get("aborts") or [])
        admits = []
        for a in wire.get("admits") or []:
            s = Sequence(a["seq"], list(a["prompt"]),
                         int(a["max_tokens"]), a.get("session"), None)
            admits.append(s)
        self._apply_locked_step(aborts, admits, list(wire["batch"]))
        with self._lock:
            self._last_step_at = time.time()
        return True

    def _apply_locked_step(self, aborts, admits, batch):
        if _fp.ARMED:
            # the chaos kill point: `exit` here is a rank dying
            # mid-decode, starving every other rank's allreduce
            _fp.fire_strict("serve.decode_step")
        self._apply_aborts(aborts)
        self._apply_admits(admits)
        self._decode(batch)
        with self._lock:
            self._steps += 1

    def _apply_aborts(self, aborts):
        from ray_tpu import exceptions as exc

        for item in aborts:
            seq_id, reason = item if isinstance(item, (tuple, list)) \
                else (item, "aborted")
            with self._lock:
                seq = self._running.pop(seq_id, None)
            self._kv.free(seq_id)
            if seq is not None and seq.channel is not None:
                seq.channel.finish(exc.SequenceAborted(seq_id, reason))

    def _apply_admits(self, admits):
        from ray_tpu import exceptions as exc

        for seq in admits:
            adopted_key = None
            try:
                if seq.session and self._kv.has(
                        _SESSION_PREFIX + seq.session):
                    # warm session: adopt the cached prefix — the
                    # affinity hit skips re-prefilling prior turns
                    key = _SESSION_PREFIX + seq.session
                    seq.cached_tokens = self._kv.adopt(key, seq.seq_id)
                    adopted_key = key
                    with self._lock:
                        self._sessions.pop(key, None)
                elif self._prefix_sharing and seq.prompt:
                    # cold path: walk the prefix tree, adopt the
                    # longest indexed page-aligned prefix (refcount
                    # bumps, no prefill) — only the tail embeds below
                    seq.prefix_tokens = self._kv.adopt_prefix(
                        seq.seq_id, seq.prompt)
                else:
                    self._kv.alloc_table(seq.seq_id)
                tail = seq.prompt[seq.prefix_tokens:] \
                    if seq.prefix_tokens else seq.prompt
                if tail:
                    self._kv.append(seq.seq_id,
                                    self._model.embed_tokens(tail))
                if self._prefix_sharing and adopted_key is None \
                        and seq.prompt:
                    # index this prompt's full pages so later
                    # admissions (any session) adopt them
                    self._kv.register_prefix(seq.seq_id, seq.prompt)
            except KVCacheExhausted:
                # admission-time exhaustion is a SHED: the sequence
                # never ran; pages written for it go back — but an
                # ADOPTED session prefix is restored intact (truncate
                # the partial prompt rows, re-key back), or a
                # "retryable" shed would silently destroy the session
                if adopted_key is not None:
                    self._kv.truncate(seq.seq_id, seq.cached_tokens)
                    self._kv.adopt(seq.seq_id, adopted_key)
                    with self._lock:
                        self._sessions[adopted_key] = time.time()
                else:
                    self._kv.free(seq.seq_id)
                if seq.channel is not None:
                    seq.channel.finish(exc.ServeOverloadedError(
                        self._backend, self._kv.pages_in_use(),
                        self._kv.num_pages, self._retry_after))
                continue
            seq.admitted_at = time.time()
            # one page-table walk per admission (covers an adopted
            # session prefix + the fresh prompt rows)
            seq.kv_sum = self._kv.gather_sum(seq.seq_id)
            with self._lock:
                self._running[seq.seq_id] = seq

    def _decode(self, batch):
        from ray_tpu import exceptions as exc

        with self._lock:
            seqs = [self._running[s] for s in batch
                    if s in self._running]
        if not seqs:
            # aborts/failed admits emptied the step: the gang still
            # meets in an allreduce so rank op streams stay aligned
            if self._peers or not self._driver:
                self._allreduce(np.zeros(1, dtype=np.float32))
            return
        sums = np.stack([s.kv_sum for s in seqs])
        partial = self._model.partial_logits(sums)
        logits = self._allreduce(np.asarray(partial, dtype=np.float32))
        toks = self._model.next_tokens(logits)
        # one embed call for the whole batch's next tokens (B python/
        # numpy round trips per step would dominate the toy-model step)
        u_all = self._model.embed_tokens([int(t) for t in toks])
        emitted = 0
        finished: list[Sequence] = []
        for i, (seq, tok) in enumerate(zip(seqs, toks)):
            tok = int(tok)
            seq.generated.append(tok)
            done = (tok == getattr(self._model, "eos_token", -1)
                    or len(seq.generated) >= seq.max_tokens)
            if not done or seq.session:
                # session-keyed finishes append the final token too, so
                # the retained cache holds the WHOLE turn for the next
                # one; anonymous finishes skip the write (freed below)
                try:
                    self._kv.append(seq.seq_id, u_all[i])
                    seq.kv_sum = seq.kv_sum + u_all[i]
                except KVCacheExhausted:
                    if not done:
                        # mid-decode exhaustion: abort THIS sequence
                        # typed, identically on every rank (same pool
                        # arithmetic everywhere)
                        with self._lock:
                            self._running.pop(seq.seq_id, None)
                        self._kv.free(seq.seq_id)
                        if seq.channel is not None:
                            seq.channel.push(tok)
                            seq.channel.finish(exc.SequenceAborted(
                                seq.seq_id, "KV page pool exhausted"))
                        continue
                    # finished anyway: retire without session retention
                    seq.session = None
            if seq.channel is not None:
                if seq.channel.first_token_at is None:
                    M_TTFT_S.observe(time.time() - seq.submitted_at,
                                     exemplar=seq.trace_id)
                seq.channel.push(tok)
                emitted += 1
            if done:
                finished.append(seq)
        if emitted:
            self._tokens_emitted += emitted
            M_TOKENS_TOTAL.inc(emitted)
        for seq in finished:
            with self._lock:
                self._running.pop(seq.seq_id, None)
            self._retire(seq)
            if seq.channel is not None:
                seq.channel.finish()

    def _retire(self, seq: Sequence):
        """Early-retire a finished sequence: session-keyed caches are
        RETAINED (LRU-bounded) for the next turn; anonymous ones free
        immediately."""
        if seq.session:
            key = _SESSION_PREFIX + seq.session
            self._kv.free(key)  # stale same-key cache, if any
            self._kv.adopt(seq.seq_id, key)
            with self._lock:
                # OrderedDict insertion order IS the LRU order (adoption
                # pops the key, retirement re-appends): eviction is an
                # O(1) popitem instead of a min() scan per victim
                self._sessions.pop(key, None)
                self._sessions[key] = time.time()
                evict = []
                while len(self._sessions) > self._session_max:
                    oldest, _ = self._sessions.popitem(last=False)
                    evict.append(oldest)
                self._sessions_evicted += len(evict)
                for victim in evict:
                    # feedback for the router: drained into the next
                    # stream_open reply so its sticky table prunes
                    # entries whose cache no longer exists
                    self._evicted_feedback.append(
                        victim[len(_SESSION_PREFIX):])
                del self._evicted_feedback[:-64]
            for victim in evict:
                self._kv.free(victim)
                M_SESSIONS_EVICTED_TOTAL.inc()
        else:
            self._kv.free(seq.seq_id)

    # -- death / shutdown -------------------------------------------------

    def _die(self, error: BaseException):
        """Terminal failure (starved allreduce = gang death): every open
        stream finishes TYPED, every KV page frees, the engine refuses
        new work with the same error. Zero leaked pages is the chaos
        invariant the conftest sweep checks."""
        with self._lock:
            self._dead = error
            running = list(self._running.values())
            waiting = list(self._waiting)
            self._running.clear()
            self._waiting.clear()
        for seq in running + waiting:
            if seq.channel is not None:
                seq.channel.finish(error)
        self._kv.free_all()
        M_DECODE_BATCH.set(0)
        if self._on_dead is not None:
            try:
                self._on_dead(error)
            except Exception:
                pass

    def close(self):
        from ray_tpu import exceptions as exc

        self._stopped = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            running = list(self._running.values())
            waiting = list(self._waiting)
            self._running.clear()
            self._waiting.clear()
        for seq in running + waiting:
            if seq.channel is not None:
                seq.channel.finish(exc.SequenceAborted(
                    seq.seq_id, "engine shutdown"))
        self._kv.close()

    def _reap_channels(self):
        now = time.time()
        with self._lock:
            stale = [s for s, ch in self._channels.items()
                     if ch.done and ch.finished_at
                     and now - ch.finished_at > CHANNEL_TTL_S]
            for s in stale:
                self._channels.pop(s, None)

    # -- introspection ----------------------------------------------------

    def debug_state(self) -> dict:
        """The decode-batch occupancy / KV / stream-backlog rows of
        `ray-tpu state serve` and the dashboard; `stall_age_s` is the
        doctor's decode-stage age (None while idle — an empty engine is
        not a wedged one)."""
        with self._lock:
            running = len(self._running)
            waiting = len(self._waiting)
            live = ([s for s in self._running]
                    + [s.seq_id for s in self._waiting]
                    + list(self._sessions))
            open_chs = [ch for ch in self._channels.values()
                        if not ch.done]
            backlog = sum(len(ch.tokens) - ch.consumed
                          for ch in self._channels.values())
            last = self._last_step_at
        return {
            "backend": self._backend,
            "decode_batch": running,
            "max_decode_batch": self._max_batch,
            "waiting": waiting,
            "steps": self._steps,
            "tokens_emitted": self._tokens_emitted,
            "open_streams": len(open_chs),
            "stream_backlog": backlog,
            "stall_age_s": (round(time.time() - last, 3)
                            if running else None),
            "sessions": {k[len(_SESSION_PREFIX):]: self._kv.length(k)
                         for k in self._sessions},
            "sessions_evicted": self._sessions_evicted,
            "kv": self._kv.debug_state(),
            "kv_leaked": self._kv.leak_report(live),
            "dead": repr(self._dead) if self._dead else "",
        }


# ---------------------------------------------------------------------------
# actor-facing host mixin (Replica and ReplicaGroupMember)
# ---------------------------------------------------------------------------


class StreamingEngineHost:
    """The stream API an engine-hosting actor exposes to routers.
    `stream_next` is ASYNC: it parks on the actor's event loop (like
    the controller's long-poll), so any number of open streams
    long-poll concurrently while sync methods keep dispatching."""

    _engine: DecodeEngine | None = None

    def _start_engine(self, model, config: dict, backend: str,
                      allreduce=None, peers=None, driver: bool = True):
        self._engine = DecodeEngine(model, config, backend,
                                    allreduce=allreduce, peers=peers,
                                    driver=driver)

    def _require_engine(self) -> DecodeEngine:
        if self._engine is None:
            raise RuntimeError(
                "this replica does not host a decode engine "
                "(deploy with BackendConfig(streaming=True))")
        return self._engine

    async def stream_open(self, data) -> dict:
        """Admit one sequence; returns its id plus `session_cached` —
        whether the session's KV prefix is warm on THIS replica
        (advisory, read at submit). A client sending only the new
        turn's delta tokens MUST check it: a cold session decodes from
        the delta alone, so the caller re-sends full history on a miss
        (eviction, restart, affinity fallback) instead of silently
        getting a different generation."""
        prompt, max_tokens, session, _ = parse_stream_request(data)
        eng = self._require_engine()
        cached = bool(session) and eng.session_info(session)["cached"]
        return {"seq": eng.submit(prompt, max_tokens, session),
                "session_cached": cached,
                # router-side prefix index feed: which page-aligned
                # prefixes this replica now holds, and which sessions
                # it LRU-evicted since the last report
                "prefix_hashes": eng.prefix_hashes(prompt),
                "evicted_sessions": eng.drain_evicted_sessions()}

    # once a stream is flowing, later chunks coalesce this long before
    # replying: one poll RPC then carries a step-burst of tokens instead
    # of one RPC per token. The FIRST chunk always returns immediately —
    # time-to-first-token never pays the coalescing window.
    STREAM_COALESCE_S = 0.05

    async def stream_next(self, seq_id: str, cursor: int,
                          wait_s: float = 2.0) -> dict:
        """Long-poll the sequence's channel past `cursor`. The reply
        embeds a terminal typed error (if any) AFTER the final tokens,
        so the router drains then re-raises."""
        import asyncio

        from ray_tpu import exceptions as exc

        eng = self._require_engine()
        ch = eng.channel(seq_id)
        if ch is None:
            return {"tokens": [], "cursor": cursor, "done": True,
                    "error": exc.SequenceAborted(
                        seq_id, "unknown sequence (reaped or never "
                        "admitted on this replica)")}
        cursor = int(cursor)
        chunk = await ch.wait_async(cursor, float(wait_s))
        if cursor > 0 and chunk["tokens"] and not chunk["done"]:
            await asyncio.sleep(self.STREAM_COALESCE_S)
            chunk = ch.chunk(cursor)
        return chunk

    async def stream_abort(self, seq_id: str,
                           reason: str = "client disconnect") -> bool:
        eng = self._engine
        return eng.abort(seq_id, reason) if eng is not None else False

    def engine_state(self) -> dict:
        """Sync introspection hook (tests, `ray-tpu state serve`)."""
        eng = self._engine
        return eng.debug_state() if eng is not None else {}

    # -- scale-up warm start (controller-driven) --------------------------

    def export_prefix_pages(self, max_pages: int = 128) -> dict:
        """Warm-start DONOR: snapshot the hottest prefix-tree pages
        into plasma and return `{"ref": ..., "pages": n}`. The ref is
        relayed by the controller as a ~100-byte marker (nested refs
        rehydrate unresolved); the importer's `get` then pulls the
        bytes donor->importer over the PR 5 bulk channel — the
        controller never touches the page data."""
        eng = self._require_engine()
        entries = eng.export_prefix(max_pages)
        if not entries:
            return {"ref": None, "pages": 0}
        import ray_tpu

        return {"ref": ray_tpu.put(entries), "pages": len(entries)}

    def import_prefix_pages(self, payload) -> int:
        """Warm-start IMPORTER (advisory): resolve a donor's export and
        seed the local prefix index so the first admissions hit warm
        pages instead of re-prefilling. Returns pages adopted; 0 on any
        mismatch, a lost donor, or a gang member — gang ranks replay
        the driver's admission stream and MUST NOT diverge in pool
        state, so only single-shard engines accept a warm import."""
        eng = self._require_engine()
        if eng._peers or not eng._driver:
            return 0
        ref = payload.get("ref") if isinstance(payload, dict) else None
        if ref is None:
            return 0
        self._hint_kv_warm(ref)
        import ray_tpu

        try:
            entries = ray_tpu.get(ref, timeout=30.0)
        except Exception:
            return 0  # donor died with the only copy: warm is advisory
        return eng.import_prefix(entries)

    @staticmethod
    def _hint_kv_warm(ref) -> None:
        """Best-effort: label the upcoming bulk pull as `kv_warm` so
        `ray-tpu state transfers` attributes the bytes to cache
        warming, not anonymous traffic."""
        try:
            from ray_tpu._private import global_state

            cw = global_state.get_core_worker()
            if cw is None:
                return
            cw._io.run(cw.raylet.call("hint_pull_purpose", {
                "object_id": ref.id().binary(),
                "purpose": "kv_warm"}))
        except Exception:
            pass
