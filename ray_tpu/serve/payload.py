"""Zero-copy large request/response bodies (ROADMAP item 1).

Bodies over a backend's `large_payload_threshold` do NOT travel through
the router pickled inside the query: the producer (HTTP proxy for
requests, replica for responses) `put`s the raw bytes into plasma and
ships a `LargePayload` marker instead. The router then moves ~100 bytes
of marker; the consumer resolves the ref on its own node, so the bytes
ride the PR 5 bulk channel (streaming zero-copy pull) exactly once,
directly producer->consumer. A replica-group leader forwards the MARKER
to its shard members, so an N-shard fan-out is N pulls of the same
plasma object, not N pickled copies.

Failure domain: the plasma object is owned by the producer process; if
it dies before the consumer resolves, `unwrap` surfaces the typed
ObjectLostError (HTTP: 503)."""

from __future__ import annotations

from ray_tpu.serve.metrics import M_ZERO_COPY_BYTES_TOTAL


class LargePayload:
    """Marker carrying a plasma ObjectRef in place of a large body."""

    __slots__ = ("ref", "nbytes")

    def __init__(self, ref, nbytes: int):
        self.ref = ref
        self.nbytes = nbytes

    def __repr__(self):
        return f"LargePayload({self.ref!r}, {self.nbytes}B)"


def wrap(body, threshold: int | None):
    """Promote `body` to a plasma-backed LargePayload when it is a bytes
    blob at or over `threshold` (None/0 = never). Anything else passes
    through unchanged."""
    if not threshold:
        return body
    if isinstance(body, (bytes, bytearray, memoryview)):
        nbytes = len(body)
    else:
        nbytes = getattr(body, "nbytes", None)  # numpy/jax arrays
        if nbytes is None:
            return body
    if nbytes < threshold:
        return body
    import ray_tpu

    ref = ray_tpu.put(bytes(body) if isinstance(
        body, (bytearray, memoryview)) else body)
    M_ZERO_COPY_BYTES_TOTAL.inc(nbytes)
    return LargePayload(ref, nbytes)


def unwrap(data, timeout: float = 30.0):
    """Resolve a LargePayload back to its bytes (one bulk-channel pull
    on first touch, node-local reads after). Typed ObjectLostError if
    the producer died with the only copy."""
    if isinstance(data, LargePayload):
        import ray_tpu

        return ray_tpu.get(data.ref, timeout=timeout)
    return data
