"""Router — batches queries and balances them over replicas (reference:
python/ray/serve/router.py:178 Router / :48 ReplicaSet; micro-batching from
backend_worker.py:33 BatchQueue lives here so one actor RPC carries a full
batch — the TPU-relevant unit of work).

Each endpoint gets a flusher thread: queries queue up to max_batch_size or
batch_wait_timeout, then fly to the least-loaded replica with a free slot
(max_concurrent_queries in-flight batches per replica). A single completion
thread polls outstanding batches to release replica slots."""

from __future__ import annotations

import threading
import time


class _PendingQuery:
    __slots__ = ("data", "event", "ref", "error", "abandoned", "loop",
                 "future")

    def __init__(self, data):
        self.data = data
        self.event = threading.Event()
        self.ref = None
        self.error = None
        self.abandoned = False
        self.loop = None    # set by assign_async: asyncio bridge
        self.future = None

    def _notify(self):
        """Dispatch outcome is ready: wake the sync waiter and, for async
        callers, resolve their future on its own event loop (the flusher
        thread can't touch asyncio state directly)."""
        self.event.set()
        if self.future is not None:
            def _done(q=self):
                if not q.future.done():
                    if q.error is not None:
                        q.future.set_exception(q.error)
                    else:
                        q.future.set_result(q.ref)
            try:
                self.loop.call_soon_threadsafe(_done)
            except RuntimeError:
                # caller's event loop already closed (proxy shutdown
                # race): nobody is waiting; the sync event is set
                pass


class Router:
    def __init__(self, controller, endpoint: str,
                 refresh_interval: float = 0.25):
        self._controller = controller
        self._endpoint = endpoint
        self._refresh_interval = refresh_interval
        self._lock = threading.Lock()
        self._queue: list[_PendingQuery] = []
        self._inflight: dict[bytes, int] = {}   # actor_id -> live batches
        self._outstanding: list[tuple[bytes, list]] = []  # (actor_id, refs)
        self._state = None
        self._state_time = 0.0
        self._closed = False
        self._wake = threading.Event()
        self._refresh()
        self._flusher = threading.Thread(target=self._flush_loop, daemon=True)
        self._flusher.start()
        self._completer = threading.Thread(target=self._completion_loop,
                                           daemon=True)
        self._completer.start()
        self._poller = threading.Thread(target=self._poll_loop, daemon=True)
        self._poller.start()

    # -- state sync ------------------------------------------------------

    def _refresh(self):
        import ray_tpu

        self._state = ray_tpu.get(
            self._controller.get_routing_state.remote(self._endpoint),
            timeout=30)
        self._state_time = time.monotonic()

    def _poll_loop(self):
        """Long-poll push of routing state (reference: long_poll.py:26) +
        queue-depth reporting for the controller's autoscaler (reference:
        autoscaling_policy.py:137). The dispatch path never talks to the
        controller."""
        import ray_tpu

        while not self._closed:
            try:
                with self._lock:
                    qlen = len(self._queue)
                ray_tpu.get(self._controller.report_queue_len.remote(
                    self._endpoint, qlen), timeout=30)
                snap = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._state["version"] if self._state else -1, 2.0),
                    timeout=30)
            except Exception:
                time.sleep(0.5)
                continue
            if snap is None:
                continue
            st = snap["endpoints"].get(self._endpoint)
            if st is not None:
                self._state = st
                self._wake.set()

    # -- client surface --------------------------------------------------

    def assign(self, data, timeout: float = 30.0):
        """Enqueue one query; block until its batch is dispatched; return
        the caller's ObjectRef slice of the batched call."""
        q = _PendingQuery(data)
        with self._lock:
            self._queue.append(q)
        self._wake.set()
        if not q.event.wait(timeout):
            # Nobody will consume the result — withdraw the query so it
            # doesn't burn a replica slot after we've given up on it.
            with self._lock:
                q.abandoned = True
                if q in self._queue:
                    self._queue.remove(q)
            raise TimeoutError(
                f"no replica accepted the query within {timeout}s")
        if q.error is not None:
            raise q.error
        return q.ref

    async def assign_async(self, data, timeout: float = 30.0):
        """assign() for asyncio callers (the HTTP proxy): enqueue and
        await dispatch WITHOUT parking a thread per request — the proxy's
        request concurrency is then bounded by the event loop, not an
        executor pool."""
        import asyncio

        q = _PendingQuery(data)
        q.loop = asyncio.get_running_loop()
        q.future = q.loop.create_future()
        with self._lock:
            self._queue.append(q)
        self._wake.set()
        try:
            return await asyncio.wait_for(asyncio.shield(q.future),
                                          timeout)
        except asyncio.TimeoutError:
            with self._lock:
                q.abandoned = True
                if q in self._queue:
                    self._queue.remove(q)
            raise TimeoutError(
                f"no replica accepted the query within {timeout}s")

    def close(self):
        self._closed = True
        self._wake.set()

    # -- flusher ---------------------------------------------------------

    @staticmethod
    def _pick_backend(state: dict) -> str | None:
        """Weighted-random backend per batch (reference: serve v1
        set_traffic — router splits by endpoint traffic policy)."""
        import random

        traffic = state.get("traffic")
        if not traffic:
            return state.get("backend")
        names = list(traffic)
        if len(names) == 1:
            return names[0]
        return random.choices(names, weights=[traffic[n] for n in names])[0]

    def _pick_replica(self, state: dict, backend: str):
        st = state["backends"].get(backend)
        if st is None:
            return None
        cap = st["config"]["max_concurrent_queries"]
        with self._lock:
            best, best_load = None, None
            for handle in st["replicas"]:
                load = self._inflight.get(handle._actor_id.binary(), 0)
                if load < cap and (best_load is None or load < best_load):
                    best, best_load = handle, load
        return best

    def _flush_loop(self):
        import logging

        while not self._closed:
            self._wake.wait(timeout=0.05)
            self._wake.clear()
            try:
                self._flush_once()
            except Exception:
                # the flusher must outlive any single bad dispatch —
                # a dead flusher turns every future assign() into a
                # timeout
                logging.getLogger("ray_tpu.serve").exception(
                    "router flush iteration failed")
                time.sleep(0.05)

    def _flush_once(self):
        import random

        while not self._closed:
            # one consistent snapshot per iteration: the poller
            # thread swaps self._state on traffic cutover, and mixing
            # two snapshots' backend maps would KeyError the flusher
            state = self._state
            with self._lock:
                if not self._queue:
                    break
            backend = self._pick_backend(state)
            if backend is None or backend not in state["backends"]:
                time.sleep(0.01)
                continue
            cfg = state["backends"][backend]["config"]
            # fill a batch (or give stragglers batch_wait_timeout)
            if cfg["max_batch_size"]:
                deadline = time.monotonic() + cfg["batch_wait_timeout"]
                while (not self._closed
                       and len(self._queue) < cfg["max_batch_size"]
                       and time.monotonic() < deadline):
                    time.sleep(0.001)
            replica = self._pick_replica(state, backend)
            if replica is None:
                # chosen backend saturated — try any other traffic
                # backend with capacity before waiting
                for other in state.get("traffic", {}):
                    if other != backend:
                        replica = self._pick_replica(state, other)
                        if replica is not None:
                            backend = other
                            cfg = state["backends"][other]["config"]
                            break
            if replica is None:
                time.sleep(0.002)
                continue
            # batch sized by the backend that will actually serve it
            max_bs = cfg["max_batch_size"] or 1
            with self._lock:
                batch = [q for q in self._queue[:max_bs]
                         if not q.abandoned]
                del self._queue[:max_bs]
            if not batch:
                continue
            self._dispatch(replica, batch)
            # shadow traffic: mirror the batch, results dropped
            # (reference: serve/api.py shadow_traffic)
            for sb, prop in (state.get("shadow") or {}).items():
                if random.random() < prop:
                    sreplica = self._pick_replica(state, sb)
                    if sreplica is not None:
                        self._dispatch(sreplica, batch, shadow=True)

    def _dispatch(self, replica, batch: list[_PendingQuery],
                  shadow: bool = False):
        key = replica._actor_id.binary()
        with self._lock:
            self._inflight[key] = self._inflight.get(key, 0) + 1
        refs: list = []
        try:
            out = replica.handle_batch.options(
                num_returns=len(batch)).remote([q.data for q in batch])
            refs = [out] if len(batch) == 1 else list(out)
            if not shadow:
                for q, ref in zip(batch, refs):
                    q.ref = ref
                    q._notify()
        except Exception as e:
            if not shadow:
                for q in batch:
                    q.error = e
                    q._notify()
        with self._lock:
            if refs:
                # shadow batches still occupy a replica slot until done
                # (backpressure), their results just go nowhere
                self._outstanding.append((key, refs))
            else:
                self._inflight[key] -= 1

    def _completion_loop(self):
        """One thread polls every outstanding batch; a finished batch frees
        its replica slot (no thread-per-batch)."""
        import ray_tpu

        while not self._closed:
            with self._lock:
                outstanding = list(self._outstanding)
            if not outstanding:
                time.sleep(0.005)
                continue
            for key, refs in outstanding:
                try:
                    _, not_done = ray_tpu.wait(
                        refs, num_returns=len(refs), timeout=0)
                except Exception:
                    not_done = []
                if not not_done:
                    with self._lock:
                        self._outstanding.remove((key, refs))
                        self._inflight[key] -= 1
                    self._wake.set()
            time.sleep(0.005)


class ServeHandle:
    """Caller-facing handle (reference: python/ray/serve/handle.py):
    handle.remote(data) -> ObjectRef; ray_tpu.get(ref) -> result."""

    def __init__(self, controller, endpoint: str):
        self._router = Router(controller, endpoint)
        self.endpoint = endpoint

    def remote(self, data=None):
        return self._router.assign(data)

    def __repr__(self):
        return f"ServeHandle({self.endpoint!r})"
