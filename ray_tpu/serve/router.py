"""Router — batches queries and balances them over replicas (reference:
python/ray/serve/router.py:178 Router / :48 ReplicaSet; micro-batching from
backend_worker.py:33 BatchQueue lives here so one actor RPC carries a full
batch — the TPU-relevant unit of work).

Each endpoint gets a flusher thread: queries queue up to max_batch_size or
batch_wait_timeout, then fly to the least-loaded replica with a free slot
(max_concurrent_queries in-flight batches per replica). Batch completion —
releasing the replica slot, and resolving result-mode queries — rides
memstore ready-callbacks fired by the task-reply path: there is no polling
thread, and a whole batch's results reach a waiting event loop in one
coalesced wakeup (rpc.loop_call_queue)."""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict

from ray_tpu._private import stats as _stats
from ray_tpu._private import tracing
from ray_tpu.serve.kv_cache import prefix_block_hashes
from ray_tpu.serve.metrics import (M_ADMITTED_TOTAL, M_ROUTER_QUEUED,
                                   M_ROUTER_SESSIONS_PRUNED, M_SHED_TOTAL)

M_ROUTER_QUEUE_S = _stats.Histogram(
    "serve.router_queue_s", _stats.LATENCY_BOUNDARIES_S,
    "query enqueue -> batch dispatch to a replica (the autoscaler's "
    "queue-delay feed, observed for every query)")

# Live routers in this process (driver handles AND proxy actors), for
# the debug_state/stall-doctor plane: queued queries with ages surface
# in `ray-tpu state` without the router knowing who is asking.
_live_routers: "weakref.WeakSet[Router]" = weakref.WeakSet()


def debug_routers() -> list[dict]:
    out = []
    for router in list(_live_routers):
        if getattr(router, "_closed", False):
            continue
        try:
            out.append(router.debug_state())
        except Exception:
            continue
    return out


def _parse_session(data):
    """(prompt, max_tokens, session, stream?) — the engine's request
    schema; the router only needs the session key for affinity."""
    from ray_tpu.serve.engine import parse_stream_request

    return parse_stream_request(data)


class _PendingQuery:
    __slots__ = ("data", "event", "ref", "error", "abandoned", "loop",
                 "future", "want_result", "trace", "t_enqueue")

    def __init__(self, data):
        self.data = data
        self.event = threading.Event()
        self.ref = None
        self.error = None
        self.abandoned = False
        self.loop = None    # set by assign_async/call_async: asyncio bridge
        self.future = None
        self.want_result = False  # call_async: resolve with the VALUE
        # the caller's ambient trace context (the HTTP proxy mints one
        # per sampled request): carried to the flusher thread so the
        # dispatched batch task joins the request's trace tree
        self.trace = tracing.current()
        self.t_enqueue = time.time()

    def _notify(self):
        """Dispatch outcome is ready: wake the sync waiter and, for async
        callers, resolve their future on its own event loop (the flusher
        thread can't touch asyncio state directly). Result-mode queries
        only land here on dispatch ERRORS — their success path resolves at
        completion with the value, with zero per-query dispatch wakeups."""
        self.event.set()
        if self.future is not None:
            from ray_tpu._private import rpc

            def _done(q=self):
                # abandoned = caller timed out and stopped awaiting; an
                # exception set now would only surface as "Future
                # exception was never retrieved" GC spam
                if not q.future.done() and not q.abandoned:
                    if q.error is not None:
                        q.future.set_exception(q.error)
                    else:
                        q.future.set_result(q.ref)
            try:
                rpc.loop_call_queue(self.loop).call(_done)
            except RuntimeError:
                # caller's event loop already closed (proxy shutdown
                # race): nobody is waiting; the sync event is set
                pass


class Router:
    def __init__(self, controller, endpoint: str,
                 refresh_interval: float = 0.25):
        self._controller = controller
        self._endpoint = endpoint
        self._refresh_interval = refresh_interval
        self._lock = threading.Lock()
        self._queue: list[_PendingQuery] = []
        self._inflight: dict[bytes, int] = {}   # actor_id -> live batches
        # streaming tier: sticky session -> replica actor key, plus live
        # open-stream accounting (streams hold an _inflight slot for
        # their whole life, not one batch). Both tables are LRU-bounded
        # OrderedDicts: insertion order is eviction order, hits refresh
        # via move_to_end, caps come from the backend config
        # (router_session_cap / router_prefix_cap).
        self._sessions: OrderedDict[str, bytes] = OrderedDict()
        # prefix-hash -> replica actor key, fed by the engine's
        # stream_open meta: new sessions route to the replica already
        # holding their longest page-aligned prefix
        self._prefixes: OrderedDict[str, bytes] = OrderedDict()
        self._streams_open = 0
        self._affinity_hits = 0
        self._affinity_misses = 0
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._sessions_pruned = 0
        self._state = None
        self._state_time = 0.0
        self._shed_total = 0
        self._admitted_total = 0
        self._closed = False
        self._wake = threading.Event()
        self._refresh()
        self._flusher = threading.Thread(target=self._flush_loop, daemon=True)
        self._flusher.start()
        self._poller = threading.Thread(target=self._poll_loop, daemon=True)
        self._poller.start()
        _live_routers.add(self)

    def debug_state(self) -> dict:
        """Msgpack-safe live snapshot: queued queries with ages (+trace
        ids), per-replica in-flight batches — the serve rows of
        `ray-tpu state` and the doctor's router_queue stage."""
        now = time.time()
        with self._lock:
            queue = list(self._queue)
            inflight = {aid.hex()[:16]: n
                        for aid, n in self._inflight.items() if n}
        maxq, _ = self._admission()
        return {
            "endpoint": self._endpoint,
            "queued": len(queue),
            "max_queued": maxq or 0,
            "shed_total": self._shed_total,
            "admitted_total": self._admitted_total,
            "streams_open": self._streams_open,
            "sessions": len(self._sessions),
            "affinity_hits": self._affinity_hits,
            "affinity_misses": self._affinity_misses,
            "prefix_index": len(self._prefixes),
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "sessions_pruned": self._sessions_pruned,
            "oldest_age_s": (round(max(now - q.t_enqueue
                                       for q in queue), 3)
                             if queue else 0.0),
            "inflight_batches": inflight,
            "queries": [{
                "endpoint": self._endpoint,
                "age_s": round(now - q.t_enqueue, 3),
                "trace_id": (q.trace.trace_id.hex()
                             if q.trace is not None else ""),
            } for q in queue[:25]],
        }

    # -- state sync ------------------------------------------------------

    def _refresh(self):
        import ray_tpu

        self._state = ray_tpu.get(
            self._controller.get_routing_state.remote(self._endpoint),
            timeout=30)
        self._state_time = time.monotonic()

    def _poll_loop(self):
        """Long-poll push of routing state (reference: long_poll.py:26) +
        queue-depth reporting for the controller's autoscaler (reference:
        autoscaling_policy.py:137). The dispatch path never talks to the
        controller."""
        import ray_tpu

        while not self._closed:
            try:
                with self._lock:
                    qlen = len(self._queue)
                ray_tpu.get(self._controller.report_queue_len.remote(
                    self._endpoint, qlen), timeout=30)
                snap = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._state["version"] if self._state else -1, 2.0),
                    timeout=30)
            except Exception:
                time.sleep(0.5)
                continue
            if snap is None:
                continue
            st = snap["endpoints"].get(self._endpoint)
            if st is not None:
                self._state = st
                self._wake.set()

    # -- admission control (load shedding / backpressure) ----------------

    def _admission(self) -> tuple[int | None, float]:
        """(max_queued_requests, retry_after_s) for this endpoint, read
        from the primary backend's config in the current routing state
        (None = unbounded)."""
        state = self._state
        if not state:
            return None, 1.0
        cfg = (state.get("backends", {})
               .get(state.get("backend"), {})
               .get("config"))
        if not cfg:
            return None, 1.0
        return (cfg.get("max_queued_requests"),
                float(cfg.get("overload_retry_after_s") or 1.0))

    def _admit(self, q: _PendingQuery) -> None:
        """Append under the bounded queue or raise the typed shed error.
        All bookkeeping the shed/cancel paths must keep honest lives
        here and in _abandon/_take_batch: the live-queue gauge moves
        with every append/remove, and a shed never touches any ref or
        memstore state (nothing was created for it)."""
        from ray_tpu import exceptions as exc

        maxq, retry_after = self._admission()
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    f"router for {self._endpoint!r} is closed")
            depth = len(self._queue)
            if maxq is not None and depth >= maxq:
                self._shed_total += 1
                shed = exc.ServeOverloadedError(
                    self._endpoint, depth, maxq, retry_after)
            else:
                self._queue.append(q)
                self._admitted_total += 1
                shed = None
        if shed is not None:
            M_SHED_TOTAL.inc()
            raise shed
        M_ADMITTED_TOTAL.inc()
        M_ROUTER_QUEUED.add(1)
        self._wake.set()

    # -- client surface --------------------------------------------------

    def assign(self, data, timeout: float = 30.0):
        """Enqueue one query; block until its batch is dispatched; return
        the caller's ObjectRef slice of the batched call."""
        q = _PendingQuery(data)
        self._admit(q)
        if not q.event.wait(timeout):
            # Nobody will consume the result — withdraw the query so it
            # doesn't burn a replica slot after we've given up on it.
            self._abandon(q)
            raise TimeoutError(
                f"no replica accepted the query within {timeout}s")
        if q.error is not None:
            raise q.error
        return q.ref

    async def assign_async(self, data, timeout: float = 30.0):
        """assign() for asyncio callers (the HTTP proxy): enqueue and
        await dispatch WITHOUT parking a thread per request — the proxy's
        request concurrency is then bounded by the event loop, not an
        executor pool."""
        import asyncio

        q = _PendingQuery(data)
        q.loop = asyncio.get_running_loop()
        q.future = q.loop.create_future()
        self._admit(q)
        try:
            return await asyncio.wait_for(asyncio.shield(q.future),
                                          timeout)
        except asyncio.TimeoutError:
            self._abandon(q)
            raise TimeoutError(
                f"no replica accepted the query within {timeout}s")
        except asyncio.CancelledError:
            self._abandon(q)  # caller task cancelled (client disconnect)
            raise

    async def call_async(self, data, timeout: float = 30.0):
        """One round trip for asyncio callers (the HTTP proxy): enqueue and
        await the RESULT VALUE directly. Versus assign_async + `await ref`
        this removes both per-request cross-thread wakeups: dispatch does
        not notify the caller at all, and the reply's deserialized values
        are delivered for the whole batch in one coalesced loop tick."""
        import asyncio

        q = _PendingQuery(data)
        q.loop = asyncio.get_running_loop()
        q.future = q.loop.create_future()
        q.want_result = True
        self._admit(q)
        try:
            return await asyncio.wait_for(asyncio.shield(q.future), timeout)
        except asyncio.TimeoutError:
            self._abandon(q)
            raise TimeoutError(
                f"request timed out after {timeout}s") from None
        except asyncio.CancelledError:
            # caller task cancelled (HTTP client disconnected mid-request):
            # same cleanup as a timeout, or the dead client's query still
            # dispatches and its orphaned future collects exception spam
            self._abandon(q)
            raise

    # -- streaming (continuous-batching backends) ------------------------

    def _pick_stream_replica(self, state: dict, backend: str,
                             session: str | None,
                             prefix_hashes: list[str] = (),
                             cfg: dict | None = None):
        """KV-aware pick, in order: (1) sticky session -> the replica
        holding that session's KV pages; (2) prefix index -> the
        replica holding the LONGEST page-aligned prefix of this prompt
        (hashes checked longest-first, so a deep match beats a shallow
        one); (3) least-loaded fallback. Sessions whose replica
        vanished (gang restart, downscale) re-stick wherever they
        land."""
        st = state["backends"].get(backend)
        if st is None or not st["replicas"]:
            return None
        cfg = cfg or {}
        session_cap = int(cfg.get("router_session_cap") or 4096)
        live = {h._actor_id.binary(): h for h in st["replicas"]}
        with self._lock:
            if session:
                want = self._sessions.get(session)
                if want is not None and want in live:
                    self._affinity_hits += 1
                    self._sessions.move_to_end(session)
                    return live[want]
            for h in reversed(prefix_hashes):
                want = self._prefixes.get(h)
                if want is not None and want in live:
                    self._prefix_hits += 1
                    self._prefixes.move_to_end(h)
                    if session:
                        self._affinity_misses += 1
                        self._stick(session, want, session_cap)
                    return live[want]
            if prefix_hashes:
                self._prefix_misses += 1
            best, best_load = None, None
            for key, handle in live.items():
                load = self._inflight.get(key, 0)
                if best_load is None or load < best_load:
                    best, best_load = handle, load
            if session and best is not None:
                self._affinity_misses += 1
                self._stick(session, best._actor_id.binary(),
                            session_cap)
        return best

    def _stick(self, session: str, key: bytes, cap: int):
        """Record session -> replica under self._lock, LRU-bounded."""
        self._sessions.pop(session, None)
        self._sessions[session] = key
        while len(self._sessions) > cap:
            self._sessions.popitem(last=False)
            self._sessions_pruned += 1
            M_ROUTER_SESSIONS_PRUNED.inc()

    def _note_stream_meta(self, key: bytes, reply: dict,
                          cfg: dict | None = None):
        """Digest a stream_open reply's routing feedback: index the
        prefix hashes this replica now holds (LRU-bounded), and prune
        sticky entries for sessions the engine LRU-evicted — without
        this the router pins a session to a replica whose cache is
        long gone."""
        cfg = cfg or {}
        prefix_cap = int(cfg.get("router_prefix_cap") or 8192)
        hashes = reply.get("prefix_hashes") or []
        evicted = reply.get("evicted_sessions") or []
        with self._lock:
            for h in hashes:
                self._prefixes.pop(h, None)
                self._prefixes[h] = key
            while len(self._prefixes) > prefix_cap:
                self._prefixes.popitem(last=False)
            for sess in evicted:
                # only unpin if still pointing at the evicting replica
                # (the session may have re-stuck elsewhere already)
                if self._sessions.get(sess) == key:
                    self._sessions.pop(sess, None)
                    self._sessions_pruned += 1
                    M_ROUTER_SESSIONS_PRUNED.inc()

    async def stream_async(self, data, timeout: float = 60.0):
        """Async generator of token chunks from a streaming backend:
        open a sequence on the affine replica, long-poll its channel,
        yield each chunk as it lands. `timeout` bounds time WITHOUT
        progress (admission included), not total generation.

        Accounting (the long-lived-request fix): the stream holds the
        queued gauge only until the sequence is admitted, then one
        in-flight slot on its replica until it ends — and the ABANDON
        path (caller cancelled / disconnected mid-stream) aborts the
        remote sequence so its KV pages free, then returns both gauges,
        exactly like a one-shot query's withdraw."""
        import asyncio

        from ray_tpu import exceptions as exc

        state = self._state
        backend = self._pick_backend(state) if state else None
        if backend is None or backend not in state.get("backends", {}):
            raise RuntimeError(
                f"no backend serving endpoint {self._endpoint!r}")
        cfg = state["backends"][backend]["config"]
        if not cfg.get("streaming"):
            raise RuntimeError(
                f"backend {backend!r} is not a streaming backend "
                f"(deploy with BackendConfig(streaming=True))")
        poll_s = float(cfg.get("stream_poll_s") or 2.0)
        prompt, _, session, _ = _parse_session(data)
        # same chained page hashes the engine computes: a router-side
        # hash matches a replica-side one iff the token pages match
        phashes = []
        if prompt and cfg.get("prefix_sharing", True):
            phashes = prefix_block_hashes(
                prompt, int(cfg.get("kv_page_size") or 16))
        deadline = time.monotonic() + timeout
        replica = None
        while replica is None:
            replica = self._pick_stream_replica(state, backend, session,
                                                phashes, cfg)
            if replica is None:
                # gang restarting / replicas scaling: wait for cutover
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no replica for {backend!r} within {timeout}s")
                await asyncio.sleep(0.05)
                state = self._state
        key = replica._actor_id.binary()
        with self._lock:
            self._inflight[key] = self._inflight.get(key, 0) + 1
        M_ROUTER_QUEUED.add(1)
        queued = True
        opened = False
        seq_id = None
        finished = False
        try:
            try:
                reply = await replica.stream_open.remote(data)
            except BaseException as e:
                if isinstance(e, exc.ServeOverloadedError):
                    with self._lock:
                        self._shed_total += 1
                    M_SHED_TOTAL.inc()
                    raise
                raise self._map_group_error(e, cfg) from None
            seq_id = reply["seq"]
            self._note_stream_meta(key, reply, cfg)
            M_ROUTER_QUEUED.add(-1)
            queued = False
            opened = True
            M_ADMITTED_TOTAL.inc()  # admitted = the engine accepted it
            with self._lock:
                self._admitted_total += 1
                self._streams_open += 1
            # meta chunk first: session-cache hit/miss is part of the
            # stream contract (a delta-prompt client must resend full
            # history on a miss — see stream_open)
            from ray_tpu.serve.streaming import meta_chunk
            yield meta_chunk(
                seq_id,
                session_cached=reply.get("session_cached", False),
                prefix_hashes=reply.get("prefix_hashes") or [])
            cursor = 0
            deadline = time.monotonic() + timeout
            while True:
                try:
                    chunk = await replica.stream_next.remote(
                        seq_id, cursor, poll_s)
                except BaseException as e:
                    raise self._map_group_error(e, cfg) from None
                if chunk["tokens"]:
                    cursor = chunk["cursor"]
                    deadline = time.monotonic() + timeout  # progress
                    yield chunk
                if chunk["done"]:
                    finished = True
                    err = chunk.get("error")
                    if err is not None:
                        if isinstance(err, exc.ServeOverloadedError):
                            # engine-side shed (KV pool / prefill): the
                            # 503 must move the shed counters even
                            # though stream_open itself succeeded
                            with self._lock:
                                self._shed_total += 1
                            M_SHED_TOTAL.inc()
                        raise self._map_group_error(err, cfg)
                    return
                if time.monotonic() > deadline:
                    finished = True  # we abort it: not abandoned
                    await self._abort_stream(replica, seq_id,
                                             "stream idle timeout")
                    raise TimeoutError(
                        f"stream {seq_id} made no progress within "
                        f"{timeout}s")
        finally:
            if queued:
                M_ROUTER_QUEUED.add(-1)
            with self._lock:
                self._inflight[key] -= 1
                if opened:
                    self._streams_open -= 1
            if opened and not finished:
                # abandon path: caller cancelled / client disconnected
                # mid-stream — abort the sequence so the engine frees
                # its KV pages (fire-and-forget on the caller's loop;
                # we cannot await inside GeneratorExit)
                try:
                    asyncio.get_running_loop().create_task(
                        self._abort_stream(replica, seq_id,
                                           "client disconnect"))
                except RuntimeError:
                    pass  # caller's loop is gone; the engine's stream
                    # reaper and gang teardown bound the leak
            self._wake.set()

    @staticmethod
    async def _abort_stream(replica, seq_id: str, reason: str):
        try:
            await replica.stream_abort.remote(seq_id, reason)
        except Exception:
            pass  # replica already dead: pages died with it

    def _abandon(self, q: _PendingQuery):
        """Caller gave up (timeout / client disconnect). While still
        queued the query is withdrawn outright — queue gauge reclaimed,
        no refs were ever created for it. Once dispatched, the abandoned
        flag makes the completion path drop the result and free the
        router-owned ref instead of parking it on a dead future."""
        with self._lock:
            q.abandoned = True
            dequeued = q in self._queue
            if dequeued:
                self._queue.remove(q)
        if dequeued:
            M_ROUTER_QUEUED.add(-1)

    def close(self):
        with self._lock:
            self._closed = True
            stranded = list(self._queue)
            self._queue.clear()
        if stranded:
            # a closed router must not strand queued callers until their
            # timeout: error them now and give the gauge back
            M_ROUTER_QUEUED.add(-len(stranded))
            err = RuntimeError(
                f"router for {self._endpoint!r} closed while the query "
                f"was queued")
            for q in stranded:
                q.error = err
                q._notify()
        self._wake.set()

    # -- flusher ---------------------------------------------------------

    @staticmethod
    def _pick_backend(state: dict) -> str | None:
        """Weighted-random backend per batch (reference: serve v1
        set_traffic — router splits by endpoint traffic policy)."""
        import random

        traffic = state.get("traffic")
        if not traffic:
            return state.get("backend")
        names = list(traffic)
        if len(names) == 1:
            return names[0]
        return random.choices(names, weights=[traffic[n] for n in names])[0]

    def _pick_replica(self, state: dict, backend: str):
        st = state["backends"].get(backend)
        if st is None:
            return None
        cap = st["config"]["max_concurrent_queries"]
        with self._lock:
            best, best_load = None, None
            for handle in st["replicas"]:
                load = self._inflight.get(handle._actor_id.binary(), 0)
                if load < cap and (best_load is None or load < best_load):
                    best, best_load = handle, load
        return best

    def _flush_loop(self):
        import logging

        while not self._closed:
            self._wake.wait(timeout=0.05)
            self._wake.clear()
            try:
                self._flush_once()
            except Exception:
                # the flusher must outlive any single bad dispatch —
                # a dead flusher turns every future assign() into a
                # timeout
                logging.getLogger("ray_tpu.serve").exception(
                    "router flush iteration failed")
                time.sleep(0.05)

    def _flush_once(self):
        import random

        while not self._closed:
            # one consistent snapshot per iteration: the poller
            # thread swaps self._state on traffic cutover, and mixing
            # two snapshots' backend maps would KeyError the flusher
            state = self._state
            with self._lock:
                if not self._queue:
                    break
            backend = self._pick_backend(state)
            if backend is None or backend not in state["backends"]:
                time.sleep(0.01)
                continue
            cfg = state["backends"][backend]["config"]
            # fill a batch (or give stragglers batch_wait_timeout) —
            # event-driven: enqueues set _wake, so a full batch dispatches
            # the moment it fills instead of on the next 1ms poll tick
            # (each sleep(0.001) is a timer syscall that cost multiple ms
            # under load on the 1-core box)
            if cfg["max_batch_size"]:
                deadline = time.monotonic() + cfg["batch_wait_timeout"]
                while (not self._closed
                       and len(self._queue) < cfg["max_batch_size"]):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._wake.wait(remaining)
                    self._wake.clear()
            replica = self._pick_replica(state, backend)
            if replica is None:
                # chosen backend saturated — try any other traffic
                # backend with capacity before waiting
                for other in state.get("traffic", {}):
                    if other != backend:
                        replica = self._pick_replica(state, other)
                        if replica is not None:
                            backend = other
                            cfg = state["backends"][other]["config"]
                            break
            if replica is None:
                time.sleep(0.002)
                continue
            # batch sized by the backend that will actually serve it
            max_bs = cfg["max_batch_size"] or 1
            with self._lock:
                taken = min(max_bs, len(self._queue))
                batch = [q for q in self._queue[:max_bs]
                         if not q.abandoned]
                del self._queue[:max_bs]
            if taken:
                M_ROUTER_QUEUED.add(-taken)
            if not batch:
                continue
            self._dispatch(replica, batch, cfg=cfg)
            # shadow traffic: mirror the batch, results dropped
            # (reference: serve/api.py shadow_traffic)
            for sb, prop in (state.get("shadow") or {}).items():
                if random.random() < prop:
                    sreplica = self._pick_replica(state, sb)
                    if sreplica is not None:
                        self._dispatch(sreplica, batch, shadow=True,
                                       cfg=state["backends"][sb]["config"])

    def _map_group_error(self, e, cfg):
        """Sharded backends: a dead group LEADER surfaces to callers as
        the typed ReplicaGroupDied (member deaths are typed by the
        leader itself; leader death is an actor error only the router
        can attribute to the gang)."""
        from ray_tpu import exceptions as exc

        if (cfg and cfg.get("num_shards", 1) > 1
                and isinstance(e, (exc.ActorDiedError,
                                   exc.ActorUnavailableError))):
            return exc.ReplicaGroupDied(
                self._endpoint, "",
                f"group leader died: {type(e).__name__}: {e}")
        return e

    def _dispatch(self, replica, batch: list[_PendingQuery],
                  shadow: bool = False, cfg: dict | None = None):
        key = replica._actor_id.binary()
        with self._lock:
            self._inflight[key] = self._inflight.get(key, 0) + 1
        batch_ctx = None
        if not shadow:
            # queue-wait hop closes here: histogram for every query,
            # spans for the traced ones. The first traced query's
            # context becomes ambient for the batch's .remote() below,
            # so the replica-side exec span joins its request tree.
            now = time.time()
            for q in batch:
                M_ROUTER_QUEUE_S.observe(
                    now - q.t_enqueue,
                    exemplar=tracing.exemplar_of(q.trace))
                if q.trace is not None:
                    tracing.record_span(
                        "serve.router_queue", q.t_enqueue, now,
                        tracing.child(q.trace))
                    if batch_ctx is None:
                        batch_ctx = q.trace
        refs: list = []
        try:
            with tracing.use(batch_ctx):
                out = replica.handle_batch.options(
                    num_returns=len(batch)).remote([q.data for q in batch])
            refs = [out] if len(batch) == 1 else list(out)
            if not shadow:
                for q, ref in zip(batch, refs):
                    if q.want_result:
                        continue  # resolved at completion with the value
                    q.ref = ref
                    q._notify()
        except Exception as e:
            if not shadow:
                e = self._map_group_error(e, cfg)
                for q in batch:
                    q.error = e
                    q._notify()
        if refs:
            # shadow batches still occupy a replica slot until done
            # (backpressure); their results are reclaimed the moment
            # each lands (_watch_batch owns and frees those refs)
            self._watch_batch(key, refs, () if shadow else batch,
                              cfg=cfg)
        else:
            with self._lock:
                self._inflight[key] -= 1

    def _watch_batch(self, key: bytes, refs: list, batch,
                     cfg: dict | None = None):
        """Arm one memstore ready-callback per return: the last one to
        fire frees the replica slot, and result-mode queries get their
        deserialized value pushed straight to their event loop. The
        callbacks run inline on the task-reply (io-loop) thread, so a
        whole batch completes in one pass with no polling anywhere.

        Ref reclamation: refs only the ROUTER will ever read — shadow
        results, and result-mode (call_async) returns whose callers get
        the VALUE — are held in `owned` and dropped deterministically as
        each completes, so their memstore entries and owned-table rows
        free on the spot instead of whenever GC finds the callback
        closures ("results go nowhere" must not strand entries). Refs
        handed to assign() callers are theirs to hold; the router keeps
        no copy past the callback."""
        from ray_tpu._private import global_state, rpc, serialization
        from ray_tpu._private.memstore import IN_PLASMA

        cw = global_state.get_core_worker()
        state = {"left": len(refs)}
        waiters = {ref.id(): q for q, ref in zip(batch, refs)
                   if q.want_result}
        if batch:
            owned = {ref.id(): ref for q, ref in zip(batch, refs)
                     if q.want_result}
        else:  # shadow: every result is nobody's — all router-owned
            owned = {ref.id(): ref for ref in refs}

        def finish_one(oid):
            owned.pop(oid, None)  # deterministic free (see docstring)
            with self._lock:
                state["left"] -= 1
                done = state["left"] == 0
                if done:
                    self._inflight[key] -= 1
            if done:
                self._wake.set()

        def deliver(q, result, is_exc):
            def _set():
                fut = q.future
                # abandoned = caller timed out; setting an exception on
                # the orphaned future would log "exception was never
                # retrieved" at GC for every such request
                if fut is None or fut.done() or q.abandoned:
                    return
                if is_exc:
                    fut.set_exception(result)
                else:
                    fut.set_result(result)
            try:
                rpc.loop_call_queue(q.loop).call(_set)
            except RuntimeError:
                pass  # caller's loop closed; result goes nowhere

        def make_cb(ref):
            oid = ref.id()
            q = waiters.get(oid)

            def resolve_blocking():
                import ray_tpu
                try:
                    deliver(q, ray_tpu.get(ref), False)
                except BaseException as e:
                    deliver(q, self._map_group_error(e, cfg), True)
                finally:
                    finish_one(oid)

            def on_ready():
                if q is None:
                    finish_one(oid)
                    return
                found, value, is_exc = cw.memstore.get_if_ready(oid)
                if not found or value is IN_PLASMA:
                    # raced a reset(), or a plasma-resident result: the
                    # read may pull/reconstruct — keep it off this thread
                    threading.Thread(target=resolve_blocking,
                                     daemon=True).start()
                    return
                try:
                    result = serialization.deserialize(value)
                except BaseException as e:
                    result, is_exc = e, True
                if is_exc:
                    result = self._map_group_error(result, cfg)
                deliver(q, result, is_exc)
                finish_one(oid)

            return on_ready

        for ref in refs:
            cw.memstore.add_ready_callback(ref.id(), make_cb(ref))


class ServeHandle:
    """Caller-facing handle (reference: python/ray/serve/handle.py):
    handle.remote(data) -> ObjectRef; ray_tpu.get(ref) -> result."""

    def __init__(self, controller, endpoint: str):
        self._router = Router(controller, endpoint)
        self.endpoint = endpoint

    def remote(self, data=None):
        return self._router.assign(data)

    def stream(self, data=None, timeout: float = 60.0):
        """Sync token generator over a streaming backend: bridges the
        router's async stream onto a private loop thread so plain
        callers iterate tokens as they decode. Abandoning the generator
        mid-stream cancels the async side, which aborts the remote
        sequence (KV pages free) — same contract as an HTTP client
        disconnecting."""
        import asyncio
        import queue as _queue

        out: _queue.Queue = _queue.Queue()
        holder: dict = {}

        def run():
            async def go():
                holder["task"] = asyncio.current_task()
                try:
                    async for chunk in self._router.stream_async(
                            data, timeout=timeout):
                        out.put(("tokens", chunk["tokens"]))
                except asyncio.CancelledError:
                    out.put(("done", None))
                    raise
                except BaseException as e:
                    out.put(("error", e))
                    return
                out.put(("done", None))

            try:
                asyncio.run(go())
            except BaseException:
                pass
            holder["loop_done"] = True

        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        try:
            while True:
                kind, val = out.get()
                if kind == "tokens":
                    yield from val
                elif kind == "error":
                    raise val
                else:
                    return
        finally:
            task = holder.get("task")
            if task is not None and not holder.get("loop_done"):
                try:
                    task.get_loop().call_soon_threadsafe(task.cancel)
                except RuntimeError:
                    pass

    def __repr__(self):
        return f"ServeHandle({self.endpoint!r})"
