"""RL throughput benchmark: env-steps/s THROUGH the framework
(north-star metric #2, BASELINE.json "RLlib PPO Atari env-steps/s";
reference context: rllib claims ~30k transitions/s for IMPALA at 32
workers + GPU learner, doc/source/rllib-algorithms.rst:160, and the
release PPO regression logs, release/release_logs/1.2.0/
rllib_regression_tf.txt).

This box has CPU CartPole vector envs, so the absolute numbers measure a
different machine class than the reference's Atari+GPU rigs — the
artifact exists so every round records the framework's sampling+learning
pipeline rate under the SAME workload, with run metadata for cross-round
provenance. Results are written like MICROBENCH.json.

Usage: python -m ray_tpu.rlbench [--out RLBENCH_rNN.json] [--seconds 20]
"""

from __future__ import annotations

import json
import time

from ray_tpu._private.bench_meta import run_metadata as _metadata


def bench_ppo(seconds: float) -> dict:
    """Synchronous PPO: sample (2 workers x 2 envs) -> SGD epochs.
    Every sampled step is trained, so one rate describes both."""
    from ray_tpu.rllib.agents.ppo import PPOTrainer

    trainer = PPOTrainer(config={
        "env": "CartPole-v1",
        "num_workers": 2,
        "num_envs_per_worker": 2,
        "rollout_fragment_length": 128,
        "train_batch_size": 1024,
        "sgd_minibatch_size": 256,
        "num_sgd_iter": 8,
        "seed": 0,
    })
    trainer.step()  # compile + warmup
    sampled = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        m = trainer.step()
        sampled += m.get("num_env_steps_trained", 0)
    wall = time.perf_counter() - t0
    trainer.cleanup()
    return {
        "name": "ppo_cartpole_env_steps",
        "env": "CartPole-v1",
        "per_second": round(sampled / wall, 1),
        "env_steps": sampled,
        "wall_s": round(wall, 2),
        "learner_utilization": 1.0,  # sync: every sampled step trains
    }


def bench_impala(seconds: float) -> dict:
    """Async IMPALA: actors sample while the LearnerThread consumes;
    utilization = trained/sampled (1.0 = learner keeps up; the reference
    reports the same two counters)."""
    from ray_tpu.rllib.agents.impala import ImpalaTrainer

    trainer = ImpalaTrainer(config={
        "env": "CartPole-v1",
        "num_workers": 2,
        "num_envs_per_worker": 2,
        "rollout_fragment_length": 80,
        "train_batch_size": 800,
        "seed": 0,
    })
    trainer.step()  # compile + warmup
    base_sampled = trainer._sampled
    base_trained = trainer._learner.num_steps_trained
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        trainer.step()
    wall = time.perf_counter() - t0
    sampled = trainer._sampled - base_sampled
    trained = trainer._learner.num_steps_trained - base_trained
    trainer.cleanup()
    return {
        "name": "impala_cartpole_env_steps",
        "env": "CartPole-v1",
        "per_second": round(sampled / wall, 1),
        "trained_per_second": round(trained / wall, 1),
        "env_steps": sampled,
        "wall_s": round(wall, 2),
        "learner_utilization": round(trained / max(sampled, 1), 3),
    }


def main(seconds: float = 20.0) -> dict:
    import ray_tpu

    # logical CPUs: the trainers place 2 rollout workers + a learner;
    # on a 1-core box autodetection would leave workers unschedulable
    # (they timeshare either way — this benchmark measures pipeline
    # rate, not core scaling)
    ray_tpu.init(num_cpus=8)
    try:
        results = [bench_ppo(seconds), bench_impala(seconds)]
    finally:
        ray_tpu.shutdown()
    doc = {
        "metadata": _metadata(),
        "reference_context": (
            "reference IMPALA ~30k env-steps/s at 32 workers + V100 "
            "learner on Atari (rllib-algorithms.rst:160); this artifact "
            "runs CPU CartPole on one shared box — compare across "
            "rounds, not across machine classes"),
        "results": results,
    }
    for r in results:
        print(f"{r['name']} per second {r['per_second']}")
    return doc


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None)
    parser.add_argument("--seconds", type=float, default=20.0)
    args = parser.parse_args()
    doc = main(args.seconds)
    print(json.dumps(doc))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
