"""Streaming ingest pipeline: sharded dataset actors produce per-rank
batches into the object plane; each train worker prefetches
`prefetch_depth` batches ahead (double-buffered at the default depth 2)
so input time overlaps step compute instead of serializing before it.

Data path: `DatasetShard.next_batch` returns the batch through the
normal actor return path — large batches land in plasma and cross-node
pulls ride the bulk transfer channel (raylet/transfer.py), so the
worker's prefetched ObjectRefs resolve via striped chunk streams, not
pickles through the driver. The worker's `IngestStream` keeps at most
`prefetch_depth` requests in flight and observes `train.ingest_wait_s`
around each blocking get — the "is training input-bound?" histogram.

Failure domain: an ingest actor dying mid-epoch surfaces as a typed
actor error inside the consuming worker's epoch; the Trainer's gang
scan treats dead ingest actors like dead workers (resize restarts the
gang AND its dataset actors at the surviving world size, re-sharding
the dataset over the new rank count). Un-consumed prefetched refs are
dropped on every exit path, so no plasma batches leak."""

from __future__ import annotations

import collections
import dataclasses
import pickle
import time
from typing import Any, Callable

from ray_tpu._private import failpoints as _fp

# End-of-epoch sentinel: actor returns (not raises) it so prefetched
# requests past the end resolve cheaply instead of erroring.
_END = "__ray_tpu_ingest_end__"


@dataclasses.dataclass
class IngestSpec:
    """Trainer(ingest=IngestSpec(...)) — one DatasetShard actor per
    worker rank.

    dataset_fn(shard_index, num_shards, config) -> either a reusable
    sequence of batches (replayed every epoch) or a callable
    ``epoch -> iterable`` for epoch-varying streams. Cloudpickled to
    the actor, so closures and __main__ classes work.

    prefetch_depth: in-flight batches per worker (None = the
    `train_ingest_prefetch_depth` config knob, default 2 — double
    buffering). resources: per-dataset-actor resource dict
    (default {"CPU": 1})."""

    dataset_fn: Callable[[int, int, dict], Any]
    prefetch_depth: int | None = None
    resources: dict | None = None


class DatasetShard:
    """Actor producing one rank's batch stream. Single-threaded actor
    semantics give in-order `next_batch` delivery, so the worker's
    pipelined requests arrive as a strictly sequential pull."""

    def __init__(self, dataset_fn_pickled: bytes, shard_index: int,
                 num_shards: int, config: dict | None):
        fn = pickle.loads(dataset_fn_pickled)
        self._source = fn(shard_index, num_shards, config or {})
        self._gen = None
        self._iter = None

    def next_batch(self, gen: int, epoch: int):
        """Next batch of the consumer's iteration `gen`, or the end
        sentinel. A new gen rebuilds the iterator — gen (not epoch) is
        the rebuild key so an epoch RETRIED after a mid-stream abort
        replays from the start instead of resuming a half-consumed
        iterator. Sequences replay as-is; callables get the epoch."""
        if _fp.ARMED:
            _fp.fire_strict("train.ingest_batch")
        if gen != self._gen:
            src = (self._source(epoch) if callable(self._source)
                   else self._source)
            self._iter = iter(src)
            self._gen = gen
        try:
            return next(self._iter)
        except StopIteration:
            return _END

    def ping(self):
        return True

    def failpoints(self):
        """Chaos-test introspection: this actor process's failpoint
        registry. Cluster arming rides pubsub (async); tests poll this
        until the spec lands before relying on an armed point."""
        return _fp.snapshot()


class IngestStream:
    """Worker-side iterable over one DatasetShard, `depth` requests in
    flight. Fresh iterator per epoch (the operator's epoch counter is
    read lazily, so one IngestStream instance serves the whole run)."""

    def __init__(self, actor, depth: int, epoch_fn: Callable[[], int],
                 get_timeout: float = 300.0):
        self._actor = actor
        self._depth = max(1, int(depth))
        self._epoch_fn = epoch_fn
        self._timeout = get_timeout
        self._gen = 0

    def __iter__(self):
        import ray_tpu
        from ray_tpu.train import metrics as _tm

        epoch = self._epoch_fn()
        self._gen += 1
        gen = self._gen
        refs: collections.deque = collections.deque()
        try:
            while True:
                while len(refs) < self._depth:
                    refs.append(self._actor.next_batch.remote(gen, epoch))
                t0 = time.perf_counter()
                batch = ray_tpu.get(refs.popleft(), timeout=self._timeout)
                _tm.INGEST_WAIT_S.observe(time.perf_counter() - t0)
                if isinstance(batch, str) and batch == _END:
                    return
                yield batch
        finally:
            # Drop in-flight refs on every exit (end, error, early
            # break): out-of-scope ObjectRefs release their plasma
            # entries — the conftest leak check holds us to this.
            refs.clear()


def hist_quantile(snap: dict, q: float) -> float:
    """Quantile upper bound from a Histogram snapshot (bench/gate
    readback for `train.ingest_wait_s`): the boundary of the bucket
    where the cumulative count crosses q (inf for the overflow
    bucket)."""
    n = snap.get("count", 0)
    if not n:
        return 0.0
    target = q * n
    cum = 0
    for i, c in enumerate(snap["counts"]):
        cum += c
        if cum >= target:
            bounds = snap["boundaries"]
            return bounds[i] if i < len(bounds) else float("inf")
    return float("inf")
