"""Cross-replica weight-update shard math (ZeRO; PAPERS.md "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training",
arXiv:2004.13336).

Layout contract — shared by the operator's sharded step, the collective
layer's quantized reducescatter fast path, and the checkpoint manifest:

- the model's parameters ravel to ONE flat f32 bucket of `numel`
  elements (jax.flatten_util.ravel_pytree order);
- the bucket is zero-padded to ``pad_numel = ceil(numel / (world *
  QUANT_BLOCK)) * world * QUANT_BLOCK`` so every rank owns one
  *uniform*, QUANT_BLOCK-aligned span of ``pad_numel // world``
  elements. Uniform spans keep the allgather of param shards on the
  fast collective tiers (which require uniform geometry) and line the
  reducescatter chunks up with the int8 block-scale grid, so
  ``quantize="int8"`` engages with zero re-marshalling;
- rank r's span is ``[r*S, (r+1)*S)`` with ``S = pad_numel // world``
  — identical to np.array_split (the hub/ring/shm reducescatter
  partition) because pad_numel divides evenly;
- optimizer state is ``optimizer.init(param_shard)``: every array leaf
  of the optax state is either a 1-D vector of exactly S elements
  (shard-partitioned — momentum/adam moments) or smaller (replicated —
  step counters, scalars). Resharding relies on exactly that shape
  dichotomy.

Reshard-on-resize contract: pad-region gradients are identically zero,
so pad-region optimizer state stays at its zero init; merging shards
and re-splitting to a new world size therefore reconstructs the exact
state any world size would have reached (optimizers whose state init is
not zeros_like — none in optax's common set — are outside the
contract)."""

from __future__ import annotations

import numpy as np

from ray_tpu.collective.types import QUANT_BLOCK


def padded_numel(numel: int, world: int) -> int:
    """Smallest multiple of world * QUANT_BLOCK holding `numel`."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    unit = world * QUANT_BLOCK
    return -(-numel // unit) * unit


def shard_span(numel: int, world: int, rank: int) -> tuple[int, int]:
    """Rank's [lo, hi) span of the padded flat bucket."""
    s = padded_numel(numel, world) // world
    return rank * s, (rank + 1) * s


def shard_spans(numel: int, world: int) -> list[tuple[int, int]]:
    return [shard_span(numel, world, r) for r in range(world)]


def opt_nbytes(opt_state) -> int:
    """Bytes held by the array leaves of an optimizer state (the
    `train.optim_shard_bytes` gauge — 1/N of the replicated figure in
    sharded mode)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(opt_state):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def _is_partitioned(leaf, span_elems: int) -> bool:
    return (isinstance(leaf, np.ndarray) and leaf.ndim == 1
            and leaf.size == span_elems)


def merge_opt_shards(shards: list[dict]) -> list:
    """Merge per-rank shard states (``opt_shard_state()`` dicts, rank
    order) back into full padded flat leaves: partitioned leaves
    concatenate across ranks, replicated leaves come from rank 0."""
    if not shards:
        raise ValueError("no shards to merge")
    order = sorted(shards, key=lambda s: s["rank"])
    ranks = [s["rank"] for s in order]
    if ranks != list(range(len(order))):
        raise ValueError(f"shard set is not ranks 0..N-1: {ranks}")
    span = order[0]["span"][1] - order[0]["span"][0]
    merged = []
    for j, leaf in enumerate(order[0]["leaves"]):
        if _is_partitioned(leaf, span):
            merged.append(np.concatenate([s["leaves"][j] for s in order]))
        else:
            merged.append(leaf)
    return merged


def reshard_opt_shards(shards: list[dict], new_world: int) -> list[dict]:
    """Re-partition a saved/live shard set to `new_world` ranks — the
    elastic-resize restore and any-world-size checkpoint load path.
    Partitioned leaves are merged, trimmed to the real `numel`, then
    zero-padded to the NEW pad_numel and split into uniform spans."""
    if not shards:
        raise ValueError("no shards to reshard")
    numel = int(shards[0]["numel"])
    merged = merge_opt_shards(shards)
    old_span = shards[0]["span"][1] - shards[0]["span"][0]
    new_pad = padded_numel(numel, new_world)
    s = new_pad // new_world
    out = []
    for rank in range(new_world):
        lo, hi = rank * s, (rank + 1) * s
        leaves = []
        for j, full in enumerate(merged):
            if _is_partitioned(shards[0]["leaves"][j], old_span):
                vec = np.zeros(new_pad, full.dtype)
                vec[:numel] = full[:numel]
                leaves.append(vec[lo:hi].copy())
            else:
                leaves.append(full)
        out.append({"rank": rank, "world_size": new_world,
                    "span": (lo, hi), "numel": numel,
                    "pad_numel": new_pad, "leaves": leaves})
    return out
