"""TrainingOperator — user-defined training logic run on each worker
(reference: python/ray/util/sgd/torch/training_operator.py:50 — setup :175,
register :187, train_epoch :437), redesigned jax-first:

- the user registers a functional model (init_fn + loss_fn) and an optax
  optimizer instead of nn.Module/torch.optim objects;
- the framework jits one fused step: value_and_grad → (cross-worker grad
  allreduce) → optimizer update with donated buffers;
- gradients cross workers as ONE flat bucket (ravel_pytree), the DDP
  bucketing idea without the bookkeeping.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


class TrainingOperator:
    """Subclass and implement setup(); call self.register(...) there."""

    def __init__(self, config: dict, world_rank: int, world_size: int,
                 group_name: str | None = None):
        self.config = config or {}
        self.world_rank = world_rank
        self.world_size = world_size
        self._group_name = group_name
        self._registered = False
        self._train_loader = None
        self._val_loader = None
        self.epoch = 0
        self.global_step = 0
        self.setup(self.config)
        if not self._registered:
            raise RuntimeError(
                "TrainingOperator.setup() must call self.register(...)")

    # ------------------------------------------------------------------
    # user surface
    # ------------------------------------------------------------------

    def setup(self, config: dict):
        raise NotImplementedError

    def register(self, *, model_init: Callable[[jax.Array], Any],
                 loss_fn: Callable[[Any, Any], jax.Array],
                 optimizer, seed: int = 0,
                 eval_fn: Callable[[Any, Any], dict] | None = None):
        """model_init(rng) -> params pytree; loss_fn(params, batch) -> scalar
        loss; optimizer: optax GradientTransformation; eval_fn(params, batch)
        -> metrics dict (defaults to {"val_loss": loss_fn(...)})."""
        self._registered = True
        self._loss_fn = loss_fn
        self._eval_fn = eval_fn
        self._optimizer = optimizer
        self.params = model_init(jax.random.key(seed))
        self.opt_state = optimizer.init(self.params)
        _, self._unravel = ravel_pytree(self.params)
        self._build_steps()

    def register_data(self, *, train_loader: Iterable | None = None,
                      validation_loader: Iterable | None = None):
        self._train_loader = train_loader
        self._val_loader = validation_loader

    # ------------------------------------------------------------------
    # jitted steps
    # ------------------------------------------------------------------

    def _build_steps(self):
        loss_fn, optimizer = self._loss_fn, self._optimizer
        unravel = self._unravel

        @jax.jit
        def grad_step(params, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            return loss, ravel_pytree(grads)[0]

        def apply_step(params, opt_state, flat_grads):
            grads = unravel(flat_grads)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return jax.tree.map(lambda p, u: p + u, params, updates), opt_state

        self._grad_step = grad_step
        self._apply_step = jax.jit(apply_step, donate_argnums=(0, 1))

        if self._eval_fn is None:
            self._jit_eval = jax.jit(
                lambda params, batch: {"val_loss": loss_fn(params, batch)})
        else:
            self._jit_eval = jax.jit(self._eval_fn)

    def _allreduce_grads(self, flat_grads: jax.Array) -> np.ndarray:
        if self.world_size == 1:
            return flat_grads
        from ray_tpu.collective import collective as col

        avg = col.allreduce(np.asarray(flat_grads),
                            group_name=self._group_name)
        return avg / self.world_size

    # ------------------------------------------------------------------
    # train/validate loops (reference: training_operator.py:437 train_epoch)
    # ------------------------------------------------------------------

    def train_batch(self, batch) -> dict:
        loss, flat_grads = self._grad_step(self.params, batch)
        flat_grads = self._allreduce_grads(flat_grads)
        self.params, self.opt_state = self._apply_step(
            self.params, self.opt_state, flat_grads)
        self.global_step += 1
        return {"train_loss": float(loss)}

    def train_epoch(self, num_steps: int | None = None) -> dict:
        if self._train_loader is None:
            raise RuntimeError("no train_loader registered")
        t0 = time.perf_counter()
        losses, samples = [], 0
        it = iter(self._train_loader)
        step = 0
        for batch in it:
            metrics = self.train_batch(batch)
            losses.append(metrics["train_loss"])
            samples += _batch_size(batch)
            step += 1
            if num_steps is not None and step >= num_steps:
                break
        self.epoch += 1
        dt = time.perf_counter() - t0
        return {
            "epoch": self.epoch,
            "batch_count": len(losses),
            "num_samples": samples,
            "train_loss": float(np.mean(losses)) if losses else float("nan"),
            "last_train_loss": losses[-1] if losses else float("nan"),
            "samples_per_s": samples / dt if dt > 0 else 0.0,
        }

    def validate(self, num_steps: int | None = None) -> dict:
        if self._val_loader is None:
            raise RuntimeError("no validation_loader registered")
        all_metrics: list[dict] = []
        samples = 0
        for step, batch in enumerate(self._val_loader):
            m = self._jit_eval(self.params, batch)
            all_metrics.append({k: float(v) for k, v in m.items()})
            samples += _batch_size(batch)
            if num_steps is not None and step + 1 >= num_steps:
                break
        out = {k: float(np.mean([m[k] for m in all_metrics]))
               for k in (all_metrics[0] if all_metrics else {})}
        out["num_samples"] = samples
        return out

    # ------------------------------------------------------------------
    # checkpointing (reference: torch_trainer.py:543 save / :552 load)
    # ------------------------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(
                lambda x: np.asarray(x) if isinstance(
                    x, (jnp.ndarray, np.ndarray)) else x, self.opt_state),
            "epoch": self.epoch,
            "global_step": self.global_step,
        }

    def load_state_dict(self, state: dict):
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            lambda ref, x: jnp.asarray(x) if isinstance(
                x, np.ndarray) else x,
            self.opt_state, state["opt_state"])
        self.epoch = state["epoch"]
        self.global_step = state["global_step"]


def _batch_size(batch) -> int:
    leaves = jax.tree.leaves(batch)
    return int(leaves[0].shape[0]) if leaves and hasattr(
        leaves[0], "shape") and leaves[0].ndim else 0
