"""TrainingOperator — user-defined training logic run on each worker
(reference: python/ray/util/sgd/torch/training_operator.py:50 — setup :175,
register :187, train_epoch :437), redesigned jax-first:

- the user registers a functional model (init_fn + loss_fn) and an optax
  optimizer instead of nn.Module/torch.optim objects;
- the framework jits one fused step: value_and_grad → (cross-worker grad
  allreduce) → optimizer update with donated buffers;
- when the model has mutable state (batchnorm stats), register with
  stateful=True and model_init returning (params, state), loss_fn
  (params, state, batch) -> (loss, new_state);
- single-worker (or XLA-backend) groups run ONE fused jit per batch with
  all buffers donated and the loss left on device — no host syncs inside
  the epoch loop, so the framework path matches a bare jit loop;
- multi-worker host groups move gradients as ONE flat bucket
  (ravel_pytree), the DDP bucketing idea without the bookkeeping.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from ray_tpu._private import profiling as _profiling


class TrainingOperator:
    """Subclass and implement setup(); call self.register(...) there."""

    def __init__(self, config: dict, world_rank: int, world_size: int,
                 group_name: str | None = None):
        self.config = config or {}
        self.world_rank = world_rank
        self.world_size = world_size
        self._group_name = group_name
        self._registered = False
        self._train_loader = None
        self._val_loader = None
        self.epoch = 0
        self.global_step = 0
        self.setup(self.config)
        if not self._registered:
            raise RuntimeError(
                "TrainingOperator.setup() must call self.register(...)")

    # ------------------------------------------------------------------
    # user surface
    # ------------------------------------------------------------------

    def setup(self, config: dict):
        raise NotImplementedError

    def register(self, *, model_init: Callable[[jax.Array], Any],
                 loss_fn: Callable[..., jax.Array],
                 optimizer, seed: int = 0, stateful: bool = False,
                 eval_fn: Callable[..., dict] | None = None,
                 mesh=None, param_spec=None, batch_spec=None):
        """Register the functional model.

        stateful=False: model_init(rng) -> params;
            loss_fn(params, batch) -> scalar loss.
        stateful=True (models with mutable state, e.g. batchnorm):
            loss_fn(params, state, batch) -> (loss, new_state).
        optimizer: optax GradientTransformation.
        eval_fn(params[, state], batch) -> metrics dict (defaults to
            loss_fn in eval position).

        mesh: a jax Mesh (possibly GLOBAL, spanning worker processes via
            parallel.multihost) — the step runs SPMD over it and gradient
            combination is XLA's psum over the batch axes, NOT the HOST
            collective backend. param_spec: PartitionSpec or pytree of
            them for the params (default replicated); batch_spec:
            PartitionSpec for batches (default P('dp'): rows over the
            data axis).
        """
        self._registered = True
        self._loss_fn = loss_fn
        self._eval_fn = eval_fn
        self._optimizer = optimizer
        self._stateful = stateful
        if stateful:
            self.params, self.model_state = model_init(jax.random.key(seed))
        else:
            self.params = model_init(jax.random.key(seed))
            self.model_state = None
        if mesh is None and self.config.get("mesh_mode") == "fsdp":
            # FSDP mesh mode: the topology-derived ('data','fsdp') mesh
            # (parallel.mesh.mesh_shape_for — the same table the
            # ICI_RING placement record carries), params sharded over
            # the fsdp axis, batch over data. The fused step stays ONE
            # jit: with_sharding_constraint pins the updated params so
            # XLA keeps every optimizer buffer on its shard.
            from jax.sharding import PartitionSpec as P

            from ray_tpu.parallel import mesh as _meshlib

            mesh = _meshlib.fsdp_mesh()
            if param_spec is None:
                param_spec = _meshlib.fsdp_param_specs(self.params, mesh)
            if batch_spec is None:
                batch_spec = P("data")
        self._mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            def to_sharding(spec):
                return NamedSharding(mesh, spec if spec is not None else P())

            if param_spec is None or isinstance(param_spec, P):
                p_shard = to_sharding(param_spec)
                self.params = jax.device_put(self.params, p_shard)
            else:  # pytree of PartitionSpecs matching params
                self.params = jax.tree.map(
                    lambda p, s: jax.device_put(p, to_sharding(s)),
                    self.params, param_spec,
                    is_leaf=lambda x: isinstance(x, P))
            if self.model_state is not None:
                self.model_state = jax.device_put(self.model_state,
                                                  to_sharding(None))
            self._batch_sharding = to_sharding(
                batch_spec if batch_spec is not None else P("dp"))
            self._param_shardings = jax.tree.map(
                lambda p: p.sharding, self.params)
        else:
            self._param_shardings = None
        # sharded stays on at world_size == 1 (collectives degenerate to
        # identity) so an elastic resize N→1→N keeps ONE state layout —
        # optimizer shards merge/split instead of changing format.
        self._sharded = (bool(self.config.get("sharded_update"))
                         and mesh is None)
        _, self._unravel = ravel_pytree(self.params)
        if self._sharded:
            self._init_sharded_state()
        else:
            # After placement: optax init inherits the params' shardings
            # (zeros_like preserves sharding), so optimizer state is laid
            # out like the params without extra plumbing.
            self.opt_state = optimizer.init(self.params)
        from ray_tpu.train import metrics as _tm
        from ray_tpu.train import sharding as _shard

        _tm.OPT_SHARD_BYTES.set(_shard.opt_nbytes(self.opt_state))
        self._build_steps()

    def _init_sharded_state(self):
        """ZeRO weight-update sharding (arXiv:2004.13336): this rank
        keeps the FULL params (needed for the forward) but only 1/N of
        the optimizer state — optax initialized on the rank's uniform
        span of the padded flat param bucket (layout: train/sharding.py).
        The step becomes reducescatter(grads) → local shard update →
        allgather(params)."""
        from ray_tpu.train import sharding as _shard

        flat, _ = ravel_pytree(self.params)
        self._numel = int(flat.size)
        self._pad_numel = _shard.padded_numel(self._numel, self.world_size)
        self._shard_lo, self._shard_hi = _shard.shard_span(
            self._numel, self.world_size, self.world_rank)
        self._param_shard = jnp.pad(
            flat, (0, self._pad_numel - self._numel)
        )[self._shard_lo:self._shard_hi]
        self.opt_state = self._optimizer.init(self._param_shard)
        self._opt_treedef = jax.tree.structure(self.opt_state)

    def register_data(self, *, train_loader: Iterable | None = None,
                      validation_loader: Iterable | None = None):
        self._train_loader = train_loader
        self._val_loader = validation_loader

    # ------------------------------------------------------------------
    # jitted steps
    # ------------------------------------------------------------------

    def _build_steps(self):
        loss_fn, optimizer = self._loss_fn, self._optimizer
        unravel = self._unravel
        stateful = self._stateful
        shardings = self._param_shardings

        def pin(params):
            # FSDP/mesh mode: constrain the UPDATED params back onto
            # their named shardings so the whole fused step — grads,
            # optimizer buffers, update — stays sharded inside one jit
            # instead of XLA replicating intermediates.
            return (params if shardings is None
                    else jax.lax.with_sharding_constraint(params, shardings))
        # compile observability (profiling.py): the first dispatch of a
        # NEW batch shape class recompiles the jitted step — record it
        # (jax.compiles_total / jax.compile_s / a `jax.compile` span) so
        # a shape-churning loader reads as a recompile storm, not a
        # mystery slowdown
        self._compile_probe = _profiling.CompileProbe("train.step")

        # Fused path (single worker): grads + update in one jit, buffers
        # donated so XLA updates params/opt_state in place; loss stays on
        # device — the epoch loop issues pure async dispatches.
        if stateful:
            def fused(params, mstate, opt_state, batch):
                (loss, new_mstate), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, mstate, batch)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = pin(jax.tree.map(lambda p, u: p + u, params,
                                          updates))
                return params, new_mstate, opt_state, loss

            def grad_step(params, mstate, batch):
                (loss, new_mstate), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, mstate, batch)
                return loss, new_mstate, ravel_pytree(grads)[0]

            self._fused_step = jax.jit(fused, donate_argnums=(0, 1, 2))
            self._fused_donate = (0, 1, 2)
            self._grad_step = jax.jit(grad_step)
        else:
            def fused(params, mstate, opt_state, batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = pin(jax.tree.map(lambda p, u: p + u, params,
                                          updates))
                return params, mstate, opt_state, loss

            def grad_step(params, mstate, batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                return loss, mstate, ravel_pytree(grads)[0]

            self._fused_step = jax.jit(fused, donate_argnums=(0, 2))
            self._fused_donate = (0, 2)
            self._grad_step = jax.jit(grad_step)

        def apply_step(params, opt_state, flat_grads):
            grads = unravel(flat_grads)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return jax.tree.map(lambda p, u: p + u, params, updates), opt_state

        self._apply_step = jax.jit(apply_step, donate_argnums=(0, 1))
        if self._sharded:
            ws = self.world_size
            pad = self._pad_numel - self._numel

            # The ZeRO step's local half: average the reduce-scattered
            # grad shard, update THIS rank's 1/N of (params, opt state).
            # Elementwise over the flat bucket, so it is bitwise the
            # same arithmetic the replicated apply_step would do on
            # these elements — the bit-exactness bar rests on this.
            def shard_apply(pshard, opt_state, gshard):
                g = gshard / ws
                updates, opt_state = optimizer.update(g, opt_state, pshard)
                return pshard + updates, opt_state

            self._shard_apply = jax.jit(shard_apply, donate_argnums=(0, 1))
            self._pad_grads = jax.jit(lambda g: jnp.pad(g, (0, pad)))
        # persistent AOT compile cache over the step seams: one
        # CachedFunction per (step name, batch shape class), keyed
        # additionally by a jaxpr hash of the USER computation
        # (loss_fn/optimizer) so two models with identical shapes never
        # share an executable. A restarted/elastically-resized worker
        # whose shapes an earlier generation compiled loads instead of
        # re-tracing — and records NO compile event.
        self._step_cache = {}

        if self._eval_fn is not None:
            self._jit_eval = jax.jit(self._eval_fn)
        elif stateful:
            self._jit_eval = jax.jit(
                lambda params, mstate, batch:
                {"val_loss": loss_fn(params, mstate, batch)[0]})
        else:
            self._jit_eval = jax.jit(
                lambda params, batch: {"val_loss": loss_fn(params, batch)})

    def _allreduce_grads(self, flat_grads: jax.Array):
        from ray_tpu.collective import collective as col

        # the gradient bucket stays a device array: a device-capable
        # group (Transport.DEVICE) reduces it over ICI with zero host
        # copies; host groups convert internally. The group's quantize
        # default (Trainer(quantize="int8")) applies to the wire here.
        avg = col.allreduce(flat_grads, group_name=self._group_name)
        return avg / self.world_size

    def _reducescatter_grads(self, flat_grads: jax.Array):
        """Sharded step, wire half 1: pad the flat grad bucket to the
        shard layout and reduce-scatter it — each rank receives only the
        summed span it will update, (w-1)/w * bucket bytes on the wire
        instead of ~2x bucket for allreduce. The group's quantize
        default (Trainer(quantize="int8")) drops it ~4x further."""
        from ray_tpu._private import failpoints as _fp
        from ray_tpu.collective import collective as col

        if _fp.ARMED:
            _fp.fire_strict("train.reducescatter")
        padded = self._pad_grads(flat_grads)
        if self.world_size == 1:
            return padded  # whole (padded) bucket IS the rank's span
        return col.reducescatter(padded, group_name=self._group_name)

    def _allgather_params(self):
        """Sharded step, wire half 2: every rank contributes its updated
        param shard; concatenation (uniform spans, rank order) rebuilds
        the padded flat bucket, trimmed + unraveled into self.params.
        The gather relays exact bytes, so params stay bit-identical
        across ranks even under a quantized (lossy) grad wire."""
        if self.world_size == 1:
            self.params = self._unravel(self._param_shard[:self._numel])
            return
        from ray_tpu.collective import collective as col

        shards = col.allgather(np.asarray(self._param_shard),
                               group_name=self._group_name)
        flat = np.concatenate(shards)[:self._numel]
        self.params = self._unravel(jnp.asarray(flat))

    # ------------------------------------------------------------------
    # train/validate loops (reference: training_operator.py:437 train_epoch)
    # ------------------------------------------------------------------

    def train_batch(self, batch) -> dict:
        """Sync path for step-at-a-time callers; returns a host float."""
        loss = self._dispatch_batch(batch)
        self.global_step += 1
        return {"train_loss": float(loss)}

    def _place_batch(self, batch):
        """Mesh path: lift a host-local batch onto the (global) mesh —
        each process contributes its local rows; XLA's compiled
        collectives combine across processes."""
        if jax.process_count() > 1:
            from ray_tpu.parallel import multihost

            return multihost.shard_host_batch(batch, self._batch_sharding)
        return jax.device_put(batch, self._batch_sharding)

    def _cached_step(self, name: str, shape_key: str, jitted, donate=()):
        """The per-(step, shape-class) CachedFunction — compile
        observability moves inside it: a persistent-cache HIT records no
        compile event (jax.compiles_total stays flat on a warm restart),
        a miss records exactly what CompileProbe.watch did before."""
        from ray_tpu._private import compile_cache as _cc

        key = (name, shape_key)
        fn = self._step_cache.get(key)
        if fn is None:
            fn = self._step_cache[key] = _cc.CachedFunction(
                "train.step", key, jitted, donate_argnums=donate,
                record_key=f"train.step:{name}:{shape_key}",
                fingerprint_computation=True)
        return fn

    def _dispatch_batch(self, batch):
        """Run one step, returning the (possibly device-resident) loss."""
        shape_key = _profiling.shape_class(batch)
        if self._mesh is not None:
            # SPMD over the (global) mesh — no HOST allreduce.
            batch = self._place_batch(batch)
            step = self._cached_step("fused-mesh", shape_key,
                                     self._fused_step, self._fused_donate)
            self.params, self.model_state, self.opt_state, loss = step(
                self.params, self.model_state, self.opt_state, batch)
            return loss
        if self.world_size == 1 and not self._sharded:
            step = self._cached_step("fused", shape_key,
                                     self._fused_step, self._fused_donate)
            self.params, self.model_state, self.opt_state, loss = step(
                self.params, self.model_state, self.opt_state, batch)
            return loss
        grad = self._cached_step("grad", shape_key, self._grad_step)
        loss, self.model_state, flat_grads = grad(
            self.params, self.model_state, batch)
        if self._sharded:
            # ZeRO schedule: reducescatter(grads) -> update local 1/N
            # shard of (params, opt state) -> allgather(params).
            gshard = self._reducescatter_grads(flat_grads)
            apply = self._cached_step("shard-apply", "flat",
                                      self._shard_apply, (0, 1))
            self._param_shard, self.opt_state = apply(
                self._param_shard, self.opt_state, jnp.asarray(gshard))
            self._allgather_params()
            return loss
        flat_grads = self._allreduce_grads(flat_grads)
        apply = self._cached_step("apply", "flat", self._apply_step,
                                  (0, 1))
        self.params, self.opt_state = apply(
            self.params, self.opt_state, flat_grads)
        return loss

    def train_epoch(self, num_steps: int | None = None,
                    profile_dir: str | None = None) -> dict:
        if self._train_loader is None:
            raise RuntimeError("no train_loader registered")
        from ray_tpu.train import metrics as _tm

        if profile_dir:
            jax.profiler.start_trace(profile_dir)
        try:
            t0 = time.perf_counter()
            losses, samples = [], 0
            step = 0
            t_step = t0
            for batch in self._train_loader:
                # step_s spans loader wait + dispatch: together with
                # ingest_wait_s (observed inside IngestStream's get)
                # the pair answers "is training input-bound?"
                losses.append(self._dispatch_batch(batch))
                self.global_step += 1
                bs = _batch_size(batch)
                samples += bs
                if bs:
                    _tm.TOKENS_TOTAL.inc(bs)
                now = time.perf_counter()
                _tm.STEP_S.observe(now - t_step)
                t_step = now
                step += 1
                if num_steps is not None and step >= num_steps:
                    break
            # One sync for the whole epoch: the loop was async dispatch.
            losses = [float(x) for x in losses]
            dt = time.perf_counter() - t0
        finally:
            if profile_dir:
                jax.profiler.stop_trace()
        self.epoch += 1
        return {
            "epoch": self.epoch,
            "batch_count": len(losses),
            "num_samples": samples,
            "train_loss": float(np.mean(losses)) if losses else float("nan"),
            "last_train_loss": losses[-1] if losses else float("nan"),
            "samples_per_s": samples / dt if dt > 0 else 0.0,
        }

    def validate(self, num_steps: int | None = None) -> dict:
        if self._val_loader is None:
            raise RuntimeError("no validation_loader registered")
        all_metrics: list[dict] = []
        samples = 0
        for step, batch in enumerate(self._val_loader):
            if self._mesh is not None:
                batch = self._place_batch(batch)
            with self._compile_probe.watch(
                    "eval", _profiling.shape_class(batch)):
                m = (self._jit_eval(self.params, self.model_state, batch)
                     if self._stateful
                     else self._jit_eval(self.params, batch))
            all_metrics.append({k: float(v) for k, v in m.items()})
            samples += _batch_size(batch)
            if num_steps is not None and step + 1 >= num_steps:
                break
        out = {k: float(np.mean([m[k] for m in all_metrics]))
               for k in (all_metrics[0] if all_metrics else {})}
        out["num_samples"] = samples
        return out

    # ------------------------------------------------------------------
    # checkpointing (reference: torch_trainer.py:543 save / :552 load)
    # ------------------------------------------------------------------

    def state_dict(self) -> dict:
        def to_np(x):
            if not isinstance(x, (jnp.ndarray, np.ndarray)):
                return x
            # Cross-process (multihost) shards aren't addressable locally:
            # gather them before converting (replicated arrays pass
            # np.asarray directly).
            if (isinstance(x, jax.Array) and not x.is_fully_addressable
                    and not x.is_fully_replicated):
                from jax.experimental import multihost_utils

                x = multihost_utils.process_allgather(x)
            return np.asarray(x)

        out = {
            "params": jax.tree.map(to_np, self.params),
            "model_state": (None if self.model_state is None
                            else jax.tree.map(to_np, self.model_state)),
            "epoch": self.epoch,
            "global_step": self.global_step,
        }
        if self._sharded:
            # no replicated opt blob exists in sharded mode — the state
            # carries THIS rank's shard (train/sharding.py dict format)
            out["sharded_update"] = True
            out["opt_shard"] = self.opt_shard_state()
        else:
            out["opt_state"] = jax.tree.map(to_np, self.opt_state)
        return out

    def load_state_dict(self, state: dict):
        self.params = jax.tree.map(jnp.asarray, state["params"])
        if state.get("model_state") is not None:
            self.model_state = jax.tree.map(jnp.asarray,
                                            state["model_state"])
        if self._sharded:
            if "opt_state" in state:
                raise ValueError(
                    "replicated checkpoint (full opt_state) cannot load "
                    "into a sharded-update trainer; re-save it sharded "
                    "or construct Trainer(sharded=False)")
            # rebuild the local param shard from the restored params;
            # the optimizer shard arrives separately (load_opt_shard,
            # possibly resharded) unless this state happens to carry a
            # geometry-matching shard (same-rank broadcast restore).
            flat, _ = ravel_pytree(self.params)
            self._param_shard = jnp.pad(
                flat, (0, self._pad_numel - self._numel)
            )[self._shard_lo:self._shard_hi]
            sh = state.get("opt_shard")
            if (sh is not None and sh["world_size"] == self.world_size
                    and sh["rank"] == self.world_rank):
                self.load_opt_shard(sh)
        else:
            if state.get("sharded_update"):
                raise ValueError(
                    "sharded checkpoint cannot load into an unsharded "
                    "trainer; construct Trainer(sharded=True) or load "
                    "the sharded manifest via Trainer.load()")
            self.opt_state = jax.tree.map(
                lambda ref, x: jnp.asarray(x) if isinstance(
                    x, np.ndarray) else x,
                self.opt_state, state["opt_state"])
        self.epoch = state["epoch"]
        self.global_step = state["global_step"]

    def opt_shard_state(self) -> dict:
        """This rank's optimizer-state shard in the train/sharding.py
        dict format (numpy leaves) — the unit of sharded checkpoints and
        elastic resharding."""
        leaves = [np.asarray(x) if isinstance(x, (jnp.ndarray, np.ndarray))
                  else x for x in jax.tree.leaves(self.opt_state)]
        return {"rank": self.world_rank, "world_size": self.world_size,
                "span": (self._shard_lo, self._shard_hi),
                "numel": self._numel, "pad_numel": self._pad_numel,
                "leaves": leaves}

    def load_opt_shard(self, shard: dict):
        """Install a shard produced by opt_shard_state (or
        sharding.reshard_opt_shards) — geometry must match this rank."""
        if (int(shard["world_size"]) != self.world_size
                or tuple(shard["span"]) != (self._shard_lo, self._shard_hi)
                or int(shard["numel"]) != self._numel):
            raise ValueError(
                f"optimizer shard geometry {shard['world_size']}x"
                f"{tuple(shard['span'])} (numel {shard['numel']}) does "
                f"not match rank {self.world_rank}: expected "
                f"{self.world_size}x({self._shard_lo}, {self._shard_hi}) "
                f"numel {self._numel}; reshard with "
                "train.sharding.reshard_opt_shards first")
        leaves = [jnp.asarray(x) if isinstance(x, np.ndarray) else x
                  for x in shard["leaves"]]
        self.opt_state = jax.tree.unflatten(self._opt_treedef, leaves)


def _batch_size(batch) -> int:
    leaves = jax.tree.leaves(batch)
    return int(leaves[0].shape[0]) if leaves and hasattr(
        leaves[0], "shape") and leaves[0].ndim else 0
