"""TorchTrainingOperator — the second-framework trainer path (reference:
python/ray/util/sgd/torch/training_operator.py:50 — this is the analog of
the reference's torch-native operator, so torch users can move over
without rewriting to jax; CPU torch in this image, gradient plane =
ray_tpu.collective HOST backend as one flat bucket).

Same Trainer-facing surface as the jax TrainingOperator (train_epoch /
validate / state_dict / load_state_dict), so `Trainer(TorchOpSubclass,
...)` just works, including elastic resize."""

from __future__ import annotations

import time
from typing import Iterable

import numpy as np


class TorchTrainingOperator:
    """Subclass, implement setup(), call self.register(...)."""

    def __init__(self, config: dict, world_rank: int, world_size: int,
                 group_name: str | None = None):
        self.config = config or {}
        self.world_rank = world_rank
        self.world_size = world_size
        self._group_name = group_name
        self._registered = False
        self._train_loader = None
        self._val_loader = None
        self.epoch = 0
        self.global_step = 0
        self.setup(self.config)
        if not self._registered:
            raise RuntimeError(
                "TorchTrainingOperator.setup() must call self.register(...)")

    # -- user surface ----------------------------------------------------

    def setup(self, config: dict):
        raise NotImplementedError

    def register(self, *, model, optimizer, criterion,
                 scheduler=None):
        """model: nn.Module; optimizer: torch optimizer over its params;
        criterion(output, target) -> loss; scheduler: optional LR sched
        stepped per epoch."""
        import torch

        self._registered = True
        self.model = model
        self.optimizer = optimizer
        self.criterion = criterion
        self.scheduler = scheduler
        self._torch = torch

    def register_data(self, *, train_loader: Iterable | None = None,
                      validation_loader: Iterable | None = None):
        self._train_loader = train_loader
        self._val_loader = validation_loader

    # -- gradient plane --------------------------------------------------

    def _allreduce_grads(self):
        """Average gradients across workers as ONE flat numpy bucket
        (reference: DistributedTorchRunner's DDP allreduce — here over the
        HOST collective group the Trainer created)."""
        if self.world_size == 1:
            return
        from ray_tpu.collective import collective as col

        torch = self._torch
        grads = [p.grad for p in self.model.parameters()
                 if p.grad is not None]
        if not grads:
            return
        flat = torch.cat([g.reshape(-1) for g in grads]).numpy()
        summed = col.allreduce(flat, group_name=self._group_name)
        flat = torch.from_numpy(np.asarray(summed) / self.world_size)
        off = 0
        for g in grads:
            n = g.numel()
            g.copy_(flat[off:off + n].reshape(g.shape))
            off += n

    # -- loops (same shape as the jax operator) --------------------------

    def train_batch(self, batch) -> dict:
        torch = self._torch
        features, target = batch
        features = torch.as_tensor(np.asarray(features))
        target = torch.as_tensor(np.asarray(target))
        self.model.train()
        self.optimizer.zero_grad()
        output = self.model(features)
        loss = self.criterion(output, target)
        loss.backward()
        self._allreduce_grads()
        self.optimizer.step()
        self.global_step += 1
        return {"train_loss": float(loss.detach())}

    def train_epoch(self, num_steps: int | None = None,
                    profile_dir: str | None = None) -> dict:
        if self._train_loader is None:
            raise RuntimeError("no train_loader registered")
        t0 = time.perf_counter()
        losses, samples = [], 0
        for step, batch in enumerate(self._train_loader):
            losses.append(self.train_batch(batch)["train_loss"])
            samples += len(batch[0])
            if num_steps is not None and step + 1 >= num_steps:
                break
        if self.scheduler is not None:
            self.scheduler.step()
        dt = time.perf_counter() - t0
        self.epoch += 1
        return {
            "epoch": self.epoch,
            "batch_count": len(losses),
            "num_samples": samples,
            "train_loss": float(np.mean(losses)) if losses else float("nan"),
            "last_train_loss": losses[-1] if losses else float("nan"),
            "samples_per_s": samples / dt if dt > 0 else 0.0,
        }

    def validate(self, num_steps: int | None = None) -> dict:
        if self._val_loader is None:
            raise RuntimeError("no validation_loader registered")
        torch = self._torch
        self.model.eval()
        losses, samples = [], 0
        with torch.no_grad():
            for step, (features, target) in enumerate(self._val_loader):
                features = torch.as_tensor(np.asarray(features))
                target = torch.as_tensor(np.asarray(target))
                loss = self.criterion(self.model(features), target)
                losses.append(float(loss))
                samples += len(features)
                if num_steps is not None and step + 1 >= num_steps:
                    break
        return {"val_loss": float(np.mean(losses)) if losses else
                float("nan"), "num_samples": samples}

    # -- checkpointing ---------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "model": {k: v.numpy() for k, v in
                      self.model.state_dict().items()},
            # Optimizer moments + scheduler counters must survive elastic
            # resize / save-load (reference: training_operator state_dict
            # includes them) or Adam momentum and the LR schedule reset.
            "optimizer": self.optimizer.state_dict(),
            "scheduler": (self.scheduler.state_dict()
                          if self.scheduler is not None else None),
            "epoch": self.epoch,
            "global_step": self.global_step,
        }

    def load_state_dict(self, state: dict):
        torch = self._torch
        self.model.load_state_dict(
            {k: torch.as_tensor(v) for k, v in state["model"].items()})
        if state.get("optimizer") is not None:
            self.optimizer.load_state_dict(state["optimizer"])
        if state.get("scheduler") is not None and self.scheduler is not None:
            self.scheduler.load_state_dict(state["scheduler"])
        self.epoch = state["epoch"]
        self.global_step = state["global_step"]
