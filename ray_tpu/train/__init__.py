"""ray_tpu.train — distributed SGD training (the RaySGD equivalent;
reference: python/ray/util/sgd/)."""

from ray_tpu.train.ingest import DatasetShard, IngestSpec, IngestStream
from ray_tpu.train.operator import TrainingOperator
from ray_tpu.train.torch_operator import TorchTrainingOperator
from ray_tpu.train.trainer import Trainer, TrainWorker

__all__ = ["DatasetShard", "IngestSpec", "IngestStream",
           "TorchTrainingOperator", "Trainer", "TrainWorker",
           "TrainingOperator"]
