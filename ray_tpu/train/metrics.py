"""Training-plane metrics (registered at import so the metrics-registry
drift gate — tests/test_observability.py — can hold ARCHITECTURE.md to
them).

step_s is the FULL step: input wait (ingest get / loader next) +
dispatch; ingest_wait_s isolates the input half, so "input-bound" reads
directly off the pair (a healthy double-buffered ingest pipeline keeps
ingest_wait_s p50 ~0 while step_s tracks compute). optim_shard_bytes is
the per-process optimizer-state footprint — 1/N of the replicated
figure once the weight update is sharded."""

from __future__ import annotations

from ray_tpu._private import stats

STEP_S = stats.Histogram(
    "train.step_s", stats.LATENCY_BOUNDARIES_S,
    "one training step wall time, input wait included (per worker)")

TOKENS_TOTAL = stats.Count(
    "train.tokens_total",
    "training examples consumed by dispatched steps (per worker; "
    "tokens/s = delta over the metrics history)")

INGEST_WAIT_S = stats.Histogram(
    "train.ingest_wait_s", stats.LATENCY_BOUNDARIES_S,
    "time the step loop blocked waiting for the next prefetched ingest "
    "batch (p50 ~0 = input fully overlapped with compute)")

OPT_SHARD_BYTES = stats.Gauge(
    "train.optim_shard_bytes",
    "bytes of optimizer state held by this worker (the local 1/N shard "
    "under the sharded weight update; the full replicated state "
    "otherwise)")
