"""Trainer — distributed data-parallel training over worker actors
(reference: python/ray/util/sgd/torch/torch_trainer.py:39 TorchTrainer —
train :365, fault-tolerant _resize_worker_group :328, save/load :543/:552;
worker group: worker_group.py:107 RemoteWorkerGroup, _setup_process_group
:153).

TPU-first differences: each worker is one actor per host running a jax
runtime; gradient allreduce goes through ray_tpu.collective (HOST TCP
backend across processes; within a host the jitted step shards over the
local device mesh, so ICI collectives come from XLA, not this layer)."""

from __future__ import annotations

import pickle
import time
import uuid

import cloudpickle

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import global_state
from ray_tpu.collective.collective import CollectiveActorMixin

# Sharded checkpoint manifest marker (Trainer.save/load): `path` holds a
# small index dict with this format tag; params + per-rank optimizer
# shards live in sibling files it names.
_SHARDED_CKPT_FORMAT = "ray_tpu.sharded_ckpt"


class TrainWorker(CollectiveActorMixin):
    """Actor wrapping a TrainingOperator (reference:
    distributed_torch_runner.py DistributedTorchRunner)."""

    def __init__(self, operator_cls_pickled: bytes, config: dict,
                 world_rank: int, world_size: int, group_name: str):
        self._operator_cls = pickle.loads(operator_cls_pickled)
        self._config = config
        self._rank = world_rank
        self._world_size = world_size
        self._group_name = group_name
        self.operator = None

    def setup_operator(self):
        if self._config.get("multihost"):
            # Join the group's global jax runtime BEFORE the operator's
            # first backend use; the operator then sees jax.devices() =
            # the whole group and builds a global mesh.
            from ray_tpu.parallel import multihost

            multihost.initialize(self._group_name, self._world_size,
                                 self._rank)
        self.operator = self._operator_cls(
            self._config, self._rank, self._world_size,
            group_name=self._group_name)
        return True

    def train_epoch(self, num_steps=None, profile_dir=None):
        return self.operator.train_epoch(num_steps, profile_dir=profile_dir)

    def validate(self, num_steps=None):
        return self.operator.validate(num_steps)

    def state_dict(self):
        return self.operator.state_dict()

    def load_state_dict(self, state):
        self.operator.load_state_dict(state)
        return True

    def read_counter(self, name: str) -> float:
        """Worker-process metric readback (wire A/B verification)."""
        from ray_tpu._private import stats

        snap = stats.snapshot().get(name)
        return float(snap["value"]) if snap else 0.0

    def read_metric(self, name: str):
        """Full metric snapshot (histograms/gauges, not just counter
        values) — bench + ingest-wait gate readback."""
        from ray_tpu._private import stats

        return stats.snapshot().get(name)

    def peak_rss(self) -> int:
        """Peak RSS of this worker process in bytes (bench readback)."""
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(ru if sys.platform == "darwin" else ru * 1024)

    def attach_ingest(self, dataset_actor, depth: int):
        """Register a streaming loader over this rank's DatasetShard
        actor: batches prefetch `depth` deep through the object plane
        while the step computes (validation loader untouched)."""
        from ray_tpu.train.ingest import IngestStream

        op = self.operator
        op.register_data(
            train_loader=IngestStream(dataset_actor, depth,
                                      lambda: op.epoch),
            validation_loader=op._val_loader)
        return True

    def opt_shard_state(self):
        return self.operator.opt_shard_state()

    def load_opt_shard(self, shard):
        self.operator.load_opt_shard(shard)
        return True

    def sync_state(self, src_rank: int = 0):
        """Collectively broadcast the full training state from src_rank
        over the group's data plane (shm segment / pipelined ring for
        large payloads) instead of the driver pushing world_size pickled
        copies. Every rank must call this."""
        import numpy as np

        from ray_tpu.collective import collective as col

        group = col._manager.get_group(self._group_name)
        if self._rank == src_rank:
            blob = np.frombuffer(
                pickle.dumps(self.operator.state_dict()), np.uint8)
            size = np.array([blob.size], np.int64)
        else:
            blob = None
            size = np.zeros(1, np.int64)
        size = group.broadcast(size, src_rank)  # geometry first: all
        if self._rank != src_rank:              # ranks pass equal shapes
            blob = np.empty(int(size[0]), np.uint8)
        out = group.broadcast(blob, src_rank)
        if self._rank != src_rank:
            self.operator.load_state_dict(pickle.loads(out.tobytes()))
        return True

    def shutdown(self):
        ray_tpu.exit_actor()


class Trainer:
    """Data-parallel trainer with elastic fault tolerance (reference:
    torch_trainer.py:39)."""

    def __init__(self, training_operator_cls, *, num_workers: int = 1,
                 config: dict | None = None,
                 resources_per_worker: dict | None = None,
                 use_tpu: bool = False,
                 backend: str = "host",
                 max_retries: int = 3,
                 collective_timeout: float = 30.0,
                 setup_timeout: float = 600.0,
                 quantize: str | None = None,
                 collective_transport: str = "auto",
                 placement_strategy: str | None = "ICI_RING",
                 sharded: bool = False,
                 mesh_mode: str | None = None,
                 ingest=None):
        """quantize="int8" makes the gradient-sync collective ride the
        block-scaled int8 wire format (EQuARX-style) on the tiers that
        have a wire — the collective DEVICE plane and the host TCP ring
        — cutting gradient bytes ~4x; state sync (broadcast) and
        node-local tiers stay exact. collective_transport pins the
        group's data plane to one tier (tests / wire A/Bs).

        placement_strategy (default "ICI_RING"): gang-reserve the
        workers through a placement group per generation so consecutive
        ranks land on ICI-neighboring nodes and the collective tier is
        DERIVED from the reservation (probe-free); clusters without
        topology coords degrade it to PACK at the GCS. None disables
        the reservation entirely (pre-topology scheduling).

        sharded=True turns on the ZeRO weight-update schedule
        (arXiv:2004.13336): reducescatter(grads) → optimizer update on
        the local 1/N shard of (params, opt state) → allgather(params).
        Optimizer memory per worker drops N×; with quantize="int8" the
        grad wire drops ~4× on top. Checkpoints become per-rank shard
        files behind an index manifest (save/load), and elastic resizes
        re-partition the optimizer shards to the new world size instead
        of re-broadcasting a replicated blob.

        mesh_mode="fsdp" builds the topology-derived ('data','fsdp')
        mesh (parallel.mesh.fsdp_mesh) inside each worker and shards
        params over the fsdp axis — single-worker or multihost groups
        only (host-backend data parallelism would not sync mesh-local
        shards).

        ingest: an ingest.IngestSpec — one DatasetShard actor per rank
        streaming prefetched batches through the object plane
        (train/ingest.py); replaces the operator's train_loader."""
        self._operator_cls = training_operator_cls
        self._config = dict(config or {})
        self._sharded = bool(sharded)
        if sharded:
            if mesh_mode is not None:
                raise ValueError(
                    "sharded=True (host-collective ZeRO) and mesh_mode "
                    "(XLA SPMD) are mutually exclusive update plans")
            if self._config.get("multihost"):
                raise ValueError(
                    "sharded=True uses the HOST collective plane; "
                    "multihost groups sync through XLA psum instead")
            self._config["sharded_update"] = True
        if mesh_mode is not None:
            if mesh_mode != "fsdp":
                raise ValueError(f"unknown mesh_mode {mesh_mode!r} "
                                 "(expected 'fsdp' or None)")
            if num_workers > 1 and not self._config.get("multihost"):
                raise ValueError(
                    "mesh_mode='fsdp' with multiple workers requires "
                    "config={'multihost': True} (a GLOBAL mesh); "
                    "host-backend workers would each build a private "
                    "mesh and never sync")
            self._config["mesh_mode"] = mesh_mode
        self._ingest = ingest
        self._ingest_actors: list = []
        self._quantize = quantize
        self._collective_transport = collective_transport
        self._placement_strategy = placement_strategy
        self._pg = None
        self._num_workers = num_workers
        self._resources = dict(resources_per_worker or {"CPU": 1})
        if use_tpu:
            self._resources.setdefault("TPU", 1)
        self._backend = backend
        self._max_retries = max_retries
        self._collective_timeout = collective_timeout
        # First-compile on a cold TPU (esp. through a tunnel) can exceed
        # two minutes; operator setup waits this long before declaring the
        # worker wedged.
        self._setup_timeout = setup_timeout
        self._generation = 0
        self._uid = uuid.uuid4().hex[:8]
        self.workers: list = []
        self._last_state: dict | None = None
        self._last_shards: list | None = None
        self._start_workers(num_workers)

    # ------------------------------------------------------------------
    # worker group lifecycle (reference: worker_group.py:107/:208)
    # ------------------------------------------------------------------

    def _gang_reserve(self, num_workers: int):
        """Reserve one bundle per worker under the trainer's placement
        strategy. Best-effort: a reservation that cannot be placed
        promptly (resources still draining from the previous
        generation, single saturated node) is dropped and the workers
        schedule exactly as before — the reservation is an
        optimization, never a new failure mode."""
        if self._placement_strategy is None or num_workers <= 1:
            return None
        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)

        try:
            pg = placement_group(
                [dict(self._resources) for _ in range(num_workers)],
                strategy=self._placement_strategy,
                name=f"sgd-{self._uid}-g{self._generation}")
        except Exception:
            return None
        try:
            # short bound: a placeable gang resolves in well under a
            # second; anything longer means the fleet is saturated and
            # the pre-topology queue-and-wait path is strictly better
            # than stalling __init__ here
            if pg.ready(timeout=3.0):
                return pg
        except Exception:
            pass
        # not placeable (or ready() errored): the registered group must
        # not linger — a later GCS retry would reserve a full worker-set
        # of resources nobody ever uses
        try:
            remove_placement_group(pg)
        except Exception:
            pass
        return None

    def _release_gang(self):
        if self._pg is None:
            return
        from ray_tpu.util.placement_group import remove_placement_group

        try:
            remove_placement_group(self._pg)
        except Exception:
            pass
        self._pg = None

    def _start_workers(self, num_workers: int):
        self._generation += 1
        group_name = f"sgd_{self._uid}_g{self._generation}"
        # cloudpickle: operator classes defined in __main__ or notebooks
        # serialize by value (stdlib pickle would import-by-reference and
        # fail on the worker).
        pickled = cloudpickle.dumps(self._operator_cls)
        self._pg = self._gang_reserve(num_workers)
        worker_cls = ray_tpu.remote(
            resources=dict(self._resources))(TrainWorker)
        self.workers = [
            worker_cls.options(
                placement_group=self._pg,
                placement_group_bundle_index=rank,
            ).remote(pickled, self._config, rank, num_workers, group_name)
            if self._pg is not None else
            worker_cls.remote(pickled, self._config, rank, num_workers,
                              group_name)
            for rank in range(num_workers)
        ]
        if num_workers > 1 and not self._config.get("multihost"):
            # multihost groups sync gradients through XLA collectives
            # inside the jitted step — no HOST group needed.
            from ray_tpu.collective import collective as col

            col.create_collective_group(
                self.workers, num_workers, list(range(num_workers)),
                backend=self._backend, group_name=group_name,
                timeout=self._collective_timeout,
                quantize=self._quantize,
                transport=self._collective_transport,
                # ICI_RING reservations carry the derived transport tier
                placement_group=self._pg)
        ray_tpu.get([w.setup_operator.remote() for w in self.workers],
                    timeout=self._setup_timeout)
        self._active_workers = num_workers
        self._start_ingest(num_workers)
        self._restore_state()

    def _start_ingest(self, num_workers: int):
        """One DatasetShard actor per rank; every generation re-shards
        the dataset over the CURRENT world size (elastic resize included
        — the survivors' shards re-cover the whole dataset)."""
        if self._ingest is None:
            return
        from ray_tpu._private.config import get_config
        from ray_tpu.train.ingest import DatasetShard

        spec = self._ingest
        depth = (spec.prefetch_depth if spec.prefetch_depth is not None
                 else get_config().train_ingest_prefetch_depth)
        shard_cls = ray_tpu.remote(
            resources=dict(spec.resources or {"CPU": 1}))(DatasetShard)
        fn_pickled = cloudpickle.dumps(spec.dataset_fn)
        self._ingest_actors = [
            shard_cls.remote(fn_pickled, rank, num_workers, self._config)
            for rank in range(num_workers)]
        ray_tpu.get([a.ping.remote() for a in self._ingest_actors],
                    timeout=self._setup_timeout)
        ray_tpu.get([w.attach_ingest.remote(a, depth)
                     for w, a in zip(self.workers, self._ingest_actors)],
                    timeout=self._setup_timeout)

    def _restore_state(self):
        """Re-install training state into a freshly started generation:
        params/progress broadcast once over the data plane, then (in
        sharded mode) per-rank optimizer shards — re-partitioned to the
        new world size when it changed, never a replicated blob."""
        num_workers = len(self.workers)
        if self._last_state is not None:
            if (num_workers > 1 and self._backend == "host"
                    and not self._config.get("multihost")):
                # Weight broadcast rides the collective data plane: the
                # driver ships ONE copy to rank 0; the group's shm/ring
                # transport fans it out node-locally (the elastic-resize
                # restore used to pickle the state num_workers times).
                ray_tpu.get(
                    self.workers[0].load_state_dict.remote(self._last_state),
                    timeout=self._setup_timeout)
                ray_tpu.get([w.sync_state.remote(0) for w in self.workers],
                            timeout=self._setup_timeout)
            else:
                ray_tpu.get([w.load_state_dict.remote(self._last_state)
                             for w in self.workers],
                            timeout=self._setup_timeout)
        if self._sharded and self._last_shards:
            shards = self._last_shards
            if len(shards) != num_workers:
                if _fp.ARMED:
                    _fp.fire_strict("train.reshard")
                from ray_tpu.train import sharding as _shardlib

                shards = _shardlib.reshard_opt_shards(shards, num_workers)
            ray_tpu.get([w.load_opt_shard.remote(s)
                         for w, s in zip(self.workers, shards)],
                        timeout=self._setup_timeout)

    def _kill_workers(self):
        for w in self.workers + self._ingest_actors:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        self._ingest_actors = []
        # release the gang's bundles BEFORE the next generation reserves
        # its own — a lingering hold would starve the new reservation
        self._release_gang()

    def _resize_worker_group(self):
        """Reference: torch_trainer.py:328 — shut the group down, restart
        at whatever size is currently schedulable, restore state."""
        broken, _ = self._gang_interrupted()
        if not broken and len(self.workers) == self._num_workers:
            # No-op resize: the gang is intact at full strength — keep
            # it. Restarting here would pay a redundant state broadcast
            # and drop every warm compile cache for nothing (the old
            # path did exactly that). Wedged-but-alive groups still
            # terminate: the caller's retry budget bounds us.
            return
        self._kill_workers()
        # Prefer the full size; shrink to what every resource type can hold.
        target = self._num_workers
        avail = ray_tpu.available_resources()
        for res, need in self._resources.items():
            if need > 0:
                target = min(target, int(avail.get(res, 0) // need))
        try:
            self._start_workers(max(1, target))
        except Exception:
            self._kill_workers()
            raise

    # ------------------------------------------------------------------
    # train/validate (reference: torch_trainer.py:365 train)
    # ------------------------------------------------------------------

    def _any_worker_dead(self) -> bool:
        return self._gang_interrupted()[0]

    def _gang_interrupted(self) -> tuple[bool, bool]:
        """-> (broken, planned). Broken: a worker is DEAD or parked on a
        DRAINING node (the node is leaving; its bundle can't follow).
        Planned: every interruption found is a drain — the next
        generation re-gangs from a fresh ICI_RING reservation placed
        around the hole (the GCS placement record carries the masked
        coords), with the collective tier re-derived from that record
        rather than probe rounds."""
        cw = global_state.require_core_worker()
        try:
            draining = {n["node_id"] for n in cw.cluster_info()["nodes"]
                        if n.get("state") not in (None, "ALIVE")}
        except Exception:
            draining = set()
        broken = False
        planned = True
        # ingest actors are part of the gang: a dead DatasetShard means
        # its rank's stream is gone, so the generation restarts (and
        # re-shards the dataset) exactly like a dead worker
        for w in self.workers + self._ingest_actors:
            info = cw.get_actor_info(w._actor_id.binary())
            if info is None or info.get("state") == "DEAD":
                broken = True
                if "drained" not in (info or {}).get("death_cause", ""):
                    planned = False
            elif info.get("node_id") in draining:
                broken = True
        return broken, broken and planned

    # planned departures re-gang for free, but boundedly so — a fleet
    # draining in a loop must not keep a train() call alive forever
    _MAX_PLANNED_REGANGS = 8

    def _run_with_retries(self, fn_name: str, num_steps, **kw):
        attempt = 0
        planned_regangs = 0
        while True:
            try:
                if not self.workers:
                    raise exc.WorkerCrashedError("worker group is empty")
                return ray_tpu.get(
                    [getattr(w, fn_name).remote(num_steps, **kw)
                     for w in self.workers],
                    timeout=600)
            except (exc.ActorDiedError, exc.WorkerCrashedError,
                    exc.GetTimeoutError):
                _, planned = self._gang_interrupted()
                if planned and planned_regangs < self._MAX_PLANNED_REGANGS:
                    # a drain took a worker: planned departure costs no
                    # retry budget (crash recovery stays bounded as before)
                    planned_regangs += 1
                elif attempt >= self._max_retries:
                    raise
                else:
                    attempt += 1
            except exc.TaskError:
                # A collective timing out inside a surviving worker usually
                # means a peer died mid-epoch; anything else is a user error.
                broken, planned = self._gang_interrupted()
                if not broken:
                    raise
                if planned and planned_regangs < self._MAX_PLANNED_REGANGS:
                    planned_regangs += 1
                elif attempt >= self._max_retries:
                    raise
                else:
                    attempt += 1
            time.sleep(0.5)
            try:
                self._resize_worker_group()
            except Exception:
                if attempt >= self._max_retries:
                    raise
                # group left empty; next attempt resizes again

    def train(self, num_steps: int | None = None,
              reduce_results: bool = True, profile_dir: str | None = None):
        kw = {"profile_dir": profile_dir} if profile_dir else {}
        results = self._run_with_retries("train_epoch", num_steps, **kw)
        self._last_state = ray_tpu.get(self.workers[0].state_dict.remote(),
                                       timeout=120)
        if self._sharded:
            # the epoch-boundary snapshot is params (rank 0; identical
            # everywhere) + ALL optimizer shards — the reshardable unit
            # the elastic restore path consumes
            self._last_state.pop("opt_shard", None)
            self._last_shards = ray_tpu.get(
                [w.opt_shard_state.remote() for w in self.workers],
                timeout=120)
        return _reduce(results) if reduce_results else results

    def validate(self, num_steps: int | None = None,
                 reduce_results: bool = True):
        results = self._run_with_retries("validate", num_steps)
        return _reduce(results) if reduce_results else results

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------

    def state_dict(self) -> dict:
        return ray_tpu.get(self.workers[0].state_dict.remote(), timeout=120)

    def load_state_dict(self, state: dict):
        self._last_state = state
        ray_tpu.get([w.load_state_dict.remote(state) for w in self.workers],
                    timeout=120)

    def save(self, path: str) -> str:
        """Unsharded: one pickle, as before. Sharded: each worker's
        optimizer shard returns through the object plane (plasma +, for
        cross-node workers, the bulk transfer channel) and the driver
        writes one file per shard plus a small index manifest at `path`
        — no full replicated optimizer blob ever assembles anywhere."""
        if not self._sharded:
            with open(path, "wb") as f:
                pickle.dump(self.state_dict(), f)
            return path
        import os

        state = ray_tpu.get(self.workers[0].state_dict.remote(),
                            timeout=120)
        state.pop("opt_shard", None)
        shard_refs = [w.opt_shard_state.remote() for w in self.workers]
        params_file = os.path.basename(path) + ".params"
        with open(path + ".params", "wb") as f:
            pickle.dump(state, f)
        spans, shard_files = [], []
        for i, ref in enumerate(shard_refs):
            sh = ray_tpu.get(ref, timeout=120)
            spans.append(tuple(sh["span"]))
            shard_files.append(os.path.basename(path) + f".shard{i}")
            with open(f"{path}.shard{i}", "wb") as f:
                pickle.dump(sh, f)
            numel, pad_numel = sh["numel"], sh["pad_numel"]
        manifest = {
            "format": _SHARDED_CKPT_FORMAT, "version": 1,
            "world_size": len(shard_files),
            "numel": numel, "pad_numel": pad_numel, "spans": spans,
            "epoch": state["epoch"], "global_step": state["global_step"],
            "params_file": params_file, "shard_files": shard_files,
        }
        with open(path, "wb") as f:
            pickle.dump(manifest, f)
        return path

    def load(self, path: str):
        """Loads either format; a sharded manifest reshards to the
        CURRENT world size on the way in (any saved N → any running N)."""
        with open(path, "rb") as f:
            blob = pickle.load(f)
        if not (isinstance(blob, dict)
                and blob.get("format") == _SHARDED_CKPT_FORMAT):
            self.load_state_dict(blob)
            return
        if not self._sharded:
            raise ValueError(
                f"{path} is a sharded checkpoint manifest; load it with "
                "Trainer(sharded=True)")
        import os

        base = os.path.dirname(os.path.abspath(path))
        with open(os.path.join(base, blob["params_file"]), "rb") as f:
            self._last_state = pickle.load(f)
        self._last_shards = []
        for sf in blob["shard_files"]:
            with open(os.path.join(base, sf), "rb") as f:
                self._last_shards.append(pickle.load(f))
        self._restore_state()

    @property
    def num_workers(self) -> int:
        return len(self.workers)

    def shutdown(self, force: bool = False):
        if force:
            self._kill_workers()
            return
        for w in self.workers:
            try:
                w.shutdown.remote()
            except Exception:
                pass
        self.workers = []
        for a in self._ingest_actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._ingest_actors = []
        self._release_gang()


def _reduce(results: list[dict]) -> dict:
    """Average worker metrics; sum sample counts/throughput."""
    if not results:
        return {}
    out = {}
    for k in results[0]:
        vals = [r[k] for r in results if k in r]
        if k in ("num_samples", "samples_per_s", "batch_count"):
            out[k] = type(vals[0])(sum(vals))
        elif isinstance(vals[0], (int, float)):
            out[k] = sum(vals) / len(vals)
        else:
            out[k] = vals[0]
    return out
