"""JAX-native model zoo: the workloads the reference trains/serves
(ResNet via RaySGD, BERT fine-tune, GPT-2 serving, ViT sweeps — BASELINE.json
configs), built functional + sharding-annotated for pjit meshes."""

from ray_tpu.models import (bert, moe_transformer, resnet, transformer,
                            vit)

__all__ = ["bert", "moe_transformer", "resnet", "transformer", "vit"]
