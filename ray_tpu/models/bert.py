"""BERT-style bidirectional encoder for classification fine-tuning.

Target of BASELINE.json configs[1] ("BERT-base GLUE fine-tune"). Reuses the
transformer blocks with causal=False; adds segment embeddings and a pooled
[CLS] classification head (the GLUE fine-tune shape).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.models import transformer as tfm


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    n_layers: int = 12
    n_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq: int = 512
    type_vocab: int = 2
    num_classes: int = 2
    dtype: Any = jnp.bfloat16

    def encoder_config(self) -> tfm.TransformerConfig:
        return tfm.TransformerConfig(
            vocab_size=self.vocab_size, n_layers=self.n_layers,
            n_heads=self.n_heads, d_model=self.d_model, d_ff=self.d_ff,
            max_seq=self.max_seq, dtype=self.dtype, causal=False)


def bert_base(num_classes=2) -> BertConfig:
    return BertConfig(num_classes=num_classes)


TINY = BertConfig(vocab_size=256, n_layers=2, n_heads=4, d_model=64,
                  d_ff=256, max_seq=128)


def init(key, cfg: BertConfig):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    d = cfg.d_model
    enc = tfm.init(k1, cfg.encoder_config())
    return {
        "encoder": enc,
        "wtt": jax.random.normal(k2, (cfg.type_vocab, d),
                                 jnp.float32) * 0.02,
        "pool_w": jax.random.normal(k3, (d, d), jnp.float32) * 0.02,
        "pool_b": jnp.zeros((d,)),
        "cls_w": jax.random.normal(k4, (d, cfg.num_classes),
                                   jnp.float32) * 0.02,
        "cls_b": jnp.zeros((cfg.num_classes,)),
    }


def logical_axes(cfg: BertConfig):
    return {
        "encoder": tfm.logical_axes(cfg.encoder_config()),
        "wtt": (None, "embed"),
        "pool_w": ("embed", "embed"),
        "pool_b": ("embed",),
        "cls_w": ("embed", "vocab"),
        "cls_b": ("vocab",),
    }


def apply(params, tokens, cfg: BertConfig, token_types=None, pad_mask=None):
    """tokens: [B, T] int32; pad_mask: [B, T] bool (True = real token) —
    required for padded GLUE batches so [CLS] never attends to padding.
    Returns (logits [B, classes], sequence [B, T, D])."""
    b, t = tokens.shape
    enc = params["encoder"]
    x = enc["wte"][tokens].astype(cfg.dtype)
    x = x + enc["wpe"][:t].astype(cfg.dtype)[None]
    if token_types is not None:
        x = x + params["wtt"][token_types].astype(cfg.dtype)

    x = tfm.encode(enc, x, cfg.encoder_config(), pad_mask)

    pooled = jnp.tanh(x[:, 0].astype(jnp.float32) @ params["pool_w"]
                      + params["pool_b"])
    logits = pooled @ params["cls_w"] + params["cls_b"]
    return logits, x


def loss_fn(params, tokens, labels, cfg: BertConfig, token_types=None,
            pad_mask=None):
    logits, _ = apply(params, tokens, cfg, token_types, pad_mask)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
